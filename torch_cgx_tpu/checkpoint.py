"""Checkpoint / resume.

The reference has **no** checkpointing (SURVEY.md §5.4) — it delegates to
user code, with one sharp edge the survey flags: the per-layer compression
registry and bucket/step counters live in in-process statics
(/root/reference/src/mpi_allreduce_operations.cc:35-36,257-285) and silently
vanish on restart, so a resumed run trains *uncompressed* until layers are
re-registered. This module closes that gap TPU-natively:

* :func:`save` / :func:`restore` — orbax-backed save of the training pytree
  (params / opt_state / step / anything jax.tree-shaped), with a pure-numpy
  fallback writer when orbax is unavailable.
* The **compression registry snapshot** rides inside every checkpoint: the
  numeric ``(bucket_idx, layer_idx) -> CompressionConfig`` registry, the
  per-bucket layer sizes, and the name-pattern registry are captured at save
  and re-installed at restore, so a resumed job compresses from step one.
* :func:`latest_step` / :func:`all_steps` for resume discovery.

Layout: ``<dir>/step_<N>/`` orbax (or ``.npz``) tree + ``cgx_registry.json``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from . import config as cfg
from .utils.logging import get_logger

log = get_logger()

_STEP_RE = re.compile(r"^step_(\d+)$")
_FALLBACK_FILE = "tree.npz"


# ---------------------------------------------------------------------------
# Registry snapshot (the reference's lost-on-restart statics, §5.4).
# ---------------------------------------------------------------------------


def _bucket_key_str(b) -> str:
    # Bucket keys are ints (public register_layer API) or hashable tuples
    # (the DDP hook's (namespace, index) keys); JSON round-trip via dumps.
    return json.dumps(b)


def _bucket_key_from(v):
    if isinstance(v, str):
        v = json.loads(v)
    return tuple(v) if isinstance(v, list) else v


def registry_snapshot() -> Dict[str, Any]:
    """JSON-able dump of all three per-layer config registries."""
    numeric = [
        {
            "bucket_idx": b,
            "layer_idx": li,
            "config": dataclasses.asdict(c),
        }
        for (b, li), c in cfg._layer_configs.items()
    ]
    sizes = {_bucket_key_str(b): s for b, s in cfg._layer_sizes.items()}
    patterns = [
        {"pattern": p, "config": dataclasses.asdict(c)}
        for p, c in cfg._pattern_configs.items()
    ]
    return {"numeric": numeric, "sizes": sizes, "patterns": patterns}


def restore_registry(snap: Dict[str, Any]) -> None:
    """Re-install a :func:`registry_snapshot` (clears current registries)."""
    cfg.clear_registry()
    for b, s in snap.get("sizes", {}).items():
        cfg._layer_sizes[_bucket_key_from(b)] = list(s)
    for item in snap.get("numeric", []):
        key = (_bucket_key_from(item["bucket_idx"]), item["layer_idx"])
        cfg._layer_configs[key] = cfg.CompressionConfig(**item["config"])
    for item in snap.get("patterns", []):
        cfg.set_layer_pattern_config(
            item["pattern"], cfg.CompressionConfig(**item["config"])
        )


# ---------------------------------------------------------------------------
# In-memory step snapshots (recovery supervisor rollback — PR 5).
#
# The on-disk save/restore above is the durable cross-restart path; the
# recovery supervisor needs something much cheaper: a host-side copy of the
# training state it can roll back to WITHIN the process after evicting a
# dead rank, without touching the filesystem on the hot path. Same contract
# as the durable form — the compression-registry snapshot rides along, so a
# rolled-back run replays *compressed* with the exact per-layer configs the
# pre-fault steps used (the §5.4 gap, applied to in-process recovery).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MemorySnapshot:
    """One rollback point: the step index, a host copy of the training
    pytree, and the compression-registry snapshot taken with it."""

    step: int
    tree: Any
    registry: Dict[str, Any]


def snapshot_in_memory(tree: Any, step: int) -> MemorySnapshot:
    """Host-copy ``tree`` (device arrays fetched; every leaf owns its
    memory, so later in-place training updates cannot mutate the
    snapshot) and capture the registry alongside."""
    host = jax.tree.map(lambda x: np.array(x, copy=True), tree)
    return MemorySnapshot(
        step=int(step), tree=host, registry=registry_snapshot()
    )


def restore_in_memory(snap: MemorySnapshot) -> Any:
    """Return a fresh copy of the snapshot's tree (the snapshot itself
    stays pristine for a second rollback) and re-install its registry."""
    restore_registry(snap.registry)
    return jax.tree.map(np.copy, snap.tree)


# ---------------------------------------------------------------------------
# Tree save/restore.
# ---------------------------------------------------------------------------


def _orbax():
    try:
        import orbax.checkpoint as ocp

        return ocp
    except Exception:
        return None


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{step}")


def _registry_path(directory: str, step: int) -> str:
    """Sibling of the step dir (not inside it: orbax owns that directory and
    a crash mid-save must not strand a tree-less registry inside it)."""
    return os.path.join(directory, f"step_{step}.registry.json")


def _flatten_for_npz(tree) -> Dict[str, np.ndarray]:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = jax.tree_util.keystr(path)
        flat[key] = np.asarray(leaf)
    return flat


def save(
    directory: str,
    tree: Any,
    step: int,
    *,
    include_registry: bool = True,
    force: bool = False,
) -> str:
    """Save a pytree checkpoint at ``<directory>/step_<step>``.

    Device arrays are fetched to host; the compression registry snapshot is
    stored alongside. Returns the checkpoint path.
    """
    path = _step_dir(directory, step)
    if os.path.exists(path) and not force:
        # Refuse BEFORE touching the registry file: a failed overwrite must
        # not pair the old tree with a new registry (silent config skew).
        raise FileExistsError(path)
    os.makedirs(directory, exist_ok=True)
    host_tree = jax.tree.map(np.asarray, tree)
    # Registry first, as a sibling file: a crash between the two writes then
    # leaves a registry without a checkpoint (harmless), never a checkpoint
    # without a registry (which would silently resume uncompressed — the
    # reference's §5.4 failure mode this module exists to close).
    if include_registry:
        with open(_registry_path(directory, step), "w") as f:
            json.dump(registry_snapshot(), f, indent=1)
    ocp = _orbax()
    if ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        ckptr.save(os.path.abspath(path), host_tree, force=force)
        ckptr.wait_until_finished()
    else:  # numpy fallback: flat keypath -> array archive
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, _FALLBACK_FILE),
                 **_flatten_for_npz(host_tree))
    log.info("saved checkpoint %s", path)
    return path


def restore(
    directory: str,
    step: Optional[int] = None,
    *,
    target: Any = None,
    with_registry: bool = True,
) -> Any:
    """Restore the pytree saved at ``step`` (default: latest). ``target``
    provides structure/dtypes (required for the numpy fallback; recommended
    with orbax). Re-installs the registry snapshot when present."""
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    ocp = _orbax()
    if ocp is not None:
        ckptr = ocp.StandardCheckpointer()
        if target is not None:
            host_target = jax.tree.map(np.asarray, target)
            tree = ckptr.restore(os.path.abspath(path), host_target)
        else:
            tree = ckptr.restore(os.path.abspath(path))
    else:
        if target is None:
            raise ValueError("numpy-fallback restore requires target=")
        data = np.load(os.path.join(path, _FALLBACK_FILE))
        leaves_paths, treedef = jax.tree_util.tree_flatten_with_path(target)
        leaves = [data[jax.tree_util.keystr(p)] for p, _ in leaves_paths]
        tree = jax.tree_util.tree_unflatten(treedef, leaves)
    if with_registry:
        reg_path = _registry_path(directory, step)
        if os.path.exists(reg_path):
            with open(reg_path) as f:
                restore_registry(json.load(f))
        else:
            log.warning(
                "checkpoint %s has no compression-registry snapshot; "
                "resumed training will run UNCOMPRESSED until layers are "
                "re-registered (pass with_registry=False to silence)", path
            )
    return tree


def all_steps(directory: str) -> List[int]:
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _STEP_RE.match(name)
        if m:
            out.append(int(m.group(1)))
    return sorted(out)


def latest_step(directory: str) -> Optional[int]:
    steps = all_steps(directory)
    return steps[-1] if steps else None
