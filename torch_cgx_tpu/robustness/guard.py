"""JAX-side fault staging: the ``nan_grad`` injector.

The wire-level faults live where the bytes move (``shm.py`` /
``backend.py``); gradient poisoning must instead be *staged into the
jitted train step at trace time* — the fault has to originate inside the
compiled SPMD program, upstream of quantization, exactly where a real
overflow/0-div NaN would. ``make_train_step`` consults
:func:`nan_grad_spec` when it builds and, when armed, threads
:func:`inject_nan` between the backward pass and the gradient sync. The
non-finite *defense* this exercises is
``parallel/grad_sync`` 's ``CGX_NONFINITE_GUARD``.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.logging import metrics
from . import faults


def nan_grad_spec() -> Optional[faults.FaultSpec]:
    """The armed ``nan_grad`` spec per the current env, else None. Read
    at trace/build time — jit caches bake the decision in, like every
    other traced config in this codebase."""
    inj = faults.get_injector()
    return inj.spec("nan_grad") if inj is not None else None


def inject_nan(
    grads,
    step_idx,
    axes: Sequence[str],
    spec: faults.FaultSpec,
):
    """Poison the first element of the first float leaf with NaN when the
    (traced) step index matches ``spec.step`` (and, with ``rank=``, only
    on that position along the first sync axis). A ``prob`` spec draws a
    per-step Bernoulli from a stream seeded by ``CGX_FAULTS_SEED`` folded
    with the step index — deterministic replay, jit-compatible. Bit-exact
    identity on every non-matching step: the write is a ``where``-gated
    ``.at[].set``, no arithmetic touches the gradient."""
    import os

    leaves, treedef = jax.tree_util.tree_flatten(grads)
    idx = next(
        (
            i
            for i, l in enumerate(leaves)
            if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
        ),
        None,
    )
    if idx is None:
        return grads
    flag = (
        jnp.asarray(True)
        if spec.step is None
        else jnp.asarray(step_idx) == spec.step
    )
    if spec.prob is not None:
        seed = int(os.environ.get(faults.FAULTS_SEED_ENV, "0") or 0)
        key = jax.random.fold_in(
            jax.random.PRNGKey(seed), jnp.asarray(step_idx)
        )
        flag = jnp.logical_and(flag, jax.random.bernoulli(key, spec.prob))
    if spec.rank is not None and axes:
        flag = jnp.logical_and(flag, lax.axis_index(axes[0]) == spec.rank)
    leaf = leaves[idx]
    flat = leaf.reshape(-1)
    flat = flat.at[0].set(
        jnp.where(flag, jnp.asarray(jnp.nan, flat.dtype), flat[0])
    )
    leaves[idx] = flat.reshape(leaf.shape)
    metrics.add("cgx.faults.nan_grad_staged")  # trace-time: armed, not fired
    return jax.tree_util.tree_unflatten(treedef, leaves)
