"""Checkpoint-free elastic membership: rank join without a restart.

The PR 2 supervisor made the group *shrinkable*: an evicted rank is cut
out in place and the survivors keep training. This module adds the other
direction — a fresh process joins a RUNNING group and enters the step
loop bit-identical to a rank that was never gone, with zero checkpoint
files on disk. The pieces:

* **Join rendezvous** — the joiner announces itself through a store
  intent counter; survivors notice at a step boundary, agree on a
  step-synchronized join point two steps out (first seer claims the
  trigger slot atomically — no leader, exactly the rendezvous claim
  discipline), then run a vote → claim → decision round under
  ``cgxjoin/g<N>/`` mirroring :mod:`.rendezvous`. The decision carries
  the new member set, the joiners' assigned global ranks, the
  load-ranked donor set, every member's host fingerprint, and the step
  the joiner will resume at. A two-phase **outcome claim** closes the
  round: survivors wait for the joiners' admit acks; whoever first sees
  the acks complete (or the deadline expire) claims the outcome slot and
  publishes ``commit`` or ``abort`` — every side follows the published
  outcome, so a survivor timing out while another sees the ack land can
  never split the group.

* **Snapshot pages** — on commit the donors ship the live in-memory
  training state (params, optimizer state, EF residuals, the async
  outer-plane anchor — whatever rides the user's state tree) as
  crc32-framed pages over the PR 15 counter-stream transport (the new
  ``P_RAW``/``P_PAGE`` frame kinds). The default is RAW pages: the
  joiner's state is byte-for-byte the donors'. Registering a
  ``param_page`` wire edge makes the join wire lossy, in which case
  every SURVIVOR snaps its own state to the codec grid at the commit
  point (encode + decode locally through the same deterministic codec),
  so all members land on identical bytes again. A corrupt page frame is
  re-requested from its donor (header identity via
  ``transport.peek_header``), bounded — never a wedge.

* **Membership deltas** — survivors call
  :meth:`ProcessGroupCGX.reconfigure` with the grown member list plus
  the joiners' host info; the joiner constructs its group directly at
  the bumped generation (``peer_info=`` skips the store exchange a
  mid-step group would never answer). Trace caches, plans, and the
  async plane are invalidated through the same cascade an eviction
  runs.

Every wait on the join path — the joiner's admit poll, the survivors'
ack wait, page staleness, the final ready barrier — is bounded by the
single ``CGX_JOIN_TIMEOUT_MS`` knob. A joiner that times out aborts
ALONE (:class:`JoinAbortedError`); survivors are never stalled longer
than the bound. With ``CGX_ELASTIC`` unset the whole plane is inert:
the step-boundary hook returns immediately and no store key is touched.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config as cfg
from ..observability import flightrec
from ..observability import health as health_mod
from ..observability import timeline
from ..serving import transport as wire
from ..utils.logging import get_logger, metrics
from . import rendezvous as rdz
from .errors import BridgeTimeoutError, JoinAbortedError

log = get_logger()

JOIN_PREFIX = "cgxjoin"

# Page geometry: 1 MiB of wire bytes per frame — large enough that the
# store round-trips amortize, small enough that a corruption re-request
# re-ships a bounded sliver of the snapshot.
PAGE_BYTES = 1 << 20

# Re-requests per page before the joiner declares the wire hopeless.
MAX_PAGE_REREQS = 3

# Grace added to a comeback notice's own delay before the reserved slot
# expires (mirrors MembershipPolicy.REJOIN_SLACK_S).
REJOIN_GRACE_S = 60.0

_POLL_S = 0.05


# ---------------------------------------------------------------------------
# Cross-generation keys. Everything under cgxelastic/ deliberately lives
# OUTSIDE the g<N>/ namespace: a joiner announcing itself does not know
# the group's generation yet, and a comeback notice must survive the very
# generation bump it causes. The per-generation protocol keys all live
# under cgxjoin/g<N>/ and are reaped with the rendezvous's.
# ---------------------------------------------------------------------------


def _intent_counter_key() -> str:
    return "cgxelastic/intents/n"


def _intent_key(k: int) -> str:
    return f"cgxelastic/intents/{k}"


def _admit_key(k: int) -> str:
    return f"cgxelastic/admit/{k}"


def _comeback_key(global_rank: int) -> str:
    return f"cgxelastic/comeback/{global_rank}"


def _trigger_key(consumed: int, generation: int) -> str:
    # Keyed by (intent watermark, target generation): a shrink landing
    # between trigger and join point moves every survivor to a new
    # generation together, so a trigger claimed for the dead generation
    # is simply never adopted again (the stale key is a bounded leak).
    return f"cgxelastic/trig/{consumed}g{generation}"


def _stream_name(generation: int, joiner: int, donor_idx: int) -> str:
    return f"join-g{generation}-r{joiner}-d{donor_idx}"


def _my_host_info() -> str:
    from ..torch_backend import shm as shm_mod

    return f"{shm_mod.host_fingerprint()}|{os.getpid()}"


# ---------------------------------------------------------------------------
# The join decision record.
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class JoinDecision:
    """The converged outcome of one join rendezvous. All ranks GLOBAL.

    ``step`` is the step index every survivor shipped its state at and
    the joiner resumes from; ``step == -1`` marks a claim winner that
    could not admit anyone (no live intents, or the survivors' voted
    steps disagreed — a should-never-happen drift) — survivors treat it
    as an immediate abort and the joiner, receiving no admit record,
    times out alone. ``bits == 0`` means raw (lossless) snapshot pages.
    """

    generation: int
    members: Tuple[int, ...]
    survivors: Tuple[int, ...]
    joiners: Tuple[int, ...]
    donors: Tuple[int, ...]
    hosts: Dict[int, str]
    intents: Dict[int, int]  # joiner global rank -> intent index
    intents_n: int
    step: int
    bits: int
    bucket: int
    trigger_key: str

    def to_json(self) -> str:
        return json.dumps(
            {
                "generation": self.generation,
                "members": list(self.members),
                "survivors": list(self.survivors),
                "joiners": list(self.joiners),
                "donors": list(self.donors),
                "hosts": {str(g): v for g, v in self.hosts.items()},
                "intents": {str(g): k for g, k in self.intents.items()},
                "intents_n": self.intents_n,
                "step": self.step,
                "bits": self.bits,
                "bucket": self.bucket,
                "trigger_key": self.trigger_key,
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, raw: str) -> "JoinDecision":
        d = json.loads(raw)
        return cls(
            generation=int(d["generation"]),
            members=tuple(int(g) for g in d["members"]),
            survivors=tuple(int(g) for g in d["survivors"]),
            joiners=tuple(int(g) for g in d["joiners"]),
            donors=tuple(int(g) for g in d["donors"]),
            hosts={int(g): str(v) for g, v in d["hosts"].items()},
            intents={int(g): int(k) for g, k in d["intents"].items()},
            intents_n=int(d["intents_n"]),
            step=int(d["step"]),
            bits=int(d["bits"]),
            bucket=int(d["bucket"]),
            trigger_key=str(d["trigger_key"]),
        )


def _param_page_config() -> Tuple[int, int]:
    """(bits, bucket) the snapshot pages ship under. (0, 0) = raw — the
    default, because ``param_page`` is excluded from the CGX_WIRE_BITS
    env fallback (wire/edges.py): only an explicitly registered edge may
    trade the joiner's bit-identity for wire bytes."""
    from ..wire import edges as wire_edges

    ec = wire_edges.resolve_edge(wire_edges.EDGE_PARAM_PAGE, "state")
    if ec is None or ec.cc.bits <= 0:
        return 0, 0
    return int(ec.cc.bits), int(ec.cc.bucket_size or 512)


# ---------------------------------------------------------------------------
# Comeback notices (the preempt fault's survivor-visible half).
# ---------------------------------------------------------------------------


def publish_comeback(store, global_rank: int, delay_s: float) -> None:
    """A rank about to die with notice (platform preemption) records that
    it intends to return in ``delay_s`` seconds. The supervisor's rejoin
    rung reads this to prefer reserving the rank over forgetting it."""
    rec = {
        "rank": int(global_rank),
        "delay_s": float(delay_s),
        "ts": time.time(),
    }
    # cgx-analysis: allow(generation-hygiene) — the comeback notice must survive the generation bump the death it announces will cause; keyed by global rank, overwritten per notice
    rdz._publish(store, _comeback_key(global_rank), json.dumps(rec, sort_keys=True))
    metrics.add("cgx.elastic.comebacks")
    log.warning(
        "elastic: rank %d published a comeback notice (back in ~%.1fs)",
        global_rank, delay_s,
    )


def fresh_comeback(store, global_rank: int) -> Optional[dict]:
    """The rank's comeback record, if one exists and has not expired
    (its own promised delay plus :data:`REJOIN_GRACE_S`)."""
    key = _comeback_key(global_rank)
    if not rdz._flag_set(store, key):
        return None
    try:
        rec = json.loads(rdz._read(store, key))
    except Exception as e:
        log.warning("elastic: comeback record for %d unreadable: %s",
                    global_rank, e)
        return None
    age = time.time() - float(rec.get("ts", 0.0))
    if age > float(rec.get("delay_s", 0.0)) + REJOIN_GRACE_S:
        return None
    return rec


# ---------------------------------------------------------------------------
# Snapshot paging: state tree <-> wire bytes.
# ---------------------------------------------------------------------------


def _leaf_wire(arr: np.ndarray, bits: int, bucket: int) -> Tuple[int, bytes]:
    """(frame kind, wire bytes) for one state leaf. Only float32 leaves
    are ever codec-compressed — integer leaves (step counters, rng keys)
    must arrive exact regardless of the edge config."""
    if bits and arr.dtype == np.float32 and arr.size:
        from ..ops import codec_host

        q = codec_host.quantize(
            np.ascontiguousarray(arr.reshape(-1)), bits, bucket
        )
        return wire.P_PAGE, q.to_bytes().tobytes()
    return wire.P_RAW, np.ascontiguousarray(arr).tobytes()


def _decode_leaf(desc: dict, buf: bytes, bits: int, bucket: int) -> np.ndarray:
    shape = tuple(int(s) for s in desc["shape"])
    dtype = np.dtype(str(desc["dtype"]))
    numel = int(desc["numel"])
    if int(desc["kind"]) == wire.P_PAGE:
        from ..ops import codec_host

        q = codec_host.from_bytes(
            np.frombuffer(buf, np.uint8), numel, bits, bucket, dtype
        )
        return codec_host.dequantize(q).reshape(shape).astype(
            dtype, copy=False
        )
    if numel == 0:
        return np.zeros(shape, dtype=dtype)
    arr = np.frombuffer(buf[: numel * dtype.itemsize], dtype=dtype)
    return arr.reshape(shape).copy()


def _encode_state(state: Any, bits: int, bucket: int):
    """Flatten ``state`` and encode every leaf: (wires, descs). All
    donors hold bit-identical state (the group invariant the supervisor
    replay machinery maintains) and the codec is deterministic, so every
    donor produces the SAME bytes per leaf — which is what lets the page
    stripes interleave across donors."""
    import jax

    leaves, _ = jax.tree_util.tree_flatten(state)
    wires: List[bytes] = []
    descs: List[dict] = []
    for leaf in leaves:
        arr = np.asarray(leaf)
        kind, wb = _leaf_wire(arr, bits, bucket)
        pages = max(1, -(-len(wb) // PAGE_BYTES))
        wires.append(wb)
        descs.append({
            "kind": int(kind),
            "dtype": str(arr.dtype),
            "shape": [int(s) for s in arr.shape],
            "numel": int(arr.size),
            "bytes": len(wb),
            "pages": int(pages),
        })
    return wires, descs


def snap_state_to_grid(state: Any, bits: int, bucket: int) -> Any:
    """Encode + decode every float32 leaf through the join codec
    locally. When the join wire is lossy, every SURVIVOR runs this at
    the commit point so its state lands on the same codec grid the
    joiner's decoded pages land on — cross-rank bit-identity is restored
    without shipping a byte between survivors."""
    if not bits:
        return state
    import jax

    def snap(x):
        arr = np.asarray(x)
        if arr.dtype == np.float32 and arr.size:
            from ..ops import codec_host

            q = codec_host.quantize(
                np.ascontiguousarray(arr.reshape(-1)), bits, bucket
            )
            return codec_host.dequantize(q).reshape(arr.shape).astype(
                np.float32, copy=False
            )
        return x

    return jax.tree_util.tree_map(snap, state)


class _SnapshotDonor:
    """One donor's shipping job for one joiner: frame and post this
    donor's page stripe (global page ordinal mod n_donors), then serve
    bounded re-requests until the joiner's done flag or the deadline."""

    def __init__(
        self,
        store,
        stream: str,
        wires: List[bytes],
        descs: List[dict],
        *,
        meta: Optional[dict],
        donor_idx: int,
        n_donors: int,
        bits: int,
        bucket: int,
        deadline: float,
        injector=None,
    ):
        # PR 20 (CGX_TRANSPORT=socket): snapshot pages ride the socket
        # plane toward the joiner's receive endpoint (derived from the
        # stream's join-g<N>-r<J> base — all of a joiner's donor streams
        # share it). Re-request control keys stay on the plain store:
        # their reader is per-donor, not the stream's peer set.
        store = wire.maybe_socket_store(
            store, endpoint=f"jtx/{stream}",
            peers=(f"jrx/{stream.rsplit('-d', 1)[0]}",),
            prefixes=(f"cgxkv/{stream}/",), exclude=("/rereq/",),
        )
        self._store = store
        self._stream = stream
        self._wires = wires
        self._descs = descs
        self._meta = meta
        self._donor_idx = donor_idx
        self._n_donors = n_donors
        self._bits = bits
        self._bucket = bucket
        self._deadline = deadline
        self._injector = injector
        self._sender = wire.KvPageSender(store, stream)
        self._rereq_seen = 0
        self._thread = threading.Thread(
            target=self._run, name=f"cgx-elastic-donor-{stream}", daemon=True
        )

    def start(self) -> None:
        self._thread.start()

    def done(self) -> bool:
        return not self._thread.is_alive()

    def join(self, timeout: Optional[float] = None) -> None:
        self._thread.join(timeout=timeout)

    # -- shipping ---------------------------------------------------------

    def _frame(self, leaf: int, page: int) -> bytes:
        d = self._descs[leaf]
        payload = self._wires[leaf][page * PAGE_BYTES:(page + 1) * PAGE_BYTES]
        return wire.frame_page(
            leaf, int(d["kind"]), page, self._bits, self._bucket,
            int(d["numel"]), payload, checksum=True,
        )

    def _run(self) -> None:
        try:
            if self._meta is not None:
                self._sender._post(wire.meta_frame(self._meta))
            ordinal = 0
            shipped = 0
            for li, d in enumerate(self._descs):
                for p in range(int(d["pages"])):
                    if ordinal % self._n_donors == self._donor_idx:
                        buf = self._frame(li, p)
                        if self._injector is not None:
                            # corrupt_join_page fires AFTER the crc was
                            # computed — the flip reaches the wire.
                            hdr = wire._FRAME.size
                            buf = buf[:hdr] + self._injector.\
                                corrupt_join_payload(buf[hdr:], ordinal)
                        self._sender._post(buf)
                        shipped += 1
                    ordinal += 1
            metrics.add("cgx.elastic.pages_shipped", float(shipped))
            self._serve_rereqs()
        except Exception as e:
            log.warning("elastic donor %s: shipping failed: %s",
                        self._stream, e)
            flightrec.record_failure(e, op="elastic.donate",
                                     key=self._stream)
        finally:
            self._sender.stop()

    def _serve_rereqs(self) -> None:
        """Poll the joiner's re-request counter until it flags the
        stream done (or the join deadline passes). Re-ships post CLEAN
        frames — the injector's page ordinal already fired once."""
        base = f"cgxkv/{self._stream}"
        while time.monotonic() < self._deadline:
            try:
                if int(self._store.add(f"{base}/done", 0)) > 0:
                    return
                n = int(self._store.add(f"{base}/rereq/n", 0))
            except Exception as e:
                log.warning("elastic donor %s: rereq poll failed: %s",
                            self._stream, e)
                return
            for i in range(self._rereq_seen + 1, n + 1):
                try:
                    req = json.loads(rdz._read(self._store,
                                               f"{base}/rereq/{i}"))
                    self._sender._post(
                        self._frame(int(req["leaf"]), int(req["page"]))
                    )
                    metrics.add("cgx.elastic.page_reships")
                except Exception as e:
                    log.warning(
                        "elastic donor %s: rereq %d unserveable: %s",
                        self._stream, i, e,
                    )
            self._rereq_seen = n
            time.sleep(_POLL_S)
        log.warning(
            "elastic donor %s: deadline passed with the stream not "
            "flagged done", self._stream,
        )


class _SnapshotReceiver:
    """Joiner side: drain every donor stream, re-request corrupt pages,
    assemble per-leaf wire buffers. Completion comes from the META
    frame's leaf descriptors; every wait is bounded by the deadline."""

    def __init__(self, store, streams: Sequence[str], deadline: float):
        streams = list(streams)
        if streams:
            # Joiner endpoint (PR 20): one socket mailbox for every donor
            # stream of this join; re-requests stay on the plain store
            # (the donors poll them with bounded counter reads there).
            store = wire.maybe_socket_store(
                store,
                endpoint=f"jrx/{streams[0].rsplit('-d', 1)[0]}",
                peers=(),
                prefixes=tuple(f"cgxkv/{s}/" for s in streams),
                exclude=("/rereq/",),
            )
        self._store = store
        self._streams = list(streams)
        self._deadline = deadline
        self._consumed = {s: 0 for s in self._streams}
        self._rereq_sent = {s: 0 for s in self._streams}
        self._rereq_count: Dict[Tuple[int, int], int] = {}
        self._meta: Optional[dict] = None
        self._bufs: List[bytearray] = []
        self._got: set = set()
        self._need = -1
        self._stash: List[wire.PageFrame] = []

    def receive(self) -> Tuple[dict, List[bytes]]:
        while True:
            progressed = False
            for si, s in enumerate(self._streams):
                progressed |= self._drain(si, s)
            if self._meta is not None and len(self._got) >= self._need:
                for s in self._streams:
                    # cgx-analysis: allow(generation-hygiene) — the stream name carries the generation in-band (join-g<N>-r<J>-d<D>)
                    self._store.add(f"cgxkv/{s}/done", 1)
                metrics.add("cgx.elastic.pages_received",
                            float(len(self._got)))
                return self._meta, [bytes(b) for b in self._bufs]
            if time.monotonic() > self._deadline:
                metrics.add("cgx.elastic.join_aborts")
                raise JoinAbortedError(
                    f"elastic join: snapshot transfer incomplete at the "
                    f"deadline ({len(self._got)}/{self._need} pages, meta "
                    f"{'seen' if self._meta else 'missing'}) — donors "
                    "died or CGX_JOIN_TIMEOUT_MS is too tight for the "
                    "state size"
                )
            if not progressed:
                time.sleep(_POLL_S)

    # -- internals --------------------------------------------------------

    def _drain(self, si: int, stream: str) -> bool:
        try:
            n = int(self._store.add(f"cgxkv/{stream}/n", 0))
        except Exception as e:
            log.warning("elastic join: counter read for %s failed: %s",
                        stream, e)
            return False
        progressed = False
        for seq in range(self._consumed[stream] + 1, n + 1):
            key = f"cgxkv/{stream}/{seq}"
            try:
                buf = bytes(self._store.get(key))
            except Exception as e:
                log.warning("elastic join: fetch %s failed: %s", key, e)
                return progressed
            self._consumed[stream] = seq
            rdz._delete(self._store, key)
            progressed = True
            try:
                frame = wire.unframe_page(buf)
            except Exception:
                self._rerequest(stream, buf)
                continue
            if frame.is_meta:
                self._on_meta(json.loads(frame.payload.decode()))
            else:
                self._place(frame)
        return progressed

    def _on_meta(self, meta: dict) -> None:
        self._meta = meta
        descs = meta["leaves"]
        self._bufs = [bytearray(int(d["bytes"])) for d in descs]
        self._need = sum(int(d["pages"]) for d in descs)
        for frame in self._stash:
            self._place(frame)
        self._stash = []

    def _place(self, frame: wire.PageFrame) -> None:
        if self._meta is None:
            self._stash.append(frame)
            return
        li, p = frame.layer, frame.page_idx
        if (li, p) in self._got or li >= len(self._bufs):
            return  # duplicate (late original after a re-request) or junk
        off = p * PAGE_BYTES
        self._bufs[li][off:off + len(frame.payload)] = frame.payload
        self._got.add((li, p))

    def _rerequest(self, stream: str, buf: bytes) -> None:
        """A frame failed its checksum: name the page from the unverified
        header and ask its donor to re-ship — the corrupt-page contract
        (re-request, never wedge, never silently accept)."""
        try:
            hdr = wire.peek_header(buf)
        except Exception as e:
            raise JoinAbortedError(
                "elastic join: received a frame too mangled to even name "
                f"the page to re-request ({e})"
            )
        pk = (hdr.layer, hdr.page_idx)
        self._rereq_count[pk] = self._rereq_count.get(pk, 0) + 1
        if self._rereq_count[pk] > MAX_PAGE_REREQS:
            metrics.add("cgx.elastic.join_aborts")
            raise JoinAbortedError(
                f"elastic join: page (leaf {hdr.layer}, page "
                f"{hdr.page_idx}) failed its checksum "
                f"{self._rereq_count[pk]} times — the join wire is "
                "persistently corrupt"
            )
        i = self._rereq_sent[stream] + 1
        self._rereq_sent[stream] = i
        # Publish-after-write, single writer: payload key first, counter
        # after, so the donor's poll never reads a half-posted request.
        # cgx-analysis: allow(generation-hygiene) — the stream name carries the generation in-band (join-g<N>-r<J>-d<D>)
        self._store.set(
            f"cgxkv/{stream}/rereq/{i}",
            json.dumps({"leaf": hdr.layer, "page": hdr.page_idx}).encode(),
        )
        # cgx-analysis: allow(generation-hygiene) — the stream name carries the generation in-band (join-g<N>-r<J>-d<D>)
        self._store.add(f"cgxkv/{stream}/rereq/n", 1)
        metrics.add("cgx.elastic.page_rereqs")
        log.warning(
            "elastic join: page (leaf %d, page %d) corrupt on %s — "
            "re-requested (%d/%d)", hdr.layer, hdr.page_idx, stream,
            self._rereq_count[pk], MAX_PAGE_REREQS,
        )


# ---------------------------------------------------------------------------
# Joiner entry.
# ---------------------------------------------------------------------------


def announce_join(store, *, global_rank: int = -1,
                  host: Optional[str] = None) -> int:
    """Post a join intent; returns the intent index the admit record
    will be published under. ``global_rank`` is the identity the joiner
    wants back (a respawned preempted rank reuses its original); -1
    requests fresh capacity and the decision claim winner assigns the
    next free global rank."""
    rec = {
        "rank": int(global_rank),
        "host": host or _my_host_info(),
        "ts": time.time(),
    }
    # The counter IS the index allocator; the payload flag (written
    # after the payload) is what survivors trust, so the early bump is
    # safe — an intent whose flag never lands is skipped at decision
    # time and its joiner times out and re-announces.
    # cgx-analysis: allow(generation-hygiene) — join intents are PRE-generation by nature: the joiner cannot know the group's generation before being admitted to one
    k = int(store.add(_intent_counter_key(), 1))
    # cgx-analysis: allow(generation-hygiene) — same pre-generation intent record as the counter above
    rdz._publish(store, _intent_key(k), json.dumps(rec, sort_keys=True))
    metrics.add("cgx.elastic.join_intents")
    log.info("elastic: join intent %d posted (rank %d)", k, global_rank)
    return k


@dataclasses.dataclass
class JoinResult:
    """What :func:`join` hands back: a live group at the bumped
    generation plus the received state, positioned at ``step``. Pass
    ``decision.intents_n`` as the coordinator's ``consumed`` watermark
    when wiring the joiner's own :class:`ElasticCoordinator`."""

    group: Any
    state: Any
    step: int
    generation: int
    members: List[int]
    decision: JoinDecision


def join(
    store,
    skeleton: Any,
    *,
    global_rank: int = -1,
    timeout_s: Optional[float] = None,
) -> JoinResult:
    """Boot into a running group with no checkpoint: announce, wait for
    the admit record, ack, follow the published outcome, receive the
    snapshot pages, and construct the group at the bumped generation.

    ``skeleton`` is a state tree with the right STRUCTURE (the caller
    builds it from model code — shapes/dtypes are validated against the
    donors' leaf descriptors, values are ignored). Raises
    :class:`JoinAbortedError` on any bounded wait expiring — the joiner
    aborts alone; survivors carry on untouched and a later re-announce
    starts a fresh intent."""
    t0 = time.perf_counter()
    timeout = (timeout_s if timeout_s is not None
               else cfg.join_timeout_ms() / 1000.0)
    deadline = time.monotonic() + timeout
    k = announce_join(store, global_rank=global_rank)
    akey = _admit_key(k)
    while not rdz._flag_set(store, akey):
        if time.monotonic() > deadline:
            metrics.add("cgx.elastic.join_aborts")
            raise JoinAbortedError(
                f"elastic join: intent {k} was never admitted within "
                f"{timeout:.1f}s — no survivor noticed (CGX_ELASTIC off "
                "on the group?), the group aborted the grow, or there is "
                "no group"
            )
        time.sleep(_POLL_S)
    admit = json.loads(rdz._read(store, akey))
    decision = JoinDecision.from_json(json.dumps(admit))
    me = int(admit["you"])
    N = decision.generation
    jbase = f"{JOIN_PREFIX}/g{N}"
    store.add(f"{jbase}/jack", 1)
    okey = f"{jbase}/outcome"
    while not rdz._flag_set(store, okey):
        if time.monotonic() > deadline:
            metrics.add("cgx.elastic.join_aborts")
            raise JoinAbortedError(
                f"elastic join: admitted as rank {me} at generation {N} "
                f"but no outcome was published within {timeout:.1f}s"
            )
        time.sleep(_POLL_S)
    if rdz._read(store, okey) != "commit":
        metrics.add("cgx.elastic.join_aborts")
        raise JoinAbortedError(
            f"elastic join: the survivors aborted the generation-{N} grow "
            "(a joiner's ack never landed within the bound)"
        )
    streams = [_stream_name(N, me, di) for di in range(len(decision.donors))]
    meta, bufs = _SnapshotReceiver(store, streams, deadline).receive()
    state, step = _decode_into_skeleton(skeleton, meta, bufs)
    from .. import checkpoint as ckpt

    ckpt.restore_registry(meta.get("registry") or {})
    members = list(decision.members)
    rank = members.index(me)
    peer_info = [decision.hosts[g] for g in members]
    from ..torch_backend.backend import ProcessGroupCGX

    group = ProcessGroupCGX(
        store, rank, len(members),
        generation=N, global_ranks=members, peer_info=peer_info,
    )
    _publish_shmok(store, N, group, decision, me)
    store.add(f"{jbase}/ready", 1)
    while int(store.add(f"{jbase}/ready", 0)) < len(members):
        if time.monotonic() > deadline:
            metrics.add("cgx.elastic.join_aborts")
            raise JoinAbortedError(
                f"elastic join: ready barrier did not fill within "
                f"{timeout:.1f}s ({int(store.add(f'{jbase}/ready', 0))}"
                f"/{len(members)}) — a survivor died mid-grow"
            )
        time.sleep(_POLL_S)
    _apply_shm_consensus(store, N, group, decision)
    from . import supervisor as sup_mod

    sup_mod.invalidate_trace_caches()
    _note_membership(N, len(members))
    health_mod.membership_policy().note_membership_change(N, len(members))
    dt = time.perf_counter() - t0
    metrics.add("cgx.elastic.joins")
    metrics.set("cgx.elastic.last_join_ms", dt * 1000.0)
    timeline.record("elastic.join", timeline.CAT_RECOVERY, t0, dt,
                    generation=N, rank=me, ws=len(members))
    flightrec.record(
        "elastic", phase="joined", generation=N, rank=me,
        ws=len(members), step=step, ms=round(dt * 1000.0, 3),
    )
    log.info(
        "elastic: joined generation %d as global rank %d (ws %d, step "
        "%d, %.0f ms)", N, me, len(members), step, dt * 1000.0,
    )
    return JoinResult(
        group=group, state=state, step=step, generation=N,
        members=members, decision=decision,
    )


def _decode_into_skeleton(skeleton: Any, meta: dict,
                          bufs: List[bytes]) -> Tuple[Any, int]:
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(skeleton)
    descs = meta["leaves"]
    if len(leaves) != len(descs):
        raise JoinAbortedError(
            f"elastic join: skeleton has {len(leaves)} leaves but the "
            f"donors shipped {len(descs)} — the joiner is running "
            "different model code than the group"
        )
    bits, bucket = int(meta.get("bits", 0)), int(meta.get("bucket", 0))
    out = []
    for i, (leaf, desc, buf) in enumerate(zip(leaves, descs, bufs)):
        want = tuple(np.asarray(leaf).shape)
        got = tuple(int(s) for s in desc["shape"])
        if want != got:
            raise JoinAbortedError(
                f"elastic join: leaf {i} shape mismatch — skeleton "
                f"{want}, donors {got}"
            )
        out.append(_decode_leaf(desc, buf, bits, bucket))
    return jax.tree_util.tree_unflatten(treedef, out), int(meta["step"])


# ---------------------------------------------------------------------------
# shm admission consensus (grow version of the boot-time ok handshake).
# ---------------------------------------------------------------------------


def _publish_shmok(store, generation: int, group, decision: JoinDecision,
                   me: int) -> None:
    """Before the ready ack: '1' when this member either has a live shm
    channel or needs none (alone on its host). Published-before-ready,
    so after the barrier every member reads a complete, identical set
    and the degrade verdict is unanimous without another round."""
    jbase = f"{JOIN_PREFIX}/g{generation}"
    fp = decision.hosts.get(me, "|").rsplit("|", 1)[0]
    local_peers = sum(
        1 for g in decision.members
        if g != me and decision.hosts.get(g, "|").rsplit("|", 1)[0] == fp
    )
    ok = "1" if (getattr(group, "_shm", None) is not None
                 or local_peers == 0) else "0"
    rdz._publish(store, f"{jbase}/shmok{me}", ok)


def _apply_shm_consensus(store, generation: int, group,
                         decision: JoinDecision) -> None:
    jbase = f"{JOIN_PREFIX}/g{generation}"
    bad = []
    for g in decision.members:
        key = f"{jbase}/shmok{g}"
        try:
            if rdz._flag_set(store, key) and rdz._read(store, key) == "0":
                bad.append(g)
        except Exception:
            bad.append(g)
    if bad and getattr(group, "_shm", None) is not None:
        log.warning(
            "elastic: member(s) %s could not (re)admit their shm arena — "
            "whole group drops to the store transport", bad,
        )
        group.degrade_to_store()


def _note_membership(generation: int, ws: int) -> None:
    """Planner / async-plane invalidation hooks, lazy: neither module is
    imported into a process that never used it."""
    import sys

    planner = sys.modules.get("torch_cgx_tpu.parallel.planner")
    if planner is not None:
        planner.note_membership(generation, ws)
    async_plane = sys.modules.get("torch_cgx_tpu.parallel.async_plane")
    if async_plane is not None:
        async_plane.note_membership(generation)


# ---------------------------------------------------------------------------
# Survivor side.
# ---------------------------------------------------------------------------


class ElasticCoordinator:
    """The survivors' half of the join plane, driven from the
    supervisor's step boundary (``run_steps`` calls
    :meth:`on_step_boundary` before every step once attached).

    ``consumed`` is the intent watermark — a joiner wiring its own
    coordinator after :func:`join` passes
    ``result.decision.intents_n`` so already-admitted intents are never
    re-triggered."""

    def __init__(self, store, supervisor, *, consumed: int = 0):
        self._store = store
        self._sup = supervisor
        self._consumed = int(consumed)
        self._trigger: Optional[dict] = None
        self._donations: List[_SnapshotDonor] = []
        supervisor.attach_elastic(self)

    @property
    def consumed(self) -> int:
        return self._consumed

    # -- the per-step hook ------------------------------------------------

    def on_step_boundary(self, state: Any, step: int) -> Any:
        """One store counter read per boundary when idle; runs the whole
        admit sequence at the agreed join step. Returns the (possibly
        grid-snapped) state. Inert without ``CGX_ELASTIC``."""
        if not cfg.elastic_enabled():
            return state
        self._donations = [d for d in self._donations if not d.done()]
        if self._trigger is None:
            self._check_trigger(step)
        trig = self._trigger
        if trig is not None and step >= int(trig["join_step"]):
            self._trigger = None
            if int(trig["generation"]) != self._sup.generation + 1:
                # A shrink landed between trigger and join point: every
                # survivor dropped to this same branch (they all bumped
                # together), and the next boundary re-triggers under the
                # new generation's key.
                flightrec.record(
                    "elastic", phase="stale_trigger",
                    trigger=trig, generation=self._sup.generation,
                )
                return state
            state = self._admit(state, step, trig)
        return state

    def _check_trigger(self, step: int) -> None:
        try:
            n = int(self._store.add(_intent_counter_key(), 0))
        except Exception as e:
            log.warning("elastic: intent counter read failed: %s", e)
            return
        if n <= self._consumed:
            return
        gen_next = self._sup.generation + 1
        tk = _trigger_key(self._consumed, gen_next)
        if rdz._flag_set(self._store, tk):
            trig = json.loads(rdz._read(self._store, tk))
        elif int(self._store.add(tk + "/claim", 1)) == 1:
            # First seer: pin the join point two steps out — every
            # survivor sees the counter move within one step (the claim
            # happened after the intent's add, and step t+1 collectives
            # order every peer's boundary t+1 after this boundary), so
            # all adopt this trigger before the join step arrives.
            trig = {
                "join_step": int(step) + 2,
                "generation": gen_next,
                "n": n,
                "key": tk,
            }
            rdz._publish(self._store, tk, json.dumps(trig, sort_keys=True))
            metrics.add("cgx.elastic.triggers")
        else:
            return  # claim lost; the winner's record is one boundary away
        pol = health_mod.membership_policy()
        for k in range(self._consumed + 1, int(trig["n"]) + 1):
            if rdz._flag_set(self._store, _intent_key(k)):
                try:
                    rec = json.loads(rdz._read(self._store, _intent_key(k)))
                    pol.note_join_intent(int(rec.get("rank", -1)))
                except (KeyError, TypeError, ValueError):
                    # Malformed or raced intent record: the health note
                    # is advisory — admission itself re-reads and
                    # validates every intent under the decision claim.
                    continue
        self._trigger = trig
        flightrec.record(
            "elastic", phase="trigger", join_step=trig["join_step"],
            generation=trig["generation"], intents=trig["n"],
        )

    # -- the join rendezvous (survivor) -----------------------------------

    def _admit(self, state: Any, step: int, trig: dict) -> Any:
        t0 = time.perf_counter()
        group = self._sup.group
        me = group.global_rank
        N = self._sup.generation + 1
        jbase = f"{JOIN_PREFIX}/g{N}"
        timeout = cfg.join_timeout_ms() / 1000.0
        deadline = time.monotonic() + timeout
        pol = health_mod.membership_policy()
        rdz._publish(
            self._store, f"{jbase}/v{me}",
            json.dumps({
                "load": pol.load_score(),
                "host": _my_host_info(),
                "step": int(step),
            }, sort_keys=True),
        )
        decision = self._converge(N, me, step, trig, deadline)
        if decision is None:
            return state  # vote timeout: grow abandoned via outcome=abort
        self._consumed = int(decision.intents_n)
        if decision.step < 0 or not decision.joiners:
            metrics.add("cgx.elastic.join_aborts")
            flightrec.record(
                "elastic", phase="empty_decision", generation=N,
            )
            return state
        outcome = self._await_acks(decision, deadline)
        if outcome != "commit":
            metrics.add("cgx.elastic.join_aborts")
            flightrec.record(
                "elastic", phase="abort", generation=N,
                joiners=list(decision.joiners),
            )
            log.warning(
                "elastic: generation-%d grow aborted (joiner ack never "
                "landed within %.1fs) — survivors carry on", N, timeout,
            )
            return state
        # COMMIT: donors ship pages concurrently with the reconfigure —
        # the page streams are plain store keys, untouched by the group
        # rebuild.
        if me in decision.donors:
            self._start_donation(state, decision, deadline)
        if decision.bits:
            state = snap_state_to_grid(state, decision.bits,
                                       decision.bucket)
        joiner_info = {g: decision.hosts[g] for g in decision.joiners}
        group.reconfigure(list(decision.members), N,
                          joiner_info=joiner_info)
        from . import supervisor as sup_mod

        sup_mod.invalidate_trace_caches()
        _note_membership(N, len(decision.members))
        pol.note_membership_change(N, len(decision.members))
        _publish_shmok(self._store, N, group, decision, me)
        self._store.add(f"{jbase}/ready", 1)
        while int(self._store.add(f"{jbase}/ready", 0)) < len(decision.members):
            if time.monotonic() > deadline:
                # Post-commit wedge: the joiner (or a peer) died between
                # its ack and the barrier. Name the joiners as suspects
                # in the NEW group's local indexing and let the regular
                # recovery ladder evict them at generation N+1 — the
                # survivors' bound on a broken grow is this one timeout.
                suspects = [
                    decision.members.index(j) for j in decision.joiners
                ]
                raise BridgeTimeoutError(
                    f"elastic grow to generation {N}: ready barrier did "
                    f"not fill within {timeout:.1f}s after commit",
                    suspects=suspects,
                )
            time.sleep(_POLL_S)
        _apply_shm_consensus(self._store, N, group, decision)
        dt = time.perf_counter() - t0
        metrics.add("cgx.elastic.grows")
        metrics.set("cgx.elastic.last_join_ms", dt * 1000.0)
        timeline.record(
            "elastic.grow", timeline.CAT_RECOVERY, t0, dt,
            generation=N, ws=len(decision.members),
            joiners=list(decision.joiners),
        )
        flightrec.record(
            "elastic", phase="grow", generation=N,
            ws=len(decision.members), joiners=list(decision.joiners),
            donors=list(decision.donors), step=int(decision.step),
            ms=round(dt * 1000.0, 3),
        )
        log.info(
            "elastic: grew to generation %d (ws %d, joiners %s, "
            "%.0f ms)", N, len(decision.members),
            list(decision.joiners), dt * 1000.0,
        )
        return state

    def _converge(self, generation: int, me: int, step: int, trig: dict,
                  deadline: float) -> Optional[JoinDecision]:
        jbase = f"{JOIN_PREFIX}/g{generation}"
        participants = sorted(self._sup.survivors)
        votes: Dict[int, dict] = {}
        while True:
            if rdz._flag_set(self._store, f"{jbase}/decision"):
                return JoinDecision.from_json(
                    rdz._read(self._store, f"{jbase}/decision")
                )
            for p in participants:
                if p not in votes and rdz._flag_set(
                        self._store, f"{jbase}/v{p}"):
                    votes[p] = json.loads(
                        rdz._read(self._store, f"{jbase}/v{p}")
                    )
            if len(votes) == len(participants):
                if int(self._store.add(f"{jbase}/decision/claim", 1)) == 1:
                    decision = self._decide(step, trig, votes)
                    rdz._publish(self._store, f"{jbase}/decision",
                                 decision.to_json())
                    if decision.step >= 0:
                        for g, k in decision.intents.items():
                            admit = json.loads(decision.to_json())
                            admit["you"] = int(g)
                            # cgx-analysis: allow(generation-hygiene) — admit records are keyed by PRE-generation intent index; the joiner reading them learns its generation from the payload
                            rdz._publish(
                                self._store, _admit_key(k),
                                json.dumps(admit, sort_keys=True),
                            )
                    # One writer, exactly once: the previous generation's
                    # rendezvous AND join keys retire together.
                    rdz.reap_all(self._store, decision.generation - 1)
                    return decision
                continue  # claim lost — adopt the record next poll
            if time.monotonic() > deadline:
                # A survivor never voted (died mid-join). Abandon the
                # grow through the outcome slot so a peer that converges
                # a moment later cannot commit behind our back; the dead
                # peer itself surfaces through the data plane's bounded
                # waits and the normal shrink ladder.
                if int(self._store.add(f"{jbase}/outcome/claim", 1)) == 1:
                    rdz._publish(self._store, f"{jbase}/outcome", "abort")
                self._consumed = max(self._consumed, int(trig["n"]))
                metrics.add("cgx.elastic.join_aborts")
                flightrec.record(
                    "elastic", phase="vote_timeout",
                    votes=sorted(votes), participants=participants,
                )
                log.warning(
                    "elastic: join vote did not converge (votes from %s "
                    "of %s) — grow abandoned", sorted(votes), participants,
                )
                return None
            time.sleep(_POLL_S)

    def _decide(self, step: int, trig: dict,
                votes: Dict[int, dict]) -> JoinDecision:
        N = self._sup.generation + 1
        survivors = sorted(votes)
        hosts = {p: str(v["host"]) for p, v in votes.items()}
        step_ok = all(int(v["step"]) == int(step) for v in votes.values())
        joiner_by_rank: Dict[int, str] = {}
        intents: Dict[int, int] = {}
        next_free = (max(survivors) + 1) if survivors else 0
        for k in range(self._consumed + 1, int(trig["n"]) + 1):
            if not rdz._flag_set(self._store, _intent_key(k)):
                continue  # torn announce: skipped, joiner re-announces
            try:
                rec = json.loads(rdz._read(self._store, _intent_key(k)))
            except Exception:
                continue
            want = int(rec.get("rank", -1))
            taken = set(survivors) | set(joiner_by_rank)
            if want >= 0 and want not in taken:
                g = want  # identity preserved: a respawned rank is
                # re-admitted under its original global rank
            else:
                while next_free in taken:
                    next_free += 1
                g = next_free
            joiner_by_rank[g] = str(rec.get("host", ""))
            intents[g] = k
        if not intents or not step_ok:
            # Nothing (or nothing coherent) to admit: a step=-1 record
            # tells every survivor to consume the intents and move on.
            return JoinDecision(
                generation=N, members=tuple(survivors),
                survivors=tuple(survivors), joiners=(), donors=(),
                hosts=hosts, intents={}, intents_n=int(trig["n"]),
                step=-1, bits=0, bucket=0,
                trigger_key=str(trig.get("key", "")),
            )
        members = tuple(sorted(set(survivors) | set(joiner_by_rank)))
        hosts.update(joiner_by_rank)
        nd = min(cfg.join_donors(), len(survivors))
        donors = tuple(sorted(
            survivors,
            key=lambda p: (float(votes[p].get("load", 0.0)), p),
        )[:nd])
        bits, bucket = _param_page_config()
        return JoinDecision(
            generation=N, members=members, survivors=tuple(survivors),
            joiners=tuple(sorted(joiner_by_rank)), donors=donors,
            hosts=hosts, intents=intents, intents_n=int(trig["n"]),
            step=int(step), bits=bits, bucket=bucket,
            trigger_key=str(trig.get("key", "")),
        )

    def _await_acks(self, decision: JoinDecision,
                    deadline: float) -> str:
        """Wait for every joiner's admit ack, then settle the outcome
        through the atomic claim: commit wins over abort whenever the
        acks are complete, and whichever survivor decides first decides
        for all — the published outcome is the only truth."""
        jbase = f"{JOIN_PREFIX}/g{decision.generation}"
        okey = f"{jbase}/outcome"
        want = len(decision.joiners)
        while True:
            if rdz._flag_set(self._store, okey):
                return rdz._read(self._store, okey)
            try:
                got = int(self._store.add(f"{jbase}/jack", 0))
            except Exception:
                got = 0
            if got >= want:
                if int(self._store.add(okey + "/claim", 1)) == 1:
                    rdz._publish(self._store, okey, "commit")
                    return "commit"
            elif time.monotonic() > deadline:
                if int(self._store.add(okey + "/claim", 1)) == 1:
                    rdz._publish(self._store, okey, "abort")
                    return "abort"
            time.sleep(_POLL_S)

    def _start_donation(self, state: Any, decision: JoinDecision,
                        deadline: float) -> None:
        """Encode once, ship one stripe per joiner. The sender threads
        run concurrently with this survivor's reconfigure + next steps;
        :meth:`on_step_boundary` reaps finished donors."""
        from .. import checkpoint as ckpt
        from . import faults as faults_mod

        group = self._sup.group
        me = group.global_rank
        di = list(decision.donors).index(me)
        wires, descs = _encode_state(state, decision.bits, decision.bucket)
        total = sum(len(w) for w in wires)
        metrics.add("cgx.elastic.snapshot_bytes", float(total))
        meta = None
        if di == 0:
            meta = {
                "leaves": descs,
                "step": int(decision.step),
                "generation": int(decision.generation),
                "registry": ckpt.registry_snapshot(),
                "bits": int(decision.bits),
                "bucket": int(decision.bucket),
                "n_donors": len(decision.donors),
            }
        injector = faults_mod.get_injector(me)
        for jg in decision.joiners:
            donor = _SnapshotDonor(
                self._store,
                _stream_name(decision.generation, jg, di),
                wires, descs, meta=meta, donor_idx=di,
                n_donors=len(decision.donors), bits=decision.bits,
                bucket=decision.bucket, deadline=deadline,
                injector=injector,
            )
            donor.start()
            self._donations.append(donor)
        flightrec.record(
            "elastic", phase="donate", generation=decision.generation,
            donor_idx=di, joiners=list(decision.joiners),
            bytes=total, leaves=len(descs),
        )


# ---------------------------------------------------------------------------
# Store-key hygiene: the join namespace reaps with the rendezvous's.
# ---------------------------------------------------------------------------


def _reap_join_generation(store, generation: int) -> int:
    """Delete everything a finished generation's join round left behind:
    votes, the decision (+ claim), jack/outcome/ready, shmok flags, the
    trigger, and the consumed intent + admit records. Registered with
    :func:`rendezvous.register_reaper`, so BOTH claim winners (shrink
    and grow) retire generation N-1's keys whichever kind N is."""
    base = f"{JOIN_PREFIX}/g{generation}"
    reaped = 0
    members: List[int] = []
    if rdz._flag_set(store, f"{base}/decision"):
        try:
            d = JoinDecision.from_json(rdz._read(store, f"{base}/decision"))
            members = sorted(set(d.members) | set(d.survivors))
            for g, k in d.intents.items():
                for key in (_intent_key(k), _admit_key(k)):
                    reaped += rdz._delete(store, key)
                    reaped += rdz._delete(store, key + "/flag")
            if d.trigger_key:
                reaped += rdz._delete(store, d.trigger_key)
                reaped += rdz._delete(store, d.trigger_key + "/flag")
                reaped += rdz._delete(store, d.trigger_key + "/claim")
        except Exception as e:
            log.warning(
                "elastic: cannot enumerate generation %d join keys for "
                "reaping: %s", generation, e,
            )
    for p in members:
        reaped += rdz._delete(store, f"{base}/v{p}")
        reaped += rdz._delete(store, f"{base}/v{p}/flag")
        reaped += rdz._delete(store, f"{base}/shmok{p}")
        reaped += rdz._delete(store, f"{base}/shmok{p}/flag")
    for key in ("decision", "decision/flag", "decision/claim", "jack",
                "outcome", "outcome/flag", "outcome/claim", "ready"):
        reaped += rdz._delete(store, f"{base}/{key}")
    if reaped:
        metrics.add("cgx.elastic.keys_reaped", float(reaped))
    return reaped


rdz.register_reaper(_reap_join_generation)
