"""Per-process liveness heartbeat files: turn hangs into *named* errors.

A rank that dies without reaching ``abort()`` (SIGKILL, OOM) leaves its
peers parked in a wait. The bounded waits (``CGX_BRIDGE_TIMEOUT_MS``)
bound the park; this module answers the follow-up question — *who* died.

Design constraints learned the hard way:

* **No control-plane traffic.** Liveness must not add store round-trips
  (an early token-rendezvous design added a blocking C++ store ``get``
  to every group init and destabilized the bridge under the test
  suite's rapid init/destroy cycles). Identity rides on the pid, which
  peers already learn from the host-fingerprint exchange.
* **Per process, not per group.** One daemon thread per (process,
  directory) touches ``cgx-hb-p<pid>``; every group in the process
  shares it. The *mtime* is the signal — nothing has to be released on
  death, it simply stops advancing, which is exactly the property a
  SIGKILL'd rank needs. Pid reuse is benign: a recycled pid's new
  owner keeps the same file alive, which is the correct per-pid answer.

``suspect_dead_pids`` judges a set of peer pids; stale files past
``reap_s`` are unlinked opportunistically so dead processes' 4-byte
files don't accumulate in tmpfs forever.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, Iterable, List, Tuple

_HB_PREFIX = "cgx-hb-p"

DEFAULT_INTERVAL_S = 0.5
DEFAULT_STALE_S = 2.0
_REAP_S = 3600.0


def heartbeat_path(directory: str, pid: int) -> str:
    return os.path.join(directory, f"{_HB_PREFIX}{pid}")


class Heartbeat:
    """Daemon thread touching one liveness file (internal; use
    :func:`ensure_heartbeat`)."""

    def __init__(
        self,
        directory: str,
        pid: int,
        interval_s: float = DEFAULT_INTERVAL_S,
    ):
        self._path = heartbeat_path(directory, pid)
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    @property
    def path(self) -> str:
        return self._path

    def start(self) -> "Heartbeat":
        self._touch()
        self._thread = threading.Thread(
            target=self._run, name="cgx-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _touch(self) -> None:
        # Temp-file + os.replace: a reader never observes a truncated or
        # half-written file (the plain open(.., "w") rewrite had a torn
        # window where the file existed but was empty — under heavy tmpfs
        # contention suspect_dead_pids could read it mid-write and the
        # judgement then rested on whatever mtime the truncation left).
        tmp = f"{self._path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(str(os.getpid()))
            os.replace(tmp, self._path)
        except OSError:
            # liveness is best-effort; never fail the data plane
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._touch()

    def stop(self, unlink: bool = True) -> None:
        self._stop.set()
        if unlink:
            try:
                os.unlink(self._path)
            except OSError:
                pass


# cgx-analysis: allow(orphan-memo) — process-lifetime heartbeat writers, keyed by (dir, rank): liveness must keep beating ACROSS reconfigurations so survivors can still name this rank
_singletons: Dict[Tuple[str, int], Heartbeat] = {}
_singleton_lock = threading.Lock()


def ensure_heartbeat(directory: str) -> Heartbeat:
    """This process's heartbeat for ``directory`` (started on first use).
    Idempotent and shared by every process group in the process — group
    teardown must NOT stop it (another group may still rely on it); it
    dies with the process, which is the point."""
    key = (directory, os.getpid())
    with _singleton_lock:
        hb = _singletons.get(key)
        if hb is None:
            hb = Heartbeat(directory, os.getpid()).start()
            _singletons[key] = hb
        return hb


def suspect_dead_pids(
    directory: str,
    pids: Iterable[int],
    stale_s: float = DEFAULT_STALE_S,
) -> List[int]:
    """Pids whose heartbeat file is missing or older than ``stale_s``.
    Also reaps heartbeat litter older than an hour (crash leftovers).

    Tolerant of the writer's atomic-replace window: a ``stat`` that fails
    while the file is being swapped is retried once after a short pause —
    a live peer mid-``os.replace`` must not be declared dead on a single
    racy probe (the file reappears within microseconds; a genuinely
    missing file fails both probes). Content is never required: an
    empty/partial read (pre-fix writers, exotic filesystems) does not
    mark a pid dead — the mtime is the liveness signal, and only a stale
    mtime (or a twice-confirmed missing file) suspects the peer."""
    now = time.time()
    out = []
    for pid in pids:
        path = heartbeat_path(directory, pid)
        st = None
        for attempt in range(2):
            try:
                st = os.stat(path)
                break
            except OSError:
                if attempt == 0:
                    time.sleep(0.01)  # ride out a concurrent os.replace
        if st is None:
            out.append(pid)
            continue
        if now - st.st_mtime > stale_s:
            out.append(pid)
            if now - st.st_mtime > _REAP_S:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    # Reap orphaned atomic-write temps too: a writer SIGKILLed between
    # its tmp write and the os.replace leaves '<hb>.tmp<pid>' behind on
    # the RAM-backed tmpfs forever otherwise.
    try:
        for name in os.listdir(directory):
            if ".tmp" not in name or not name.startswith(_HB_PREFIX):
                continue
            p = os.path.join(directory, name)
            try:
                if now - os.stat(p).st_mtime > _REAP_S:
                    os.unlink(p)
            except OSError:
                pass
    except OSError:
        pass
    out = sorted(set(out))
    if out:
        # Lazy imports: liveness stays dependency-free until it actually
        # finds a suspect (this module is imported before the package
        # finishes loading).
        from ..observability import flightrec
        from ..utils.logging import metrics

        metrics.add("cgx.heartbeat.suspect_checks")
        flightrec.record(
            "heartbeat_suspect", pids=out, directory=directory,
            stale_s=stale_s,
        )
    return out
