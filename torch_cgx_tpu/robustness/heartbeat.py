"""Per-process liveness heartbeat files: turn hangs into *named* errors.

A rank that dies without reaching ``abort()`` (SIGKILL, OOM) leaves its
peers parked in a wait. The bounded waits (``CGX_BRIDGE_TIMEOUT_MS``)
bound the park; this module answers the follow-up question — *who* died.

Design constraints learned the hard way:

* **No control-plane traffic.** Liveness must not add store round-trips
  (an early token-rendezvous design added a blocking C++ store ``get``
  to every group init and destabilized the bridge under the test
  suite's rapid init/destroy cycles). Identity rides on the pid, which
  peers already learn from the host-fingerprint exchange.
* **Per process, not per group.** One daemon thread per (process,
  directory) touches ``cgx-hb-p<pid>``; every group in the process
  shares it. The *mtime* is the signal — nothing has to be released on
  death, it simply stops advancing, which is exactly the property a
  SIGKILL'd rank needs. Pid reuse is benign: a recycled pid's new
  owner keeps the same file alive, which is the correct per-pid answer.

``suspect_dead_pids`` judges a set of peer pids; stale files past
``reap_s`` are unlinked opportunistically so dead processes' 4-byte
files don't accumulate in tmpfs forever.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, Iterable, List, Tuple

_HB_PREFIX = "cgx-hb-p"

DEFAULT_INTERVAL_S = 0.5
DEFAULT_STALE_S = 2.0
_REAP_S = 3600.0

# Store-published heartbeat counters (cross-host liveness, PR 20): the
# mtime trick only works on a shared local filesystem, so a remote
# peer's death was previously only detectable via bridge timeout. The
# same daemon thread now also bumps a per-pid store counter each tick;
# remote readers judge liveness by counter ADVANCE against their own
# clock (never by comparing wall clocks across hosts). The key is
# deliberately un-namespaced: liveness is per process, not per group or
# generation, exactly like the file.
_STORE_HB_PREFIX = "cgxhb/p"


def store_heartbeat_key(pid: int) -> str:
    return f"{_STORE_HB_PREFIX}{pid}"


def heartbeat_path(directory: str, pid: int) -> str:
    return os.path.join(directory, f"{_HB_PREFIX}{pid}")


class Heartbeat:
    """Daemon thread touching one liveness file (internal; use
    :func:`ensure_heartbeat`)."""

    def __init__(
        self,
        directory: str,
        pid: int,
        interval_s: float = DEFAULT_INTERVAL_S,
    ):
        self._path = heartbeat_path(directory, pid)
        self._interval = interval_s
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._publishers: Dict[object, Callable[[], None]] = {}
        self._pub_lock = threading.Lock()

    @property
    def path(self) -> str:
        return self._path

    def start(self) -> "Heartbeat":
        self._touch()
        self._thread = threading.Thread(
            target=self._run, name="cgx-heartbeat", daemon=True
        )
        self._thread.start()
        return self

    def _touch(self) -> None:
        # Temp-file + os.replace: a reader never observes a truncated or
        # half-written file (the plain open(.., "w") rewrite had a torn
        # window where the file existed but was empty — under heavy tmpfs
        # contention suspect_dead_pids could read it mid-write and the
        # judgement then rested on whatever mtime the truncation left).
        tmp = f"{self._path}.tmp{os.getpid()}"
        try:
            with open(tmp, "w") as f:
                f.write(str(os.getpid()))
            os.replace(tmp, self._path)
        except OSError:
            # liveness is best-effort; never fail the data plane
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def add_publisher(self, tag: object, fn: Callable[[], None]) -> None:
        """Attach an extra per-tick liveness publisher (idempotent by
        ``tag``). Publishers are best-effort: an exception (a store torn
        down mid-shutdown) never stops the file heartbeat."""
        with self._pub_lock:
            self._publishers.setdefault(tag, fn)

    def _publish(self) -> None:
        with self._pub_lock:
            pubs = list(self._publishers.values())
        for fn in pubs:
            try:
                fn()
            except Exception:
                # Liveness is best-effort — a publisher failing (store
                # torn down mid-shutdown) must never fail the data
                # plane, but a persistent failure should be countable.
                from ..utils.logging import metrics

                metrics.add("cgx.heartbeat.publish_errors")

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            self._touch()
            self._publish()

    def stop(self, unlink: bool = True) -> None:
        self._stop.set()
        if unlink:
            try:
                os.unlink(self._path)
            except OSError:
                pass


# cgx-analysis: allow(orphan-memo) — process-lifetime heartbeat writers, keyed by (dir, rank): liveness must keep beating ACROSS reconfigurations so survivors can still name this rank
_singletons: Dict[Tuple[str, int], Heartbeat] = {}
_singleton_lock = threading.Lock()


def ensure_heartbeat(directory: str) -> Heartbeat:
    """This process's heartbeat for ``directory`` (started on first use).
    Idempotent and shared by every process group in the process — group
    teardown must NOT stop it (another group may still rely on it); it
    dies with the process, which is the point."""
    key = (directory, os.getpid())
    with _singleton_lock:
        hb = _singletons.get(key)
        if hb is None:
            hb = Heartbeat(directory, os.getpid()).start()
            _singletons[key] = hb
        return hb


def attach_store(directory: str, store) -> Heartbeat:
    """Publish this process's heartbeat through ``store`` too: each tick
    of the (shared, per-process) heartbeat thread also bumps
    ``cgxhb/p<pid>``. Idempotent per store object; the bump is one
    ``add`` — no blocking get, honoring the no-control-plane-round-trips
    constraint on the *read* side only (remote liveness is opt-in for
    groups that actually span hosts)."""
    hb = ensure_heartbeat(directory)
    key = store_heartbeat_key(os.getpid())
    # cgx-analysis: allow(generation-hygiene) — heartbeat counters are per-PID and deliberately cross-generation: liveness must survive reconfiguration, exactly like the mtime file
    hb.add_publisher(("store", id(store)), lambda: store.add(key, 1))
    try:
        # cgx-analysis: allow(generation-hygiene) — per-PID liveness counter, deliberately cross-generation
        store.add(key, 1)  # first observation lands before any wait
    except Exception:
        from ..utils.logging import metrics

        metrics.add("cgx.heartbeat.publish_errors")
    return hb


class RemoteLiveness:
    """Counter-advance liveness judge for cross-host peers.

    Tracks, per pid, the store heartbeat counter and the LOCAL monotonic
    time it last advanced. A pid is suspect when its counter has not
    advanced for ``stale_s`` AND it has been observed at least that long
    (a single probe can never convict — the judge needs its own history,
    which also makes it immune to cross-host clock skew: only local time
    and counter deltas are compared)."""

    def __init__(self, store, stale_s: float = DEFAULT_STALE_S):
        self._store = store
        self._stale_s = stale_s
        # pid -> (last counter value, t_first_seen, t_last_advance)
        self._obs: Dict[int, Tuple[int, float, float]] = {}
        self._lock = threading.Lock()

    def observe(self, pids: Iterable[int]) -> None:
        now = time.monotonic()
        for pid in pids:
            try:
                v = int(self._store.add(store_heartbeat_key(pid), 0))
            except Exception:
                continue  # store unreachable: no judgement, no conviction
            with self._lock:
                prev = self._obs.get(pid)
                if prev is None:
                    self._obs[pid] = (v, now, now)
                elif v != prev[0]:
                    self._obs[pid] = (v, prev[1], now)

    def suspects(
        self, pids: Iterable[int], stale_s: float | None = None
    ) -> List[int]:
        """Pids whose heartbeat counter stopped advancing (observed for
        at least ``stale_s`` with no advance). Also records a fresh
        observation, so repeated probes inside one bounded wait build the
        history the judgement needs."""
        pids = list(pids)
        self.observe(pids)
        stale = self._stale_s if stale_s is None else stale_s
        now = time.monotonic()
        out: List[int] = []
        with self._lock:
            for pid in pids:
                ob = self._obs.get(pid)
                if ob is None:
                    continue
                _, t_first, t_adv = ob
                if now - t_adv > stale and now - t_first > stale:
                    out.append(pid)
        out = sorted(set(out))
        if out:
            from ..observability import flightrec
            from ..utils.logging import metrics

            metrics.add("cgx.heartbeat.remote_suspect_checks")
            flightrec.record(
                "heartbeat_remote_suspect", pids=out, stale_s=stale,
            )
        return out


def suspect_dead_pids(
    directory: str,
    pids: Iterable[int],
    stale_s: float = DEFAULT_STALE_S,
) -> List[int]:
    """Pids whose heartbeat file is missing or older than ``stale_s``.
    Also reaps heartbeat litter older than an hour (crash leftovers).

    Tolerant of the writer's atomic-replace window: a ``stat`` that fails
    while the file is being swapped is retried once after a short pause —
    a live peer mid-``os.replace`` must not be declared dead on a single
    racy probe (the file reappears within microseconds; a genuinely
    missing file fails both probes). Content is never required: an
    empty/partial read (pre-fix writers, exotic filesystems) does not
    mark a pid dead — the mtime is the liveness signal, and only a stale
    mtime (or a twice-confirmed missing file) suspects the peer."""
    now = time.time()
    out = []
    for pid in pids:
        path = heartbeat_path(directory, pid)
        st = None
        for attempt in range(2):
            try:
                st = os.stat(path)
                break
            except OSError:
                if attempt == 0:
                    time.sleep(0.01)  # ride out a concurrent os.replace
        if st is None:
            out.append(pid)
            continue
        if now - st.st_mtime > stale_s:
            out.append(pid)
            if now - st.st_mtime > _REAP_S:
                try:
                    os.unlink(path)
                except OSError:
                    pass
    # Reap orphaned atomic-write temps too: a writer SIGKILLed between
    # its tmp write and the os.replace leaves '<hb>.tmp<pid>' behind on
    # the RAM-backed tmpfs forever otherwise.
    try:
        for name in os.listdir(directory):
            if ".tmp" not in name or not name.startswith(_HB_PREFIX):
                continue
            p = os.path.join(directory, name)
            try:
                if now - os.stat(p).st_mtime > _REAP_S:
                    os.unlink(p)
            except OSError:
                pass
    except OSError:
        pass
    out = sorted(set(out))
    if out:
        # Lazy imports: liveness stays dependency-free until it actually
        # finds a suspect (this module is imported before the package
        # finishes loading).
        from ..observability import flightrec
        from ..utils.logging import metrics

        metrics.add("cgx.heartbeat.suspect_checks")
        flightrec.record(
            "heartbeat_suspect", pids=out, directory=directory,
            stale_s=stale_s,
        )
    return out
