"""Store-based generation rendezvous for the recovery supervisor.

When a rank's recovery ladder reaches the eviction rung it must not act
alone: evicting a peer, degrading the transport, or replaying a step is
only safe if every survivor does the same thing at the same generation —
otherwise half the group posts to key namespaces the other half never
reads (exactly the aliasing the generation tag exists to kill).

This module is the agreement protocol, built from the only primitives
every c10d store (TCPStore, FileStore, test doubles) shares: atomic
``set``/``get`` per key and an atomic ``add`` counter. Notably it never
issues a *blocking* ``get`` on a key that may not exist (a FileStore
``get`` parks for the store timeout): presence is signalled through an
``add``-based flag written after the payload, so every poll is
non-blocking and the whole negotiation is bounded by ``timeout_s``.

Protocol, per target ``generation`` (keys under ``cgxrdz/g<N>/``):

1. **Vote** — each arriving rank publishes its local view: the suspects
   its bounded waits named (global ranks), whether it wants the
   transport degraded (repeated wire corruption), and the step of its
   newest in-memory rollback snapshot.
2. **Converge** — each rank polls the votes present so far, unions every
   voter's suspects, and derives ``expected = participants - suspects``.
   When all *expected* ranks have voted, the survivor set is ``expected``
   and ``degrade`` is the OR of the votes. All ranks are stuck in (or
   just failed out of) the same collective, so every survivor reaches
   this rung within one bridge timeout of the first.
3. **Decide** — the first converged rank claims the decision slot with
   an atomic counter and publishes the record (no standing leader: the
   claim elects a writer per generation, so two ranks converging with
   different vote subsets cannot publish divergent records). Every other
   rank — including a late, falsely-suspected live one — adopts the
   published decision instead of re-deriving it; if the decision
   excludes it, it raises :class:`EvictedError`.
4. **Ack barrier** — survivors bump a counter and wait until every
   survivor has acked, so nobody starts generation N+1 collectives while
   a peer is still tearing down generation N.

A rendezvous that cannot converge within ``timeout_s`` (survivors died
mid-negotiation, store gone) raises :class:`RecoveryFailedError` — the
job falls back to the pre-supervisor failure semantics: die loudly.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger, metrics
from .errors import EvictedError, RecoveryFailedError

log = get_logger()

KEY_PREFIX = "cgxrdz"


@dataclasses.dataclass(frozen=True)
class Decision:
    """The converged outcome of one generation rendezvous. All rank ids
    are GLOBAL (original-world) ranks. ``replay_step`` is the agreed
    rollback point — the MINIMUM of the survivors' voted snapshot steps
    (None when no survivor holds a snapshot): survivors can drift apart
    by whole steps around a fault (a rank whose collectives are
    send-only never blocks on the dead peer), and replaying from
    per-rank local snapshots would pair wrong-step payloads under
    identical post-recovery keys."""

    generation: int
    survivors: Tuple[int, ...]
    evicted: Tuple[int, ...]
    degrade: bool
    replay_step: Optional[int] = None

    def to_json(self) -> str:
        return json.dumps(
            {
                "generation": self.generation,
                "survivors": list(self.survivors),
                "evicted": list(self.evicted),
                "degrade": self.degrade,
                "replay_step": self.replay_step,
            },
            sort_keys=True,
        )

    @classmethod
    def from_json(cls, raw: str) -> "Decision":
        d = json.loads(raw)
        rs = d.get("replay_step")
        return cls(
            generation=int(d["generation"]),
            survivors=tuple(d["survivors"]),
            evicted=tuple(d["evicted"]),
            degrade=bool(d["degrade"]),
            replay_step=int(rs) if rs is not None else None,
        )


def _flag_set(store, key: str) -> bool:
    """Non-blocking presence probe via the add-counter flag convention
    (``<key>/flag``). Never issues a blocking get."""
    try:
        return int(store.add(key + "/flag", 0)) > 0
    except Exception as e:
        log.warning("rendezvous: flag probe for %r failed: %s", key, e)
        return False


def _publish(store, key: str, payload: str) -> None:
    """Payload first, flag second: a reader that sees the flag is
    guaranteed a complete payload under every c10d store's per-key
    atomicity."""
    store.set(key, payload.encode())
    store.add(key + "/flag", 1)


def _read(store, key: str) -> str:
    return bytes(store.get(key)).decode()


def _delete(store, key: str) -> int:
    """Best-effort single-key delete (the consume-side GC contract:
    stores without ``delete_key`` keep their keys — a bounded leak,
    never an error). Returns 1 when a key was removed."""
    try:
        return 1 if store.delete_key(key) else 0
    except (NotImplementedError, AttributeError):
        return 0
    except Exception as e:
        log.debug("rendezvous: delete(%r) failed: %s", key, e)
        return 0


def reap_generation(
    store,
    generation: int,
    *,
    key_prefix: str = KEY_PREFIX,
    participants: Optional[Sequence[int]] = None,
) -> int:
    """Delete every store key a FINISHED rendezvous generation left
    behind: votes (+ flags), the decision record (+ flag), the decision
    claim and the ack barrier. Without this, every recovery leaks its
    whole key namespace into the store for the process lifetime — on a
    FileStore that is a file that only ever grows.

    Called by the decision-claim winner when it publishes generation
    ``N``'s record, pointed at generation ``N - 1``: that rendezvous is
    strictly finished (every survivor passed its ack barrier before any
    rank could reach a new one). ``participants`` enumerates the voter
    set; when None it is recovered from the old decision record itself
    (survivors + evicted). A generation with no published decision has
    nothing enumerable and only its fixed keys are reaped.

    Accepted sharp edge: a falsely-suspected rank arriving at a reaped
    generation finds no decision and times out with
    :class:`RecoveryFailedError` instead of adopting the record and
    raising :class:`EvictedError` — it dies loudly either way, and a
    survivor that late (a full further recovery completed meanwhile) was
    never going to re-enter the group."""
    base = f"{key_prefix}/g{generation}"
    ranks: List[int] = sorted(int(p) for p in participants or ())
    if not ranks and _flag_set(store, f"{base}/decision"):
        try:
            old = Decision.from_json(_read(store, f"{base}/decision"))
            ranks = sorted(set(old.survivors) | set(old.evicted))
        except Exception as e:
            log.warning(
                "rendezvous: cannot enumerate generation %d voters for "
                "reaping: %s", generation, e,
            )
    reaped = 0
    for p in ranks:
        reaped += _delete(store, f"{base}/v{p}")
        reaped += _delete(store, f"{base}/v{p}/flag")
    reaped += _delete(store, f"{base}/decision")
    reaped += _delete(store, f"{base}/decision/flag")
    reaped += _delete(store, f"{base}/decision/claim")
    reaped += _delete(store, f"{base}/ack")
    if reaped:
        metrics.add("cgx.recovery.keys_reaped", float(reaped))
    return reaped


# Extra per-generation reapers (the elastic join plane registers one for
# its ``cgxjoin/g<N>/`` namespace): called alongside the rendezvous reap
# whenever a decision-claim winner retires generation N-1, so a shrink
# following a grow also collects the grow's keys and vice versa.
# cgx-analysis: allow(orphan-memo) — import-time registration list, not a cache: resetting it would silently drop the elastic reaper until its module is re-imported
_extra_reapers: List = []


def register_reaper(fn) -> None:
    """Register ``fn(store, generation) -> int`` to run at every
    generation reap point (idempotent per fn)."""
    if fn not in _extra_reapers:
        _extra_reapers.append(fn)


def reap_all(store, generation: int) -> int:
    """Reap generation ``generation``'s keys across every registered
    namespace (the rendezvous's own plus extras)."""
    n = reap_generation(store, generation)
    for fn in list(_extra_reapers):
        try:
            n += int(fn(store, generation) or 0)
        except Exception as e:
            log.warning(
                "rendezvous: extra reaper %r failed for generation %d: %s",
                fn, generation, e,
            )
    return n


def negotiate(
    store,
    *,
    generation: int,
    me: int,
    participants: Sequence[int],
    suspects: Sequence[int] = (),
    degrade: bool = False,
    snapshot_step: Optional[int] = None,
    timeout_s: float = 30.0,
    poll_s: float = 0.05,
    key_prefix: str = KEY_PREFIX,
) -> Decision:
    """Run one generation rendezvous; returns the agreed :class:`Decision`.

    ``me``/``participants``/``suspects`` are GLOBAL ranks; ``participants``
    is the CURRENT survivor set (pre-shrink). ``snapshot_step`` is this
    rank's newest in-memory rollback point (None = holds none); the
    decision pins ``replay_step`` to the minimum across the survivor
    votes so every survivor replays the SAME steps. Raises
    :class:`EvictedError` when the group converges on a survivor set
    excluding ``me``, and :class:`RecoveryFailedError` when no decision
    lands within ``timeout_s``.
    """
    participants = sorted(participants)
    if me not in participants:
        raise ValueError(f"rank {me} not in participants {participants}")
    base = f"{key_prefix}/g{generation}"
    deadline = time.monotonic() + timeout_s
    _publish(
        store,
        f"{base}/v{me}",
        json.dumps(
            {"suspects": sorted(set(int(s) for s in suspects)),
             "degrade": bool(degrade),
             "snap": int(snapshot_step) if snapshot_step is not None
             else None},
            sort_keys=True,
        ),
    )
    metrics.add("cgx.recovery.rendezvous_started")
    votes: Dict[int, dict] = {}
    decision: Optional[Decision] = None
    while True:
        # A published decision always wins — late arrivals (including a
        # falsely suspected live rank) adopt it instead of re-deriving.
        if _flag_set(store, f"{base}/decision"):
            decision = Decision.from_json(_read(store, f"{base}/decision"))
            break
        for p in participants:
            if p not in votes and _flag_set(store, f"{base}/v{p}"):
                votes[p] = json.loads(_read(store, f"{base}/v{p}"))
        union: set = set()
        for v in votes.values():
            union.update(int(s) for s in v.get("suspects", ()))
        expected = [p for p in participants if p not in union]
        if expected and all(p in votes for p in expected):
            # Claim the decision slot atomically before publishing: two
            # ranks can reach convergence holding DIFFERENT vote subsets
            # (a late vote landing between their polls), so concurrent
            # publishes could write divergent records over the same key
            # and split-brain the group. Only the claim winner derives
            # and publishes; losers loop back and adopt its record (the
            # winner's publish is at most one poll away).
            if int(store.add(f"{base}/decision/claim", 1)) == 1:
                snaps = [
                    votes[p]["snap"] for p in expected
                    if votes[p].get("snap") is not None
                ]
                decision = Decision(
                    generation=generation,
                    survivors=tuple(expected),
                    evicted=tuple(p for p in participants if p in union),
                    degrade=any(v.get("degrade") for v in votes.values()),
                    replay_step=min(snaps) if snaps else None,
                )
                _publish(store, f"{base}/decision", decision.to_json())
                # Store-key hygiene: generation N-1's rendezvous is
                # strictly finished (its ack barrier filled before any
                # rank could start this one), so the claim winner reaps
                # its whole key namespace here — one writer, exactly
                # once per generation.
                if generation > 0:
                    if key_prefix == KEY_PREFIX:
                        reap_all(store, generation - 1)
                    else:
                        reap_generation(
                            store, generation - 1, key_prefix=key_prefix
                        )
                break
        if time.monotonic() > deadline:
            metrics.add("cgx.recovery.rendezvous_failed")
            raise RecoveryFailedError(
                f"recovery rendezvous for generation {generation} did not "
                f"converge within {timeout_s:.1f}s: votes from "
                f"{sorted(votes)}, expected {expected or participants} "
                "(survivors died mid-negotiation, or the store is gone)"
            )
        time.sleep(poll_s)
    if me not in decision.survivors:
        metrics.add("cgx.recovery.self_evicted")
        raise EvictedError(
            f"recovery rendezvous for generation {generation} converged on "
            f"survivors {list(decision.survivors)} — this rank ({me}) was "
            "evicted by its peers"
        )
    # Ack barrier: nobody proceeds into generation-N collectives until
    # every survivor has adopted the decision.
    store.add(f"{base}/ack", 1)
    while int(store.add(f"{base}/ack", 0)) < len(decision.survivors):
        if time.monotonic() > deadline:
            metrics.add("cgx.recovery.rendezvous_failed")
            raise RecoveryFailedError(
                f"recovery rendezvous for generation {generation}: "
                "decision reached but the ack barrier did not fill within "
                f"{timeout_s:.1f}s (a survivor died after voting)"
            )
        time.sleep(poll_s)
    metrics.add("cgx.recovery.rendezvous_converged")
    return decision
