"""Deterministic fault injection for the distributed data plane.

The reference inherits failure semantics from MPI (a dead rank aborts the
job) and has no way to *rehearse* them; the flaky-shm-bench / wedged-session
class of field failures (VERDICT r5) is exactly what this layer reproduces
on demand. A :class:`FaultInjector` is built from the ``CGX_FAULTS`` env
var and threaded through :class:`~..torch_backend.shm.ShmChannel`, the
torch backend collectives, and ``make_train_step``'s gradient path.

Grammar (comma-separated ``mode[:spec]`` entries; ``spec`` tokens are
joined with ``@``)::

    CGX_FAULTS=drop_put:0.1,delay_take:50ms,corrupt_wire:step=7,kill_rank:2@step=5,nan_grad:step=3

========================  =====================================================
mode                      effect at its injection site
========================  =====================================================
``drop_put``              the payload is written but its header is never
                          published — the matching ``take`` times out
``delay_take``            sleep ``delay`` before reading a payload
``corrupt_wire``          flip a byte of the payload AFTER its checksum is
                          computed — the reader's verify fails
``kill_rank``             ``os._exit`` the process at a collective entry
``nan_grad``              poison one gradient value with NaN (staged into
                          the jitted train step at trace time)
``stall_ack``             reader acks are never observed by the writer's
                          arena — drives the pressure/backoff path
``slow_rank``             sleep ``delay`` at a collective entry on the
                          gated rank — peers' bounded waits expire while
                          the straggler is merely slow, not dead (the
                          recovery retry rung's rehearsal)
``flap``                  transient drop-then-recover: the message header
                          is published ``delay`` late instead of never —
                          the first bounded wait may expire, a retry
                          succeeds
``preempt``               SIGKILL-style exit at a collective entry, then
                          auto-respawn after ``delay`` (the respawn is
                          ``$CGX_PREEMPT_RESPAWN``, detached before the
                          exit) — the elastic join path's rehearsal: the
                          rank announces it is coming back, dies, and
                          re-enters through the join rendezvous
``corrupt_join_page``     flip a byte of ONE snapshot page frame AFTER
                          its checksum is computed (``step=N`` picks the
                          N-th shipped page) — the joiner must re-request
                          the page, not wedge or silently diverge
``leak_page``             a KV page whose last reference drops is never
                          returned to the free list — the classic slow
                          leak (alloc with suppressed release) that the
                          memory ledger's sliding-window detector must
                          name (``mem_leak`` on ``serve.kv_pool``)
                          before the pool exhausts
``conn_reset``            transport plane: the first qualifying send on
                          the gated rank opens a window of ``delay``
                          during which every socket write/connect fails
                          with a reset — the reconnect + resend-ring
                          replay rehearsal
``partial_write``         transport plane: a frame is truncated mid-wire
                          and the connection torn down (fires on the
                          first send event unless ``step=``/prob gates
                          say otherwise) — the receiver must discard the
                          torn frame and the replay must complete
``slow_link``             transport plane: sleep ``delay`` in the
                          per-peer sender thread (``edge=tcp``, the
                          default and only edge) — a slow NIC/route, not
                          a slow rank
``partition``             transport plane: sends AND reconnects between
                          the two ranks of ``ranks=a,b`` fail for
                          ``delay`` — the degrade-to-store rehearsal
                          (both directions; each rank's injector opens
                          its window on first traffic across the pair)
========================  =====================================================

Spec tokens: a bare float is a per-event probability; ``NNms``/``NNs`` a
delay; ``step=N`` fires only on the mode's N-th event (0-based; for
``nan_grad`` the training step index, for ``corrupt_join_page`` the
shipped page ordinal); ``rank=N`` restricts to one rank (a bare integer
on ``kill_rank``/``slow_rank``/``preempt`` is shorthand for
``rank=N``); ``edge=dcn`` scopes ``slow_rank`` to the cross-slice (DCN)
exchange sites ONLY — the two-level reduction's cross stage and the
async plane's sender thread — modeling a slow DCN *edge* instead of a
rank slow at every collective (the ``bench.py --async-dcn`` fault: the
synchronous two-level path stalls on it, the async plane does not);
``edge=tcp`` is the transport plane's analogue for ``slow_link``;
``ranks=a,b`` names the two endpoints of a ``partition`` (the embedded
comma is recognized by the parser — a bare trailing integer after a
``ranks=`` entry joins it instead of starting a new entry).

Determinism: probabilistic gates draw from a per-rank stream seeded by
``CGX_FAULTS_SEED`` (default 0), so a failing chaos run replays exactly.
Every fired fault bumps ``cgx.faults.<mode>`` in the metrics registry.
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

from ..utils.logging import get_logger, metrics

log = get_logger()

FAULTS_ENV = "CGX_FAULTS"
FAULTS_SEED_ENV = "CGX_FAULTS_SEED"

KILL_EXIT_CODE = 17  # distinguishable from crashes in test harnesses

MODES = (
    "drop_put",
    "delay_take",
    "corrupt_wire",
    "kill_rank",
    "nan_grad",
    "stall_ack",
    "slow_rank",
    "flap",
    "preempt",
    "corrupt_join_page",
    "leak_page",
    "conn_reset",
    "partial_write",
    "slow_link",
    "partition",
)

# Transport-plane modes whose window/fire sites live inside
# torch_backend/transport.py (the SocketTransport injection surface).
NET_MODES = ("conn_reset", "partial_write", "slow_link", "partition")

PREEMPT_RESPAWN_ENV = "CGX_PREEMPT_RESPAWN"

_DURATION_RE = re.compile(r"^(\d+(?:\.\d+)?)(ms|s)$")


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One parsed ``CGX_FAULTS`` entry."""

    mode: str
    prob: Optional[float] = None  # None = always (when step/rank gates pass)
    step: Optional[int] = None
    rank: Optional[int] = None
    delay_ms: float = 0.0
    edge: Optional[str] = None  # None = legacy sites; "dcn"/"tcp" = edge only
    ranks: Optional[Tuple[int, ...]] = None  # partition endpoints

    def __post_init__(self):
        if self.mode not in MODES:
            raise ValueError(
                f"CGX_FAULTS: unknown mode {self.mode!r} (known: {MODES})"
            )
        if self.mode == "slow_link" and self.edge is None:
            # slow_link IS an edge fault; defaulting the edge keeps the
            # legacy per-collective delay() site from ever firing it.
            object.__setattr__(self, "edge", "tcp")
        if self.edge is not None and self.edge not in ("dcn", "tcp"):
            raise ValueError(
                f"CGX_FAULTS: edge= must be 'dcn' or 'tcp', got {self.edge!r}"
            )
        if self.edge == "dcn" and self.mode != "slow_rank":
            raise ValueError(
                f"CGX_FAULTS: edge=dcn only applies to slow_rank, not "
                f"{self.mode!r}"
            )
        if self.edge == "tcp" and self.mode != "slow_link":
            raise ValueError(
                f"CGX_FAULTS: edge=tcp only applies to slow_link, not "
                f"{self.mode!r}"
            )
        if self.ranks is not None and self.mode != "partition":
            raise ValueError(
                f"CGX_FAULTS: ranks= only applies to partition, not "
                f"{self.mode!r}"
            )
        if self.mode == "partition":
            if self.ranks is None or len(self.ranks) != 2:
                raise ValueError(
                    "CGX_FAULTS: partition needs exactly two endpoints, "
                    "e.g. 'partition:10s@ranks=0,1'"
                )
            if self.delay_ms <= 0:
                raise ValueError(
                    "CGX_FAULTS: partition needs a duration, e.g. "
                    "'partition:10s@ranks=0,1'"
                )
        if self.mode in ("conn_reset", "slow_link") and self.delay_ms <= 0:
            # The window/delay IS the fault — without one the injection
            # sites never fire and the chaos run is vacuously green.
            raise ValueError(
                f"CGX_FAULTS: {self.mode} needs a duration, e.g. "
                f"'{self.mode}:500ms'"
            )
        if (
            self.mode == "partial_write"
            and self.prob is None
            and self.step is None
        ):
            # An ungated partial_write would truncate EVERY frame — the
            # link could never make progress and the replay under test
            # would never complete. Default to the first send event.
            object.__setattr__(self, "step", 0)
        if self.prob is not None and not 0.0 < self.prob <= 1.0:
            raise ValueError(
                f"CGX_FAULTS: {self.mode} probability must be in (0, 1], "
                f"got {self.prob}"
            )
        if self.mode in ("slow_rank", "flap") and self.delay_ms <= 0:
            # These modes ARE their delay — without one the injection
            # sites never fire and the chaos run is vacuously green,
            # exactly what this parser's fail-loud contract forbids.
            raise ValueError(
                f"CGX_FAULTS: {self.mode} needs a duration, e.g. "
                f"'{self.mode}:800ms'"
            )
        if self.mode == "preempt" and self.delay_ms <= 0:
            # The duration IS the respawn delay — a preempt without one
            # is just kill_rank spelled wrong, and the join path the
            # mode exists to exercise would never run.
            raise ValueError(
                "CGX_FAULTS: preempt needs a respawn duration, e.g. "
                "'preempt:2s@rank=1@step=5'"
            )


def parse_faults(raw: str) -> List[FaultSpec]:
    """Parse the ``CGX_FAULTS`` grammar; raises ValueError on junk (a typo
    silently injecting nothing would make a chaos run vacuously green)."""
    # Pre-pass: ``ranks=a,b`` embeds the entry separator — a fragment
    # that is purely digits re-joins a preceding fragment ending in a
    # ranks= list instead of starting a (junk) entry of its own.
    parts: List[str] = []
    for frag in raw.split(","):
        if (
            parts
            and frag.strip().isdigit()
            and re.search(r"ranks=\d+(?:,\d+)*\s*$", parts[-1])
        ):
            parts[-1] += "," + frag
        else:
            parts.append(frag)
    specs: List[FaultSpec] = []
    for entry in parts:
        entry = entry.strip()
        if not entry:
            continue
        mode, _, argspec = entry.partition(":")
        mode = mode.strip()
        kw: Dict[str, object] = {"mode": mode}
        for tok in filter(None, (t.strip() for t in argspec.split("@"))):
            m = _DURATION_RE.match(tok)
            if m:
                kw["delay_ms"] = float(m.group(1)) * (
                    1.0 if m.group(2) == "ms" else 1000.0
                )
            elif tok.startswith("step="):
                kw["step"] = int(tok[len("step="):])
            elif tok.startswith("ranks="):
                try:
                    kw["ranks"] = tuple(
                        int(x) for x in tok[len("ranks="):].split(",")
                    )
                except ValueError:
                    raise ValueError(
                        f"CGX_FAULTS: cannot parse ranks= token {tok!r}"
                    ) from None
            elif tok.startswith("rank="):
                kw["rank"] = int(tok[len("rank="):])
            elif tok.startswith("edge="):
                kw["edge"] = tok[len("edge="):]
            elif (
                mode in ("kill_rank", "slow_rank", "preempt")
                and "." not in tok
            ):
                kw["rank"] = int(tok)  # kill_rank:2 == kill_rank:rank=2
            else:
                try:
                    kw["prob"] = float(tok)
                except ValueError:
                    raise ValueError(
                        f"CGX_FAULTS: cannot parse token {tok!r} in "
                        f"entry {entry!r}"
                    ) from None
        specs.append(FaultSpec(**kw))  # type: ignore[arg-type]
    return specs


class FaultInjector:
    """Seeded, per-rank deterministic fault oracle.

    ``fire(mode)`` answers "does this event fault?" and advances the
    mode's event counter; call sites own *what* the fault means.
    """

    def __init__(
        self,
        specs: List[FaultSpec],
        seed: int = 0,
        rank: Optional[int] = None,
    ):
        self._specs: Dict[str, FaultSpec] = {s.mode: s for s in specs}
        self._rank = rank
        # Independent stream per (seed, rank): rank A's draws never shift
        # rank B's, so multi-rank chaos runs replay rank-locally.
        self._rng = random.Random((seed << 8) ^ ((rank if rank else 0) + 1))
        self._counts: Dict[str, int] = defaultdict(int)
        self._windows: Dict[str, float] = {}  # mode -> monotonic end time
        self._lock = threading.Lock()

    def spec(self, mode: str) -> Optional[FaultSpec]:
        return self._specs.get(mode)

    def fire(self, mode: str, step: Optional[int] = None) -> bool:
        """True iff the fault triggers for this event. Each call is one
        event of ``mode`` (its own counter supplies ``step`` when the
        caller has no natural step index)."""
        s = self._specs.get(mode)
        if s is None:
            return False
        with self._lock:
            n = self._counts[mode]
            self._counts[mode] += 1
            if s.rank is not None and self._rank is not None and s.rank != self._rank:
                return False
            if s.step is not None and (step if step is not None else n) != s.step:
                return False
            if s.prob is not None and self._rng.random() >= s.prob:
                return False
        metrics.add(f"cgx.faults.{mode}")
        # Black-box the activation: a chaos run's dump shows WHICH injected
        # fault preceded the failure it caused (lazy import — robustness
        # must stay importable before the observability package finishes).
        from ..observability import flightrec

        flightrec.record(
            "fault", mode=mode, rank=self._rank,
            event=n, step=step if step is not None else n,
        )
        return True

    def window(self, mode: str, peer: Optional[int] = None) -> bool:
        """Network fault window (``conn_reset``/``partition``): the first
        qualifying event opens a window of the spec's duration; True
        while the window is open. ``conn_reset`` gates on ``rank=``;
        ``partition`` gates on the unordered ``{self, peer}`` pair
        matching ``ranks=a,b`` (each endpoint's injector opens its own
        window on first traffic across the pair — roughly simultaneous,
        exactly like a real cut)."""
        s = self._specs.get(mode)
        if s is None:
            return False
        if s.ranks is not None:
            if self._rank is None or peer is None:
                return False
            if {self._rank, peer} != set(s.ranks):
                return False
        elif s.rank is not None and self._rank is not None:
            if s.rank != self._rank:
                return False
        if s.delay_ms <= 0:
            return False
        now = time.monotonic()
        opened = False
        with self._lock:
            end = self._windows.get(mode)
            if end is None:
                end = now + s.delay_ms / 1000.0
                self._windows[mode] = end
                opened = True
        if opened:
            metrics.add(f"cgx.faults.{mode}")
            from ..observability import flightrec

            flightrec.record(
                "fault", mode=mode, rank=self._rank, peer=peer,
                window_s=round(s.delay_ms / 1000.0, 3),
            )
            log.warning(
                "CGX_FAULTS %s window open on rank %s (%.0fms)",
                mode, self._rank, s.delay_ms,
            )
        return now < end

    def delay(self, mode: str = "delay_take") -> None:
        s = self._specs.get(mode)
        if s is not None and s.edge is not None:
            return  # edge-scoped spec: only delay_edge sites fire it
        if s is not None and s.delay_ms > 0 and self.fire(mode):
            time.sleep(s.delay_ms / 1000.0)

    def delay_edge(self, mode: str, edge: str) -> None:
        """Edge-scoped delay site (the cross-slice exchange entries): a
        spec carrying ``edge=<edge>`` fires here and ONLY here — the
        legacy per-collective :meth:`delay` site skips edge-scoped specs,
        so ``slow_rank:...@edge=dcn`` models a slow DCN link, not a rank
        slow at every collective."""
        s = self._specs.get(mode)
        if (
            s is not None and s.edge == edge and s.delay_ms > 0
            and self.fire(mode)
        ):
            time.sleep(s.delay_ms / 1000.0)

    def flap_delay(self, mode: str = "flap") -> Optional[float]:
        """Seconds to delay a header publication when the ``flap`` fault
        fires for this event, else None. The caller publishes late (a
        timer thread), modeling a transient drop that recovers — the
        defense under test is the recovery retry rung, which re-arms the
        expired bounded wait instead of escalating."""
        s = self._specs.get(mode)
        if s is not None and s.delay_ms > 0 and self.fire(mode):
            return s.delay_ms / 1000.0
        return None

    def maybe_kill(self) -> None:
        """``kill_rank``: die the way SIGKILL/OOM does — no atexit, no
        store abort, no unlinked arenas. The defenses under test must
        turn this into a bounded, named error on the surviving peers."""
        if self.fire("kill_rank"):
            log.warning(
                "CGX_FAULTS kill_rank firing on rank %s: exiting hard",
                self._rank,
            )
            os._exit(KILL_EXIT_CODE)

    def maybe_preempt(self, notify=None, step: Optional[int] = None) -> None:
        """``preempt``: the kill_rank death, preceded by a comeback
        notice and followed by an auto-respawn — the elastic join path's
        chaos rehearsal. ``notify(delay_s)`` (the call site owns the
        store; the injector has none) publishes the comeback notice the
        supervisor's rejoin rung reads; ``$CGX_PREEMPT_RESPAWN`` (a shell
        command) is spawned DETACHED before the exit and sleeps out the
        respawn delay itself, so the kill stays SIGKILL-shaped — no
        atexit, no teardown, the respawner is already a separate
        process."""
        s = self._specs.get("preempt")
        if s is None or not self.fire("preempt", step=step):
            return
        delay_s = s.delay_ms / 1000.0
        if notify is not None:
            try:
                notify(delay_s)
            except Exception as e:
                log.warning("preempt comeback notice failed: %s", e)
        respawn = os.environ.get(PREEMPT_RESPAWN_ENV, "").strip()
        if respawn:
            import subprocess

            subprocess.Popen(
                ["/bin/sh", "-c",
                 f"sleep {delay_s} && exec {respawn}"],
                start_new_session=True,
                stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL,
            )
        log.warning(
            "CGX_FAULTS preempt firing on rank %s: exiting hard, respawn "
            "in %.1fs", self._rank, delay_s,
        )
        os._exit(KILL_EXIT_CODE)

    def corrupt_join_payload(self, payload: bytes, page_ordinal: int) -> bytes:
        """``corrupt_join_page``: flip one byte of a snapshot page frame
        AFTER its checksum was computed (``step=N`` gates on the shipped
        page ordinal). The joiner's receive loop must turn this into a
        bounded page re-request — never a wedge, never silent
        divergence."""
        if not payload or not self.fire(
            "corrupt_join_page", step=page_ordinal
        ):
            return payload
        log.warning(
            "CGX_FAULTS corrupt_join_page firing on page %d", page_ordinal
        )
        buf = bytearray(payload)
        buf[len(buf) // 2] ^= 0xFF
        return bytes(buf)


# cgx-analysis: allow(orphan-memo) — injectors are keyed by the (spec, seed, rank) env contract, generation-independent by design: a recovery must not re-randomize the fault schedule under the chaos suite
_cache: Dict[Tuple[str, int, Optional[int]], FaultInjector] = {}
_cache_lock = threading.Lock()


def get_injector(rank: Optional[int] = None) -> Optional[FaultInjector]:
    """The process's injector for ``rank`` per the current ``CGX_FAULTS``
    env (None when unset/empty). Cached per (spec, seed, rank) so event
    counters and the deterministic stream persist across call sites."""
    raw = os.environ.get(FAULTS_ENV, "").strip()
    if not raw:
        return None
    seed = int(os.environ.get(FAULTS_SEED_ENV, "0") or 0)
    key = (raw, seed, rank)
    with _cache_lock:
        inj = _cache.get(key)
        if inj is None:
            inj = FaultInjector(parse_faults(raw), seed=seed, rank=rank)
            _cache[key] = inj
        return inj


def reset_injectors() -> None:
    """Drop cached injectors (tests: fresh counters/streams per case)."""
    with _cache_lock:
        _cache.clear()
