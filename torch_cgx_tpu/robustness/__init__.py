"""Fault injection + the defenses it exercises.

Three legs (see ``docs/ROBUSTNESS.md``):

* :mod:`.faults` — the deterministic ``CGX_FAULTS`` injector threaded
  through the shm channel, the torch backend, and the train step.
* :mod:`.heartbeat` — per-rank liveness files that let a bounded wait
  name its suspected dead peer instead of just expiring.
* :mod:`.errors` — the failure taxonomy (:class:`BridgeTimeoutError`,
  :class:`WireCorruptionError`), both ``RuntimeError`` subclasses.

:mod:`.guard` (the JAX-side ``nan_grad`` staging) is imported lazily by
``parallel/grad_sync`` — this package root stays importable without a
working accelerator runtime.
"""

from .errors import BridgeTimeoutError, WireCorruptionError
from .faults import (
    FaultInjector,
    FaultSpec,
    get_injector,
    parse_faults,
    reset_injectors,
)
from .heartbeat import Heartbeat, ensure_heartbeat, suspect_dead_pids

__all__ = [
    "BridgeTimeoutError",
    "WireCorruptionError",
    "FaultInjector",
    "FaultSpec",
    "get_injector",
    "parse_faults",
    "reset_injectors",
    "Heartbeat",
    "ensure_heartbeat",
    "suspect_dead_pids",
]
