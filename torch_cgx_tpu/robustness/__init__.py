"""Fault injection + the defenses it exercises + the recovery supervisor.

Five legs (see ``docs/ROBUSTNESS.md``):

* :mod:`.faults` — the deterministic ``CGX_FAULTS`` injector threaded
  through the shm channel, the torch backend, and the train step.
* :mod:`.heartbeat` — per-rank liveness files that let a bounded wait
  name its suspected dead peer instead of just expiring.
* :mod:`.errors` — the failure taxonomy (:class:`BridgeTimeoutError`,
  :class:`WireCorruptionError`, :class:`StaleGenerationError`,
  :class:`EvictedError`, :class:`RecoveryFailedError`), all
  ``RuntimeError`` subclasses.
* :mod:`.supervisor` — the per-rank recovery state machine (retry →
  degrade → evict/reconfigure → rollback/replay policy ladder) that
  turns the detected failures above into recoverable events.
* :mod:`.rendezvous` — the store-based generation agreement the
  supervisor's eviction rung runs (survivor set, degrade flag, ack
  barrier).

:mod:`.guard` (the JAX-side ``nan_grad`` staging) is imported lazily by
``parallel/grad_sync``; :mod:`.supervisor` / :mod:`.rendezvous` load
lazily too — this package root stays importable without the
observability package (and certainly without an accelerator runtime).
"""

from .errors import (
    BridgeTimeoutError,
    EvictedError,
    JoinAbortedError,
    RecoveryFailedError,
    StaleGenerationError,
    WireCorruptionError,
)
from .faults import (
    FaultInjector,
    FaultSpec,
    get_injector,
    parse_faults,
    reset_injectors,
)
from .heartbeat import Heartbeat, ensure_heartbeat, suspect_dead_pids

# Only modules NOT already bound by the eager imports above: the import
# system sets `faults`/`heartbeat`/`errors` as package attributes when
# the from-imports run, so __getattr__ never fires for those.
_LAZY = ("supervisor", "rendezvous", "retry", "elastic")


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "BridgeTimeoutError",
    "WireCorruptionError",
    "StaleGenerationError",
    "EvictedError",
    "JoinAbortedError",
    "RecoveryFailedError",
    "FaultInjector",
    "FaultSpec",
    "get_injector",
    "parse_faults",
    "reset_injectors",
    "Heartbeat",
    "ensure_heartbeat",
    "suspect_dead_pids",
    "supervisor",
    "rendezvous",
]
