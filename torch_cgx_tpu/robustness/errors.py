"""Failure taxonomy of the hardened data plane.

Both exceptions subclass :class:`RuntimeError` so every existing
``except RuntimeError`` site (and torch's Work-future plumbing, which
re-raises worker exceptions verbatim) keeps working; catching the
specific type is opt-in for callers that want to distinguish transport
death from data corruption.
"""

from __future__ import annotations

from typing import Optional, Sequence


class BridgeTimeoutError(RuntimeError):
    """A bounded wait on the bridge expired: the peer a collective was
    matched against never produced (or never acked) its payload.

    ``key`` is the store/shm key the wait was parked on (or the arena
    ack key for writer-side pressure); ``suspects`` lists ranks whose
    liveness heartbeat was missing or stale when the deadline fired —
    the "who is dead" half the raw hang never told you.
    """

    def __init__(
        self,
        message: str,
        *,
        key: Optional[str] = None,
        suspects: Sequence[int] = (),
    ):
        super().__init__(message)
        self.key = key
        self.suspects = tuple(suspects)


class AsyncStalenessError(BridgeTimeoutError):
    """The asynchronous cross-slice plane's bounded-staleness gate
    tripped: a peer slice fell more than ``CGX_ASYNC_MAX_LAG`` outer
    rounds behind this slice's outer round, and its deltas are no longer
    arriving. Subclasses :class:`BridgeTimeoutError` so the recovery
    supervisor's ladder (``RECOVERABLE``) treats it exactly like an
    expired bridge wait — with ``suspects`` naming the lagging slice's
    leader, the eviction vote has its evidence before any bridge timeout
    could have fired (the async plane never blocks on DCN, so a bridge
    timeout never WOULD fire).

    ``lag`` carries the observed staleness in outer rounds; ``round`` the
    emitting slice's outer round when the bound tripped.
    """

    def __init__(
        self,
        message: str,
        *,
        key: Optional[str] = None,
        suspects: Sequence[int] = (),
        lag: int = 0,
        round: int = 0,
    ):
        super().__init__(message, key=key, suspects=suspects)
        self.lag = int(lag)
        self.round = int(round)


class WireCorruptionError(RuntimeError):
    """A payload failed its wire checksum twice (one fresh re-read
    included): the bytes in the shared-memory arena do not match what the
    writer framed. Distinct from quantization error — this is transport
    damage, and the collective's result would be garbage."""


class StaleGenerationError(RuntimeError):
    """A message (or queued work entry) from a pre-recovery generation
    reached a group that has since reconfigured. Discarding it instead of
    decoding it is the whole point of the generation tag: a stale payload
    aliasing into the new group's matching collective would silently
    corrupt the reduction.

    ``found``/``current`` carry the message's and the group's generation.
    """

    def __init__(
        self,
        message: str,
        *,
        found: Optional[int] = None,
        current: Optional[int] = None,
    ):
        super().__init__(message)
        self.found = found
        self.current = current


class EvictedError(RuntimeError):
    """The recovery rendezvous converged on a survivor set that does not
    include this rank: a quorum of peers suspected it dead (stale
    heartbeat during their bounded waits). The correct reaction is to
    exit — the group has already moved to a new generation without us;
    with ``CGX_ELASTIC`` on, a fresh process may re-enter through the
    join rendezvous (``robustness/elastic.py``) at a later generation —
    this *process* is still done (``docs/ROBUSTNESS.md`` Elastic
    membership)."""


class JoinAbortedError(RuntimeError):
    """An elastic join attempt did not complete within
    ``CGX_JOIN_TIMEOUT_MS``. Raised on whichever side timed out: the
    joiner (admit record or snapshot pages never arrived — it aborts
    ALONE; the survivors have not reconfigured yet and keep stepping at
    the old generation) or a survivor (the joiner's ack never landed —
    the grow is abandoned and the group resumes unharmed). Never
    recoverable in-place: a fresh join attempt starts from a fresh
    intent."""


class RecoveryFailedError(RuntimeError):
    """The recovery ladder ran out of rungs: retries exhausted, and the
    generation rendezvous could not converge (survivors stopped voting /
    acking within its deadline). The job is no longer recoverable
    in-place — surface the original failure semantics (die loudly) rather
    than risk a split-brain group."""
