"""Self-healing recovery supervisor: the policy ladder over the bridge.

PR 1 gave the data plane *detection* — bounded waits, heartbeats, wire
checksums — but every detected fault was still terminal: a
``BridgeTimeoutError`` propagated out of the Work future and the job
died, exactly the all-or-nothing failure model the reference inherits
from MPI. This module turns those raises into a recoverable event. Per
rank, a :class:`RecoverySupervisor` drives training steps through a
four-rung policy ladder:

1. **Retry** (``CGX_RECOVERY_RETRIES`` / ``CGX_RECOVERY_BACKOFF_MS``) —
   lives INSIDE the data plane (``backend._wait_key`` /
   ``ShmChannel._bounded_get``): an expired bounded wait with no
   heartbeat-named suspect is re-armed with exponential backoff +
   jitter. Transient faults (``flap``, ``slow_rank``) heal locally, with
   zero cross-rank coordination and zero wire change.
2. **Degrade** (``CGX_RECOVERY_CORRUPT_THRESHOLD``) — repeated
   ``WireCorruptionError`` marks the shm byte plane untrustworthy; the
   supervisor's next rendezvous carries a degrade vote and every
   survivor drops to the store transport together.
3. **Evict + reconfigure** — on an unrecoverable timeout the suspects
   named by the heartbeat go into a store-based generation rendezvous
   (:mod:`.rendezvous`); the agreed survivor set rebuilds the group IN
   PLACE (:meth:`ProcessGroupCGX.reconfigure`) at a bumped generation:
   all store keys move to the ``g<N>/`` namespace, shm headers carry the
   epoch tag and stale traffic is discarded, SRA/Ring chunk splits
   re-derive from the shrunk world size, and the JAX-side layout/trace
   caches are invalidated through the registry version they key on.
4. **Rollback + replay** (``CGX_SNAPSHOT_EVERY``) — the step driver
   rolls the training state back to the **rendezvous-agreed** replay
   step (each vote carries the voter's newest snapshot step; the
   decision pins the minimum, because survivors can drift whole steps
   apart around a fault) and deterministically replays from the matching
   in-memory snapshot (``checkpoint.snapshot_in_memory``,
   compression-registry included); with stochastic rounding off the
   replayed steps are bit-identical to a fault-free survivor-only run
   (tested in ``tests/test_supervisor.py``).

With every recovery knob unset the supervisor is inert and nothing in
the data plane changes: generation stays 0 (legacy key/header bytes),
no snapshots are taken, failures raise exactly as in PR 1.
"""

from __future__ import annotations

import dataclasses
import sys
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .. import config as cfg
from ..observability import flightrec
from ..observability import health as health_mod
from ..observability import timeline
from ..utils.logging import get_logger, metrics
from . import rendezvous as rdz
from .errors import (
    BridgeTimeoutError,
    RecoveryFailedError,
    StaleGenerationError,
    WireCorruptionError,
)

log = get_logger()

RECOVERABLE = (BridgeTimeoutError, WireCorruptionError, StaleGenerationError)


@dataclasses.dataclass(frozen=True)
class RecoveryPolicy:
    """Knobs of the ladder (all env-derived by default)."""

    retries: int = 0
    backoff_ms: float = 100.0
    corrupt_threshold: int = 2
    snapshot_every: int = 0
    snapshot_keep: int = 4  # rollback points retained (see recover())
    max_generations: int = 8  # ladder depth bound: evictions per run
    rendezvous_timeout_s: Optional[float] = None  # None = derived

    @classmethod
    def from_env(cls) -> "RecoveryPolicy":
        return cls(
            retries=cfg.recovery_retries(),
            backoff_ms=cfg.recovery_backoff_ms(),
            corrupt_threshold=cfg.recovery_corrupt_threshold(),
            snapshot_every=cfg.snapshot_every(),
        )

    def derived_rendezvous_timeout_s(self) -> float:
        """Long enough for the slowest survivor to exhaust its own retry
        rung and reach the rendezvous: (retries + 1) bridge timeouts,
        doubled for scheduling slack, floor 10 s."""
        if self.rendezvous_timeout_s is not None:
            return self.rendezvous_timeout_s
        bt = cfg.bridge_timeout_ms()
        per_wait = (bt / 1000.0) if bt else 300.0
        return max(10.0, 2.0 * per_wait * (self.retries + 1) + 5.0)


def invalidate_trace_caches() -> None:
    """World-size shrink invalidation: bump the config registry version —
    the key every trace-time cache (``make_train_step``'s build cache,
    ``allreduce._tree_layout``'s LRU) already includes — and clear the
    layout LRU outright when the JAX side is loaded, along with the
    flightrec qerr subsample cadence (post-recovery programs are a new
    qerr stream; stale per-layer counters would subsample it on the dead
    generation's phase). Lazy: a torch-only bridge process must not
    import jax here."""
    cfg._bump_registry_version()
    if "torch_cgx_tpu.parallel.allreduce" in sys.modules:
        ar = sys.modules["torch_cgx_tpu.parallel.allreduce"]
        ar.invalidate_layout_cache("recovery reconfigure")
        ar.reset_qerr_sampling()
    elif "torch_cgx_tpu.parallel.schedule" in sys.modules:
        # allreduce.invalidate_layout_cache drops compiled schedules too;
        # this arm covers a process that loaded the schedule compiler
        # without the tree-allreduce layer (a stale chunk plan after a
        # reconfigure would wedge the pipelined in-flight window against
        # peers running the fresh world's plan).
        sys.modules["torch_cgx_tpu.parallel.schedule"].invalidate_schedule_cache(
            "recovery reconfigure"
        )
    # Step plans sit above the layout/schedule LRUs they were solved
    # for; the allreduce arm cascades into the planner already, so this
    # arm covers only a process that loaded the planner without the
    # tree-allreduce layer (the eager planned-program plane).
    if "torch_cgx_tpu.parallel.allreduce" not in sys.modules:
        planner = sys.modules.get("torch_cgx_tpu.parallel.planner")
        if planner is not None:
            planner.invalidate_plan_cache("recovery reconfigure")
    # Codec autotune memo: entries themselves are chip-keyed (world-size
    # independent), but the memo is a trace-time cache like the layout
    # and schedule LRUs — drop it with them so post-recovery traces
    # re-read the persisted state instead of serving the dead
    # generation's in-memory image (cgx.codec.autotune_invalidations).
    if "torch_cgx_tpu.ops.autotune" in sys.modules:
        sys.modules["torch_cgx_tpu.ops.autotune"].invalidate(
            "recovery reconfigure"
        )
    # Producer-fuse context: the configured mesh/axis name the dead
    # generation and stashed pre-quantized payloads hold retired traces'
    # tracers — deactivate and re-epoch so the first post-recovery build
    # reconfigures from the survivor mesh (the ISSUE 14 cascade pass
    # found this module unreachable from the ladder).
    fp = sys.modules.get("torch_cgx_tpu.ops.fused_producer")
    if fp is not None:
        fp.invalidate("recovery reconfigure")
    # The health engine's per-peer wait state is a pre-recovery stream
    # too: an evicted peer whose wait EWMA froze at the timeout value
    # would otherwise re-emit a phantom straggler event every cooldown
    # window for the rest of the run.
    health_mod.forget_peers()
    # Wire plane: derived per-edge state (resolution memo, the
    # dispatcher's numel/bits side table, EF zeroers and the closed-loop
    # controller's cadence) is a pre-recovery stream too — a stale edge
    # cadence after a reconfigure mirrors the qerr-cadence bug above.
    # Registered edge CONFIGS survive (they are configuration, not
    # state); config.reset_registries is the stronger reset.
    wire_edges = sys.modules.get("torch_cgx_tpu.wire.edges")
    if wire_edges is not None:
        wire_edges.reset_edge_state("recovery reconfigure")
    # Serving plane (PR 15): the decode-program LRU bakes page-pool
    # geometry and per-layer kv_page wire specs, and every live
    # PagedKvCache's page tables map sequences onto pool rows — both are
    # dead-generation state after a reconfigure. The generation bump the
    # page-table invalidation performs is what forces the scheduler to
    # drop its lanes and re-prefill (a stale page mapping must never be
    # gathered into a post-recovery decode step).
    serving_sched = sys.modules.get("torch_cgx_tpu.serving.scheduler")
    if serving_sched is not None:
        serving_sched.invalidate_decode_cache("recovery reconfigure")
    serving_kv = sys.modules.get("torch_cgx_tpu.serving.kv_cache")
    if serving_kv is not None:
        serving_kv.invalidate_page_tables("recovery reconfigure")
    # Topology classification memo: keyed on (mesh, axes, classifier fn),
    # none of which move when an eviction shrinks the world under an
    # unchanged mesh object — a stale hit can name an evicted rank as a
    # cross-slice leader (the PR 13 regression class).
    topo = sys.modules.get("torch_cgx_tpu.parallel.topology")
    if topo is not None:
        topo.invalidate_classification_cache("recovery reconfigure")
    # Async cross-slice plane: per-peer round bookkeeping and the pending
    # delta buffer describe the dead generation's membership — the plane
    # re-derives slice leaders from the survivor host map at the bumped
    # generation on its next outer boundary.
    async_plane = sys.modules.get("torch_cgx_tpu.parallel.async_plane")
    if async_plane is not None:
        async_plane.reset_planes("recovery reconfigure")
    # Critical-path analysis memo (ISSUE 17): a cached DAG attributes
    # against the dead generation's tracks — post-recovery spans land at
    # a bumped generation tag and must re-analyze from scratch.
    critpath = sys.modules.get("torch_cgx_tpu.observability.critpath")
    if critpath is not None:
        critpath.invalidate_critpath_cache("recovery reconfigure")
    # Memory ledger (ISSUE 18): the alloc/release window streams and
    # pool free-level trends describe the dead generation's regime —
    # carrying them across the epoch bump would fabricate a leak (the
    # abandoned arena regions release in a burst) or a phantom
    # exhaustion trend out of the reconfigure itself.
    mem = sys.modules.get("torch_cgx_tpu.observability.memledger")
    if mem is not None:
        mem.reset_ledger("recovery reconfigure")
    metrics.add("cgx.recovery.trace_cache_invalidations")


# Live supervisors, for the memory ledger's snapshot-ring sampler (the
# ledger never holds a strong ref — a torn-down supervisor must stay
# collectable). Dead supervisors self-evict.
# cgx-analysis: allow(orphan-memo) — weak liveness set: each member's snapshot ring is bounded by policy.snapshot_keep and drains with its owner; clearing the set itself would only blind the memory ledger to live rings
_LIVE_SUPERVISORS: "weakref.WeakSet" = weakref.WeakSet()


class RecoverySupervisor:
    """Per-rank recovery state machine layered over one
    :class:`~..torch_backend.backend.ProcessGroupCGX`.

    The supervisor owns the group handle (``.group``) because recovery
    can rebuild it; user code addresses peers by GLOBAL rank
    (``.global_rank``, ``.survivors``) which is stable across
    reconfigurations. Collectives must be driven synchronously through
    :meth:`run_steps` (one step's collectives complete before the next
    is issued) — the reconfiguration contract of
    ``ProcessGroupCGX.reconfigure``.
    """

    def __init__(
        self,
        store,
        group,
        *,
        policy: Optional[RecoveryPolicy] = None,
    ):
        self._store = store
        self._group = group
        self._policy = policy or RecoveryPolicy.from_env()
        self._corruptions = 0
        self._degraded = False
        # step -> checkpoint.MemorySnapshot, insertion-ordered, bounded
        # to policy.snapshot_keep. More than one is retained because the
        # rendezvous may pin the group's replay step BEHIND this rank's
        # newest snapshot (a rank whose collectives were all send-side
        # can run whole steps past a dead peer before anything blocks).
        self._snapshots: Dict[int, Any] = {}
        self._last_rollback_step: Optional[int] = None
        # Live health plane (PR 6): sustained straggler scores arrive as
        # suspect *hints* — evidence gathered BEFORE any bridge timeout
        # fires, merged into the eviction vote when the ladder runs.
        # global rank -> (monotonic receive time, score).
        self._suspect_hints: Dict[int, Tuple[float, float]] = {}
        # Elastic join plane (robustness/elastic.py): when a coordinator
        # is attached, run_steps gives it every step boundary — grow
        # decisions are step-synchronized across survivors.
        self._elastic = None
        health_mod.add_consumer(self.note_health_event)
        _LIVE_SUPERVISORS.add(self)

    # -- introspection ----------------------------------------------------

    @property
    def group(self):
        return self._group

    @property
    def policy(self) -> RecoveryPolicy:
        return self._policy

    @property
    def generation(self) -> int:
        return self._group.generation

    @property
    def global_rank(self) -> int:
        return self._group.global_rank

    @property
    def survivors(self) -> List[int]:
        return self._group.global_ranks

    @property
    def last_snapshot(self):
        if not self._snapshots:
            return None
        return self._snapshots[max(self._snapshots)]

    @property
    def last_rollback_step(self) -> Optional[int]:
        return self._last_rollback_step

    def attach_elastic(self, coordinator) -> None:
        """Hook an :class:`~.elastic.ElasticCoordinator` into the step
        loop (called by its constructor)."""
        self._elastic = coordinator

    # -- health hints (the observability→control handoff, PR 6) -----------

    HINT_TTL_S = 60.0

    def note_health_event(self, event) -> None:
        """Health-engine consumer (registered in ``__init__`` when the
        engine is running): a sustained straggler score against a peer —
        or an ``async_lag`` event naming a slice leader whose outer
        rounds stopped arriving (PR 13) — becomes suspect evidence for
        the next rendezvous, recorded in the black box the moment it
        arrives, which is typically long before any bounded wait expires
        (for async_lag, before any wait even EXISTS: the async plane
        never blocks on DCN)."""
        if getattr(event, "kind", None) not in ("straggler", "async_lag"):
            return
        suspect = getattr(event, "suspect", None)
        if suspect is None or suspect == self.global_rank:
            return
        self._suspect_hints[int(suspect)] = (
            time.monotonic(), float(event.value),
        )
        metrics.add("cgx.recovery.health_hints")
        flightrec.record(
            "recovery", phase="health_hint", suspect=int(suspect),
            score=float(event.value), generation=self.generation,
        )

    @property
    def suspect_hints(self) -> Dict[int, float]:
        """Fresh (within HINT_TTL_S) straggler hints: global rank ->
        score."""
        now = time.monotonic()
        # list(): the health evaluator thread inserts concurrently, and a
        # mid-iteration insert would raise exactly when a straggler event
        # fires during an active recovery vote.
        return {
            g: score for g, (t, score) in list(self._suspect_hints.items())
            if now - t <= self.HINT_TTL_S
        }

    # -- snapshots (rung 4 substrate) -------------------------------------

    def take_snapshot(self, step: int, state: Any) -> None:
        """Host-copy ``state`` as a rollback point (registry snapshot
        included — ``checkpoint.snapshot_in_memory``). The newest
        ``policy.snapshot_keep`` points are retained so a rendezvous can
        pin the replay step behind this rank's latest."""
        from .. import checkpoint as ckpt

        self._snapshots[int(step)] = ckpt.snapshot_in_memory(state, step)
        while len(self._snapshots) > max(self._policy.snapshot_keep, 1):
            del self._snapshots[min(self._snapshots)]
        metrics.add("cgx.recovery.snapshots")

    def rollback(self, to_step: Optional[int] = None):
        """(step, state) restored from a retained snapshot — the newest
        one, or exactly ``to_step`` when given (the rendezvous-agreed
        replay step); the registry snapshot is re-installed. Returns None
        when no matching snapshot exists."""
        if to_step is None:
            if not self._snapshots:
                return None
            snap = self._snapshots[max(self._snapshots)]
        else:
            snap = self._snapshots.get(int(to_step))
            if snap is None:
                return None
        from .. import checkpoint as ckpt

        state = ckpt.restore_in_memory(snap)
        metrics.add("cgx.recovery.rollbacks")
        return snap.step, state

    # -- the ladder -------------------------------------------------------

    def recover(self, exc: BaseException) -> rdz.Decision:
        """Walk rungs 2-3 for one detected failure: decide degrade vs
        evict, converge through the generation rendezvous, and
        reconfigure the group. (Rung 1 already ran inside the data plane;
        rung 4 is the caller's rollback to the returned decision's
        ``replay_step``, see :meth:`run_steps`.) Raises
        :class:`RecoveryFailedError` / :class:`EvictedError` when the
        group is beyond saving or this rank was voted out."""
        if self.generation + 1 > self._policy.max_generations:
            raise RecoveryFailedError(
                f"recovery ladder exhausted: {self.generation} generations "
                f"already spent (max_generations={self._policy.max_generations})"
            ) from exc
        suspects_local = list(getattr(exc, "suspects", ()) or ())
        globals_now = self._group.global_ranks
        suspects = [
            globals_now[r] for r in suspects_local if 0 <= r < len(globals_now)
        ]
        # Health-plane evidence: fresh sustained-straggler hints join the
        # vote — crucially covering the case where the timeout names no
        # suspect at all (cross-host peers have no heartbeat file).
        for g in sorted(self.suspect_hints):
            if g in globals_now and g not in suspects:
                suspects.append(g)
                metrics.add("cgx.recovery.health_hint_votes")
        # Rejoin rung (preferred over a bare evict when the suspect says
        # it is coming back): a preempted rank publishes a comeback
        # notice before dying. The shrink still proceeds — the group
        # cannot wait out a respawn — but the membership policy reserves
        # the rank's identity and the ladder records the softer rung, so
        # the respawned process re-enters through the elastic join at a
        # later generation instead of being forgotten.
        if cfg.elastic_enabled() and suspects:
            from . import elastic as elastic_mod

            rejoining = []
            for g in suspects:
                cb = elastic_mod.fresh_comeback(self._store, g)
                if cb is not None:
                    rejoining.append(g)
                    health_mod.membership_policy().expect_rejoin(
                        g,
                        float(cb.get("delay_s", 0.0))
                        + elastic_mod.REJOIN_GRACE_S,
                    )
            if rejoining:
                metrics.add("cgx.recovery.rejoin_rungs")
                flightrec.record(
                    "recovery", phase="rejoin_rung", suspects=rejoining,
                    generation=self.generation,
                )
                log.warning(
                    "recovery: suspect(s) %s announced a comeback — "
                    "shrinking now, rank reserved for rejoin", rejoining,
                )
        degrade_vote = False
        if isinstance(exc, WireCorruptionError):
            self._corruptions += 1
            degrade_vote = (
                not self._degraded
                and self._corruptions >= self._policy.corrupt_threshold
            )
        new_gen = self.generation + 1
        flightrec.record(
            "recovery", phase="detect", error=type(exc).__name__,
            generation=self.generation, suspects=suspects,
            degrade_vote=degrade_vote, message=str(exc)[:160],
        )
        t0 = time.perf_counter()
        decision = rdz.negotiate(
            self._store,
            generation=new_gen,
            me=self.global_rank,
            participants=globals_now,
            suspects=suspects,
            degrade=degrade_vote,
            snapshot_step=max(self._snapshots) if self._snapshots else None,
            timeout_s=self._policy.derived_rendezvous_timeout_s(),
        )
        timeline.record(
            "recovery.rendezvous", timeline.CAT_RECOVERY, t0,
            time.perf_counter() - t0, generation=new_gen,
            survivors=list(decision.survivors),
        )
        if decision.degrade and not self._degraded:
            self._group.degrade_to_store()
            self._degraded = True
        t1 = time.perf_counter()
        if decision.evicted:
            metrics.add("cgx.recovery.evictions", float(len(decision.evicted)))
        self._group.reconfigure(list(decision.survivors), new_gen)
        invalidate_trace_caches()
        # Hints served their purpose in this vote; the new generation's
        # evidence must come from post-recovery observations (an evicted
        # rank's hint would otherwise linger for HINT_TTL_S).
        self._suspect_hints.clear()
        timeline.record(
            "recovery.reconfigure", timeline.CAT_RECOVERY, t1,
            time.perf_counter() - t1, generation=new_gen,
            ws=len(decision.survivors),
        )
        if decision.evicted:
            # The black box is the eviction's audit trail: who was voted
            # out, by which generation, with what evidence before it.
            flightrec.record(
                "recovery", phase="evicted_peers",
                evicted=list(decision.evicted), generation=new_gen,
                survivors=list(decision.survivors),
            )
            flightrec.dump(reason="eviction")
        return decision

    def run_steps(
        self,
        state: Any,
        n_steps: int,
        step_fn: Callable[[Any, Any, int], Any],
        *,
        start_step: int = 0,
    ) -> Any:
        """Drive ``step_fn(group, state, step_idx) -> state`` for steps
        ``start_step .. start_step + n_steps`` through the full ladder.

        ``step_fn`` must treat ``state`` as read-only input and return the
        next state (on a failed step the returned value is discarded and
        the step re-runs from the rollback snapshot — in-place mutation
        would leak the failed attempt into the replay). Snapshots are
        taken every ``policy.snapshot_every`` steps, before the step runs.
        """
        step = start_step
        end = start_step + n_steps
        every = self._policy.snapshot_every
        while step < end:
            try:
                if self._elastic is not None:
                    # Elastic grow point: runs BEFORE the snapshot so a
                    # commit's grid-snapped state is what gets retained,
                    # and inside the try so a post-commit ready-barrier
                    # wedge walks the normal ladder (the joiners become
                    # the suspects).
                    state = self._elastic.on_step_boundary(state, step)
                # Cadence on the ABSOLUTE step index: a joiner's
                # run_steps starts mid-run (start_step = the join step),
                # and the rendezvous pins replay to the MINIMUM voted
                # snapshot step — survivors and joiners must snapshot
                # the same steps or a post-join recovery pins a point
                # the joiner never took.
                if every and step % every == 0:
                    self.take_snapshot(step, state)
                state = step_fn(self._group, state, step)
            except RECOVERABLE as e:
                log.warning(
                    "recovery: step %d failed with %s — running the "
                    "ladder", step, type(e).__name__,
                )
                decision = self.recover(e)
                target = decision.replay_step
                rb = self.rollback(target)
                if rb is None and target is not None:
                    # The survivors agreed to replay from `target` but
                    # this rank no longer retains that snapshot (it ran
                    # whole steps past the fault — send-only collectives
                    # never blocked — and aged the point out of the
                    # ring). Replaying from anywhere else would pair
                    # wrong-step payloads under identical post-recovery
                    # keys: die loudly instead.
                    raise RecoveryFailedError(
                        f"survivors agreed to replay from step {target} "
                        f"but this rank retains snapshots "
                        f"{sorted(self._snapshots) or 'none'} — "
                        "deterministic replay is impossible (raise "
                        "snapshot_keep or CGX_SNAPSHOT_EVERY cadence)"
                    ) from e
                if rb is not None:
                    replay_from, state = rb
                    self._last_rollback_step = replay_from
                    metrics.add(
                        "cgx.recovery.replayed_steps",
                        float(step - replay_from),
                    )
                    flightrec.record(
                        "recovery", phase="rollback", from_step=step,
                        to_step=replay_from, generation=self.generation,
                    )
                    timeline.instant(
                        "recovery.rollback", from_step=step,
                        to_step=replay_from, generation=self.generation,
                    )
                    step = replay_from
                else:
                    flightrec.record(
                        "recovery", phase="resume_no_snapshot",
                        step=step, generation=self.generation,
                    )
                continue
            step += 1
        return state
