"""Rung 1 of the recovery ladder: the bounded-wait retry policy.

Shared by every bounded wait in the data plane — the backend's
``_wait_key`` store park and the standalone ``ShmChannel``'s header poll
— so the backoff curve, the telemetry, and the named-dead-suspect
short-circuit live in exactly one place (the two call sites had started
to diverge when each carried its own copy).

The rung is local by construction: re-arming an expired wait needs no
cross-rank coordination and changes no wire byte, which is why it sits
below the rendezvous-coordinated rungs in ``docs/ROBUSTNESS.md``. With
``CGX_RECOVERY_RETRIES`` unset (the default) :meth:`WaitRetry.attempt`
always returns False and the wait raises exactly as it did pre-recovery.
"""

from __future__ import annotations

import random
import time
from typing import Sequence

from .. import config as cfg
from ..observability import flightrec
from ..observability import timeline
from ..utils.logging import metrics

_BACKOFF_CAP_S = 5.0


class WaitRetry:
    """Per-wait retry state (``CGX_RECOVERY_RETRIES`` /
    ``CGX_RECOVERY_BACKOFF_MS``): exponential backoff with up-to-50%
    uniform jitter so retrying ranks do not stampede the store in
    lockstep. Construct one per logical wait; every expired deadline
    calls :meth:`attempt` once."""

    def __init__(
        self,
        op: str,
        retries: int | None = None,
        backoff_ms: float | None = None,
    ):
        # Explicit budgets let other ladders (the transport plane's
        # reconnect rung rides CGX_TRANSPORT_RETRIES) reuse the one
        # backoff/jitter/telemetry implementation without coupling their
        # defaults to the recovery knobs.
        self._op = op
        self.remaining = (
            cfg.recovery_retries() if retries is None else max(retries, 0)
        )
        self._backoff_s = (
            cfg.recovery_backoff_ms() if backoff_ms is None else backoff_ms
        ) / 1000.0

    def attempt(self, key: str, suspects: Sequence[int] = ()) -> bool:
        """One expired bounded wait. True: a backoff was slept and the
        caller re-arms its deadline and waits again. False: the rung is
        exhausted — or a heartbeat-named ``suspects`` short-circuits it
        (a SIGKILL'd peer will not come back, and the supervisor's
        eviction rung needs the error promptly) — and the caller raises.
        """
        if self.remaining <= 0 or suspects:
            return False
        self.remaining -= 1
        pause = self._backoff_s * (1.0 + random.random() * 0.5)
        self._backoff_s = min(self._backoff_s * 2, _BACKOFF_CAP_S)
        metrics.add("cgx.recovery.retries")
        flightrec.record(
            "recovery_retry", op=self._op, key=key,
            remaining=self.remaining, backoff_s=round(pause, 4),
        )
        timeline.record(
            "recovery.retry", timeline.CAT_RECOVERY,
            time.perf_counter(), pause, key=key,
        )
        time.sleep(pause)
        return True
