"""Edge dispatcher: every collective payload through one codec surface.

Call sites in ``parallel/{moe,ring_attention,pipeline,powersgd}.py`` send
their wire payloads through :func:`wire_ppermute` /
:func:`wire_all_to_all` / :func:`wire_factor_allreduce` instead of bare
``lax`` collectives (``tools/lint.py`` enforces this). Each call resolves
its ``(edge_kind, name)`` against the edge registry (:mod:`.edges`) and
either

* lowers to the PLAIN collective (no config resolves, the payload is
  below ``CGX_COMPRESSION_MINIMAL_SIZE``, or ``CGX_WIRE`` disengages) —
  byte-identical to the pre-wire code, or
* compresses inside the staged program: quantize → collective →
  dequantize through the same ``ops.dispatch`` codec the SRA/Ring
  reducers use (Pallas on TPU, XLA elsewhere; zero host callbacks — the
  jaxpr guard in tests/test_wire.py pins this), with PowerSGD low-rank
  and top-k sparsification available as peer compressors behind the same
  surface, and optional per-edge error feedback for aggressive
  bit-widths (state threaded explicitly by the caller).

Backward passes are straight-through: the cotangent rides the same
compressed transport over the inverse permutation/reshard (the
``reducers.quantized_ppermute`` convention).
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import config as cfg_mod
from ..config import CompressionConfig
from ..ops import dispatch as ops_dispatch
from ..utils.logging import metrics
from . import edges


def engaged() -> bool:
    """Whether the dispatcher may compress: ``CGX_WIRE=on`` anywhere,
    ``auto`` (the default) only on a real TPU backend — so every CPU/CI
    path with the knob unset lowers each edge to its plain collective
    (programs bit-identical; the inertness suite pins this), ``off``
    never."""
    mode = cfg_mod.wire_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    return ops_dispatch._on_tpu()


def _active_cc(ec: Optional[edges.EdgeConfig], x) -> Optional[edges.EdgeConfig]:
    """The edge config that will actually compress this payload, or None
    (raw): engagement, bits-enabled, the dummy-codec debug knob and the
    minimal-size floor all mirror the reducers' own gates so a fallback
    here is byte-identical to the plain collective."""
    if ec is None:
        return None
    if cfg_mod.dummy_compression() or x.size < cfg_mod.minimal_size():
        return None
    if ec.compressor == edges.COMPRESSOR_QUANTIZE and not ec.cc.enabled:
        return None
    return ec


# Trace-time side table for the closed-loop controller: every compressed
# edge records its element count and current width under the same
# "wire:<kind>:<name>" label its qerr stream reports under, so
# ``controller.WireController`` can rebuild LayerStats from live
# telemetry without a host pass over the tensors.
_EDGE_INFO: Dict[str, Dict[str, int]] = {}


def edge_info() -> Dict[str, Dict[str, int]]:
    """Copy of the per-edge (numel, bits) side table (controller/tests)."""
    return {k: dict(v) for k, v in _EDGE_INFO.items()}


def reset_edge_tables() -> None:
    """Post-recovery reset (``edges.reset_edge_state``): retraced programs
    are a new edge stream; the dead generation's table must not feed the
    controller."""
    _EDGE_INFO.clear()


def edge_label(kind: str, name: str) -> str:
    return f"wire:{kind}:{name}"


def _note_edge(
    kind: str,
    name: str,
    ec: edges.EdgeConfig,
    numel: int,
    wire_bytes: Optional[float] = None,
) -> None:
    """Trace-time accounting (once per compiled program, the
    ``cgx.trace.*`` convention): per-kind raw/wire byte counters feeding
    the report/cgx_top wire ratios, the flight-recorder/timeline
    structure event, and the controller's side table. ``wire_bytes``
    overrides the estimate for compressors whose payload the generic
    model cannot see (powersgd factors)."""
    cc = ec.cc
    raw_b = numel * 4
    bits = 0
    if wire_bytes is not None:
        wire_b = wire_bytes
    elif ec.compressor == edges.COMPRESSOR_QUANTIZE:
        nb = -(-numel // cc.bucket_size)
        wire_b = numel * cc.bits / 8 + nb * 8
        bits = cc.bits
    else:  # topk: int32 index + f32 value per shipped coordinate
        k = max(1, int(np.ceil(ec.ratio * numel)))
        wire_b = 8 * k
    metrics.add("cgx.wire.edges_compressed")
    metrics.add(f"cgx.wire.bytes_raw.{kind}", float(raw_b))
    metrics.add(f"cgx.wire.bytes_wire.{kind}", float(wire_b))
    _EDGE_INFO[edge_label(kind, name)] = {"numel": numel, "bits": bits}
    from ..observability import flightrec, timeline

    rec = dict(
        edge=kind,
        edge_name=name,
        compressor=ec.compressor,
        elems=numel,
        bits=bits,
        wire_ratio=round(raw_b / wire_b, 3) if wire_b else 0.0,
    )
    flightrec.record("wire_edge", **rec)
    timeline.instant("wire_edge", **rec)


def note_external_edge(
    kind: str,
    name: str,
    *,
    numel: int,
    bits: int,
    raw_bytes: float,
    wire_bytes: float,
) -> None:
    """Per-payload accounting for edges whose bytes move OUTSIDE a staged
    collective — the serving plane's KV pages travel through a host
    transport, not a ``lax`` primitive, so :func:`_note_edge`'s
    trace-time convention (once per compiled program, with flightrec/
    timeline structure events) doesn't fit. This updates the same
    ``cgx.wire.bytes_{raw,wire}.<kind>`` counters the report/top wire
    ratios scan and the same (numel, bits) side table the closed-loop
    controllers rebuild LayerStats from — one telemetry surface
    regardless of which plane moved the bytes."""
    edges._check_kind(kind)
    metrics.add(f"cgx.wire.bytes_raw.{kind}", float(raw_bytes))
    metrics.add(f"cgx.wire.bytes_wire.{kind}", float(wire_bytes))
    _EDGE_INFO[edge_label(kind, name)] = {
        "numel": int(numel), "bits": int(bits)
    }


def _stage_qerr(label: str, x, rt) -> Optional[jax.Array]:
    """CGX_QERR_STATS: stage this edge's relative-L2 round-trip error into
    the live ``cgx.qerr.<label>`` histogram — the same stream the
    closed-loop controller consumes for dp_grad layers, so wire edges
    join the bit-allocation problem. Two hazards the allreduce qerr hook
    never faces, because wire edges sit inside *differentiated* forward
    passes: (1) ``io_callback`` has no JVP rule, so its input is
    ``stop_gradient``-ed off the tangent path; (2) scan partial eval
    (grad through the pipeline hops) DCEs effectful equations with
    unused outputs, so the callback RETURNS the error and the caller
    must anchor that returned value into its live dataflow via
    :func:`_attach_qerr` (measured: without the anchor, grad-of-scan
    silently delivers nothing). Returns None when the knob is off
    (nothing staged — the clean program is unchanged)."""
    if not cfg_mod.qerr_stats():
        return None
    from jax.experimental import io_callback

    from ..ops.codec import relative_l2_error

    err = lax.stop_gradient(relative_l2_error(x, rt).astype(jnp.float32))

    def _sink(v, label=label):
        metrics.observe(f"cgx.qerr.{label}", float(v))
        return v

    return io_callback(
        _sink, jax.ShapeDtypeStruct((), jnp.float32), err, ordered=False
    )


def _attach_qerr(out: jax.Array, err: Optional[jax.Array]) -> jax.Array:
    """Value-exact anchor for the staged qerr report: ``select(p, out,
    out)`` keeps the report's output live in the jaxpr (so no transform
    DCEs the effect) without changing a single output bit — both select
    branches are ``out``, and XLA never removes the side-effecting
    callback custom-call itself."""
    if err is None:
        return out
    return jnp.where(jnp.isfinite(err), out, out)


def init_edge_ef(x) -> jax.Array:
    """Zero per-edge error-feedback residual for ``wire_ppermute(...,
    ef=...)`` — f32, payload-shaped, PER-DEVICE (under shard_map it must
    ride a sharded carry/state slot, never a replicated one — the
    ErrorFeedbackState placement hazard applies verbatim)."""
    return jnp.zeros(jnp.shape(x), jnp.float32)


def _quantize_roundtrip(x, cc: CompressionConfig, key) -> jax.Array:
    """What this device's payload decodes to on the wire — the same
    rows=1 layout and key ``reducers.quantized_ppermute`` quantizes with,
    so the EF residual/qerr measure the exact draw the wire used."""
    q = ops_dispatch.quantize_batch(
        x.reshape(1, -1), cc, key=key if cc.stochastic else None
    )
    rt = ops_dispatch.dequantize_batch(q, out_dtype=jnp.float32)
    return lax.stop_gradient(rt.reshape(x.shape))


def _matrix_view(v) -> Tuple[int, int]:
    """(rows, cols) low-rank view of a payload: flattened leading dims x
    last dim (activations' feature dim carries the structure)."""
    return int(np.prod(v.shape[:-1])), int(v.shape[-1])


def _powersgd_eligible(v, rank: int) -> bool:
    if v.ndim < 2:
        return False
    n, m = _matrix_view(v)
    r = min(rank, n, m)
    return (n + m) * r < n * m


def _powersgd_factors(v, rank: int, key):
    """One-shot rank-r factorization of this device's payload (no
    allreduce here — the edge is point-to-point, so sender factorizes,
    receiver reconstructs): gaussian sketch -> orthonormalize -> project.
    Deterministic for key=None (fixed seed) so replays are bit-stable."""
    from ..parallel.powersgd import _orthonormalize

    n, m = _matrix_view(v)
    r = min(rank, n, m)
    mat = v.reshape(n, m).astype(jnp.float32)
    k = key if key is not None else jax.random.PRNGKey(0)
    sketch = jax.random.normal(k, (m, r), jnp.float32) / np.float32(np.sqrt(m))
    p = _orthonormalize(mat @ sketch)
    q = mat.T @ p
    return p, q


def _reconstruct(p, q, shape, dtype):
    return (p @ q.T).reshape(shape).astype(dtype)


def _ste_hop(hop_fwd, hop_bwd):
    """Straight-through wrapper: forward ships through ``hop_fwd``, the
    cotangent through ``hop_bwd`` (the same compressed transport over the
    inverse route — the quantized_ppermute convention)."""

    @jax.custom_vjp
    def f(v):
        return hop_fwd(v)

    f.defvjp(lambda v: (hop_fwd(v), None), lambda _, ct: (hop_bwd(ct),))
    return f


def wire_ppermute(
    x: jax.Array,
    axis_name: str,
    perm,
    *,
    kind: str,
    name: str = "",
    cc: Optional[CompressionConfig] = None,
    key: Optional[jax.Array] = None,
    ef: Optional[jax.Array] = None,
):
    """``lax.ppermute`` through the edge dispatcher.

    ``cc`` (explicit) bypasses the registry — the legacy ``hop_cc``
    surface of the pipeline/ulysses helpers, byte-identical to calling
    ``reducers.quantized_ppermute`` directly. Otherwise the payload
    resolves ``(kind, name)`` against the edge registry; no config (or
    ``CGX_WIRE`` disengaged) lowers to the plain ``ppermute``.

    ``ef``: per-edge error-feedback residual (f32, payload-shaped,
    per-device). When given, the call returns ``(out, ef_new)``: the
    residual is added to the payload before quantization and re-measured
    against this device's own wire decode — the aggressive-bit-width
    corrector. On a raw edge the residual passes through unchanged
    (exact wire, nothing to correct).
    """
    perm = tuple(perm)
    if cc is not None:
        if ef is not None:
            raise ValueError(
                "wire_ppermute: ef requires a registry-resolved edge — an "
                "explicit cc bypasses the per-edge EF surface (register an "
                "EdgeConfig instead)"
            )
        from ..parallel.reducers import quantized_ppermute

        return quantized_ppermute(x, axis_name, perm, cc, key=key)
    ec = _active_cc(edges.resolve_edge(kind, name) if engaged() else None, x)
    if ec is None:
        out = lax.ppermute(x, axis_name, perm)
        return (out, ef) if ef is not None else out
    inv_perm = tuple((d, s) for (s, d) in perm)
    label = edge_label(kind, name)

    if ec.compressor == edges.COMPRESSOR_QUANTIZE:
        from ..parallel.reducers import quantized_ppermute

        _note_edge(kind, name, ec, int(x.size))
        use_ef = ef is not None
        x_eff = (
            (x.astype(jnp.float32) + lax.stop_gradient(ef)).astype(x.dtype)
            if use_ef
            else x
        )
        out = quantized_ppermute(x_eff, axis_name, perm, ec.cc, key=key)
        if use_ef or cfg_mod.qerr_stats():
            rt = _quantize_roundtrip(x_eff, ec.cc, key)
            out = _attach_qerr(
                out, _stage_qerr(label, x_eff, rt.astype(x_eff.dtype))
            )
            if use_ef:
                ef_new = lax.stop_gradient(
                    x_eff.astype(jnp.float32) - rt
                )
                return out, ef_new
        return out

    if ec.compressor == edges.COMPRESSOR_POWERSGD:
        if not _powersgd_eligible(x, ec.rank):
            out = lax.ppermute(x, axis_name, perm)
            return (out, ef) if ef is not None else out
        n, m = _matrix_view(x)
        r = min(ec.rank, n, m)
        _note_edge(kind, name, ec, int(x.size), wire_bytes=(n + m) * r * 4.0)
        use_ef = ef is not None
        x_eff = (
            (x.astype(jnp.float32) + lax.stop_gradient(ef)).astype(x.dtype)
            if use_ef
            else x
        )

        def fwd(v, p_route=perm):
            p_f, q_f = _powersgd_factors(v, ec.rank, key)
            p_r = lax.ppermute(p_f, axis_name, p_route)
            q_r = lax.ppermute(q_f, axis_name, p_route)
            return _reconstruct(p_r, q_r, v.shape, v.dtype)

        out = _ste_hop(fwd, lambda ct: fwd(ct, inv_perm))(x_eff)
        if use_ef:
            p_f, q_f = _powersgd_factors(x_eff, ec.rank, key)
            rt = lax.stop_gradient(
                _reconstruct(p_f, q_f, x_eff.shape, jnp.float32)
            )
            out = _attach_qerr(
                out, _stage_qerr(label, x_eff, rt.astype(x_eff.dtype))
            )
            return out, lax.stop_gradient(x_eff.astype(jnp.float32) - rt)
        return out

    # top-k sparsification: ship the k largest-magnitude coordinates as
    # (int32 index, f32 value) pairs; receiver scatters into zeros.
    from ..parallel.topk import densify, sparsify

    _note_edge(kind, name, ec, int(x.size))
    k = max(1, int(np.ceil(ec.ratio * x.size)))
    use_ef = ef is not None
    x_eff = (
        (x.astype(jnp.float32) + lax.stop_gradient(ef)).astype(x.dtype)
        if use_ef
        else x
    )

    def fwd_tk(v, p_route=perm):
        idx, val = sparsify(v.reshape(-1).astype(jnp.float32), k)
        idx_r = lax.ppermute(idx, axis_name, p_route)
        val_r = lax.ppermute(val, axis_name, p_route)
        return densify(v.size, idx_r, val_r).reshape(v.shape).astype(v.dtype)

    out = _ste_hop(fwd_tk, lambda ct: fwd_tk(ct, inv_perm))(x_eff)
    if use_ef:
        idx, val = sparsify(x_eff.reshape(-1).astype(jnp.float32), k)
        rt = lax.stop_gradient(densify(x_eff.size, idx, val)).reshape(
            x_eff.shape
        )
        out = _attach_qerr(
            out, _stage_qerr(label, x_eff, rt.astype(x_eff.dtype))
        )
        return out, lax.stop_gradient(x_eff.astype(jnp.float32) - rt)
    return out


def wire_all_to_all(
    x: jax.Array,
    axis_name: str,
    *,
    split_axis: int,
    concat_axis: int,
    kind: str,
    name: str = "",
    cc: Optional[CompressionConfig] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """``lax.all_to_all`` (tiled) through the edge dispatcher — the MoE
    dispatch/combine and Ulysses reshard surface. Quantize-only: a
    reshard's payload is consumed immediately on arrival, so low-rank/
    sparse peer compressors (whose value is cross-step structure) are
    rejected rather than silently degraded. ``cc`` explicit bypasses the
    registry (the Ulysses ``hop_cc`` surface)."""
    if cc is not None:
        from ..parallel.reducers import quantized_all_to_all

        return quantized_all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            cc=cc, key=key,
        )
    ec = _active_cc(edges.resolve_edge(kind, name) if engaged() else None, x)
    if ec is not None:
        from ..utils import compat

        # quantized_all_to_all falls back to the plain reshard when the
        # split axis doesn't divide by the axis size — classify that case
        # as a RAW edge *here* so the accounting below never claims
        # compression for bytes that went uncompressed.
        if x.shape[split_axis] % compat.axis_size(axis_name):
            ec = None
    if ec is None:
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            tiled=True,
        )
    if ec.compressor != edges.COMPRESSOR_QUANTIZE:
        raise ValueError(
            f"edge ({kind!r}, {name!r}): compressor {ec.compressor!r} is "
            "p2p-only; all_to_all edges support 'quantize'"
        )
    from ..parallel.reducers import quantized_all_to_all
    from ..utils import compat

    _note_edge(kind, name, ec, int(x.size))
    err = None
    if cfg_mod.qerr_stats():
        # Round-trip the payload in the same (ws, -1) row layout the
        # quantized reshard quantizes; relative L2 is permutation-
        # invariant, so measuring on the rows equals measuring on x.
        ws = compat.axis_size(axis_name)
        rows = jnp.moveaxis(x, split_axis, 0).reshape(ws, -1)
        q = ops_dispatch.quantize_batch(
            rows, ec.cc, key=key if ec.cc.stochastic else None
        )
        rt = lax.stop_gradient(
            ops_dispatch.dequantize_batch(q, out_dtype=rows.dtype)
        )
        err = _stage_qerr(edge_label(kind, name), rows, rt)
    out = quantized_all_to_all(
        x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
        cc=ec.cc, key=key,
    )
    return _attach_qerr(out, err)


def wire_factor_allreduce(
    x: jax.Array,
    axes: Sequence[str],
    mesh,
    *,
    name: str = "",
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact-or-quantized allreduce of a PowerSGD factor (the
    ``powersgd_factor`` edge): no config resolves -> the plain ``psum``
    the transform always used (bit-identical); a quantize config routes
    the flattened factor through ``reducers.quantized_allreduce`` per
    axis — error-symmetric, so every device still decodes identical
    factors and the orthonormalization stays replicated."""
    ec = _active_cc(
        edges.resolve_edge(edges.EDGE_POWERSGD_FACTOR, name)
        if engaged()
        else None,
        x,
    )
    if ec is not None and ec.compressor != edges.COMPRESSOR_QUANTIZE:
        # Same loud rejection as wire_all_to_all: silently degrading a
        # misconfigured compressor to the exact psum would leave the user
        # with no signal their config was a no-op.
        raise ValueError(
            f"edge ('powersgd_factor', {name!r}): compressor "
            f"{ec.compressor!r} is p2p-only; factor allreduce edges "
            "support 'quantize'"
        )
    live_axes = [a for a in axes if mesh is None or mesh.shape[a] > 1]
    if ec is None or not live_axes:
        for a in live_axes:
            x = lax.psum(x, a)
        return x
    from ..parallel.reducers import quantized_allreduce

    _note_edge(edges.EDGE_POWERSGD_FACTOR, name, ec, int(x.size))
    flat = x.reshape(-1)
    for i, a in enumerate(live_axes):
        k = jax.random.fold_in(key, i) if key is not None else None
        flat = quantized_allreduce(
            flat, a, mesh.shape[a], ec.cc, key=k
        )
    return flat.reshape(x.shape).astype(x.dtype)
