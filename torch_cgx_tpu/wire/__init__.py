"""Unified wire plane: one compression dispatcher for every edge.

The reference compresses exactly one traffic class — the DDP gradient
allreduce — behind its per-layer config registry (ProcessGroupCGX.cc:
837-857). This subsystem generalizes that registry to per-*edge* configs
(:mod:`.edges`), routes every other collective the framework emits — MoE
all-to-all dispatch, ring-attention K/V hops, pipeline activation hops,
PowerSGD factor reductions — through the same ``ops.dispatch`` codec path
(:mod:`.dispatch`: quantize → collective → dequantize inside the staged
program, zero host callbacks), and closes the observability→control loop
(:mod:`.controller`: the live ``cgx.qerr.*`` relative-L2 stream drives
``adaptive.solve_bit_allocation`` every K steps and writes the result
back into the registries).

Everything is gated by ``CGX_WIRE`` (auto|on|off): with the knob unset
and the edge registry empty, every routed call site lowers to exactly
the plain ``lax`` collective it replaced — staged programs, store keys
and wire bytes bit-identical (docs/COMPRESSION_GUIDE.md "Every wire,
one dispatcher").
"""

from . import controller, dispatch, edges
from .controller import WireController
from .dispatch import (
    init_edge_ef,
    wire_all_to_all,
    wire_factor_allreduce,
    wire_ppermute,
)
from .edges import (
    EDGE_DP_GRAD,
    EDGE_KINDS,
    EDGE_MOE_A2A,
    EDGE_POWERSGD_FACTOR,
    EDGE_PP_ACT,
    EDGE_RING_KV,
    EDGE_XSLICE_DELTA,
    EdgeConfig,
    clear_edges,
    resolve_edge,
    set_edge_config,
)

__all__ = [
    "controller",
    "dispatch",
    "edges",
    "WireController",
    "init_edge_ef",
    "wire_all_to_all",
    "wire_factor_allreduce",
    "wire_ppermute",
    "EDGE_DP_GRAD",
    "EDGE_KINDS",
    "EDGE_MOE_A2A",
    "EDGE_POWERSGD_FACTOR",
    "EDGE_PP_ACT",
    "EDGE_RING_KV",
    "EDGE_XSLICE_DELTA",
    "EdgeConfig",
    "clear_edges",
    "resolve_edge",
    "set_edge_config",
]
