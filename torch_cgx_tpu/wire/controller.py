"""Closed-loop adaptive bit-width control from live qerr telemetry.

``parallel/adaptive.py`` solves the per-layer bit-allocation problem
from an OFFLINE host pass over a gradient tree (``measure_layer_stats``
-> ``solve_bit_allocation``). This controller closes the
observability→control loop instead: the ``cgx.qerr.*`` relative-L2
histograms the instrumented collectives already stream (dp_grad layers
via ``allreduce._report_qerr``, wire edges via
``dispatch._stage_qerr``; both need ``CGX_QERR_STATS=1``) are converted
back into the solver's error-model statistics and re-solved every K
steps, with the result written into the live registries — dp_grad
layers into the name-pattern registry, wire edges into the edge
registry. The registry-version bump both writes perform forces the next
step to retrace at the new widths, exactly like ``adapt_bits``.

Error-model conversion: the solver minimizes
``E_l(b) = numel_l * msr_l / (12 (2^b - 1)^2)``. A layer observed at
relative L2 ``rel`` while quantized at ``b_cur`` bits satisfies
``rel^2 ~ msr_unit / (12 (2^b_cur - 1)^2)`` per unit norm, so feeding
``msr_l = rel^2 * 12 * (2^b_cur - 1)^2`` makes
``E_l(b) = numel_l * rel^2 * ((2^b_cur - 1)/(2^b - 1))^2`` — the
predicted relative error at candidate width ``b``, weighted by payload
size. No gradient-norm side channel is needed: relative error is
scale-invariant.
"""

from __future__ import annotations

import dataclasses
import re
import weakref
from typing import Dict, Optional, Tuple

from .. import config as cfg_mod
from ..utils.logging import metrics
from . import dispatch, edges

_QERR_PREFIX = "cgx.qerr."

# Label prefixes OWNED by another controller objective: the default
# (unscoped) training controller must not ingest them — in a colocated
# train-and-serve process it would otherwise re-width the serving KV
# pages from the training objective, the exact cross-plane write the
# serving SLO controller's own scoping exists to prevent (it claims
# "wire:kv_page:" via label_prefix; see serving/slo.py).
_FOREIGN_OBJECTIVE_PREFIXES = ("wire:kv_page:",)

# Controllers auto-reset with the rest of the per-edge derived state
# (supervisor.invalidate_trace_caches / config.reset_registries): a
# cadence counter surviving a recovery reconfiguration would fire the
# next re-solve on the dead generation's phase — the PR 6 qerr-cadence
# bug, closed-loop edition.
# cgx-analysis: allow(orphan-memo) — weak liveness set: dead controllers self-evict, and each member's cadence/state resets through the edge-registry reset hook registered at construction
_LIVE: "weakref.WeakSet" = weakref.WeakSet()


def _reset_all() -> None:
    for c in list(_LIVE):
        c.reset()


edges.register_reset_hook(_reset_all)


class WireController:
    """Drive ``solve_bit_allocation`` from the live qerr stream.

    Host-side, called from the training loop::

        ctl = WireController(avg_bits=4, every=500)
        for step in range(n_steps):
            params, opt_state, loss = train_step(...)
            ctl.step()   # re-solves (and retraces) every 500 steps

    ``avg_bits`` — the payload-weighted average-width budget.
    ``every`` — re-solve cadence in :meth:`step` calls (0 = manual only).
    ``min_observations`` — a layer/edge needs at least this many qerr
    samples before it joins the solve (a single warm-up sample is a
    noisy basis for a retrace).

    Since the whole-step planner landed (``parallel/planner.py``), the
    sanctioned driver is ``planner.StepPlanner(avg_bits=...)``, which
    owns a controller (``every=0``) and runs this re-solve inside its own
    calibrate→plan loop — the lint ownership rule
    (``tools/lint.py check_planner_registry_ownership``) rejects new
    registry writers outside the planner; this module's ``_apply`` is the
    legacy inert path it allowlists.
    """

    def __init__(
        self,
        avg_bits: float,
        *,
        every: int = 500,
        bits_range: Tuple[int, int] = (2, 8),
        min_observations: int = 1,
        label_prefix: str = "",
    ):
        if every < 0:
            raise ValueError(f"every must be >= 0, got {every}")
        self.avg_bits = avg_bits
        self.every = every
        self.bits_range = bits_range
        self.min_observations = max(1, min_observations)
        # Objective scope: only qerr labels under this prefix join the
        # solve (and the write-back). "" = every label — the training
        # planes' whole-step budget. The serving SLO controller
        # (serving/slo.py) scopes its latency-driven budget to
        # "wire:kv_page:" so re-solving the KV width can never disturb
        # the training edges' allocation (one solver, two objectives).
        self.label_prefix = label_prefix
        self.updates = 0
        self.last_alloc: Dict[str, int] = {}
        self._count = 0
        _LIVE.add(self)

    def reset(self) -> None:
        """Drop cadence + last allocation (post-recovery / new job)."""
        self._count = 0
        self.last_alloc = {}

    def step(self) -> Optional[Dict[str, int]]:
        """Note one training step; every ``every``-th call re-solves.
        Returns the new allocation when one was applied, else None."""
        self._count += 1
        if self.every and self._count % self.every == 0:
            return self.update()
        return None

    def gather_stats(self):
        """Public alias of :meth:`_gather_stats` — the planner's
        cost-model calibration reads the same (numel, bits, qerr) tables
        this controller solves from (one telemetry surface, two
        consumers)."""
        return self._gather_stats()

    def _gather_stats(self):
        """LayerStats from the live qerr histograms + the trace-time
        (numel, bits) side tables. Only labels with a known payload and
        a quantized current width can join the error model."""
        from ..parallel import allreduce
        from ..parallel.adaptive import LayerStat

        info: Dict[str, Dict[str, int]] = {}
        info.update(allreduce.qerr_layer_info())
        info.update(dispatch.edge_info())
        hists = metrics.snapshot_typed()["histograms"]
        stats: Dict[str, LayerStat] = {}
        for hname, h in hists.items():
            if not hname.startswith(_QERR_PREFIX):
                continue
            label = hname[len(_QERR_PREFIX):]
            if self.label_prefix:
                if not label.startswith(self.label_prefix):
                    continue  # outside this controller's objective scope
            elif label.startswith(_FOREIGN_OBJECTIVE_PREFIXES):
                continue  # another objective's labels (serving KV)
            meta = info.get(label)
            if meta is None or not meta.get("bits"):
                continue  # raw or non-quantize edge: nothing to re-bit
            if h.get("count", 0) < self.min_observations:
                continue
            rel = h.get("p90", h.get("mean", 0.0)) or h.get("mean", 0.0)
            b_cur = int(meta["bits"])
            msr = float(rel) ** 2 * 12.0 * (2**b_cur - 1) ** 2
            stats[label] = LayerStat(numel=int(meta["numel"]), mean_sq_range=msr)
        return stats

    def _apply(self, alloc: Dict[str, int]) -> None:
        for label, b in alloc.items():
            if label.startswith("wire:"):
                _, kind, name = label.split(":", 2)
                cur = edges.resolve_edge(kind, name) or edges.EdgeConfig()
                edges.set_edge_config(
                    kind,
                    "^" + re.escape(name) + "$",
                    dataclasses.replace(
                        cur, cc=dataclasses.replace(cur.cc, bits=int(b))
                    ),
                )
            else:
                base = (
                    cfg_mod.resolve_pattern_config(label)
                    or cfg_mod.default_compression_config()
                )
                cfg_mod.set_layer_pattern_config(
                    "^" + re.escape(label) + "$",
                    dataclasses.replace(base, bits=int(b)),
                )
            metrics.set(f"cgx.wire.bits.{label}", float(b))

    def update(self) -> Dict[str, int]:
        """Gather -> solve -> write-back now. Returns the allocation
        ({} when no label has enough telemetry yet). Idempotent when the
        telemetry hasn't moved: the same stats solve to the same bits,
        and re-registering an identical config only costs a registry
        bump (one retrace) the first time."""
        from ..parallel.adaptive import solve_bit_allocation

        stats = self._gather_stats()
        if not stats:
            return {}
        alloc = solve_bit_allocation(
            stats, self.avg_bits, bits_range=self.bits_range
        )
        changed = alloc != self.last_alloc
        if changed:
            self._apply(alloc)
        self.last_alloc = dict(alloc)
        self.updates += 1
        metrics.add("cgx.wire.controller_updates")
        from ..observability import flightrec

        flightrec.record(
            "wire_controller",
            avg_bits=self.avg_bits,
            layers=len(alloc),
            changed=changed,
            alloc={k: int(v) for k, v in sorted(alloc.items())[:32]},
        )
        return alloc
