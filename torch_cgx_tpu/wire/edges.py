"""Per-edge compression registry.

Generalization of the reference's per-layer config registry
(``register_layer`` / the name-pattern registry in ``config.py``,
ProcessGroupCGX.cc:837-857) from gradient layers to *wire edges*: every
distinct traffic class the framework puts on the fabric is an edge kind,
and a config is keyed by ``(edge_kind, name-pattern)`` — the same
later-registration-wins regex semantics as the layer registry, and the
same registry-version bumping, so every trace/layout/schedule cache that
already keys on :func:`~torch_cgx_tpu.config.registry_version` re-derives
when an edge config changes.

Edge taxonomy (docs/COMPRESSION_GUIDE.md "Every wire, one dispatcher"):

===================  ====================================================
kind                 traffic
===================  ====================================================
``dp_grad``          data-parallel gradient allreduce (the reference's
                     only wire; resolution feeds
                     ``allreduce.resolve_leaf_config``)
``moe_a2a``          MoE expert dispatch/combine ``all_to_all``
``ring_kv``          sequence-parallel K/V traffic: ring-attention
                     ``ppermute`` hops, Ulysses reshard ``all_to_all``
``pp_act``           pipeline activation/cotangent ``ppermute`` hops
``powersgd_factor``  PowerSGD P/Q factor reductions
``xslice_delta``     asynchronous cross-slice parameter deltas: the
                     local-SGD outer loop's DCN payload
                     (``parallel/async_plane.py``), shipped by the
                     dedicated sender thread with per-edge error feedback
``kv_page``          serving-plane KV-cache pages: the fixed-size blocks
                     the paged allocator (``serving/kv_cache.py``)
                     quantizes for the disaggregated prefill→decode hop
                     and the decode scheduler's paged attention read —
                     resolved per layer, driven by the serving SLO
                     controller (``serving/slo.py``)
===================  ====================================================

Resolution order for a non-``dp_grad`` edge ``(kind, name)``:

1. the last registered ``(kind, pattern)`` whose pattern matches
   ``name`` (zeros back-filled from the env default, like the layer
   registry);
2. the ``CGX_WIRE_BITS`` env default (every routed edge at that width);
3. nothing — the edge sends raw (the dispatcher lowers to the plain
   ``lax`` collective).

``dp_grad`` entries skip step 2 (their env default remains
``CGX_COMPRESSION_QUANTIZATION_BITS``) and are consulted by
``allreduce.resolve_leaf_config`` ahead of the name-pattern registry.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Tuple

from .. import config as cfg_mod
from ..config import CompressionConfig

EDGE_DP_GRAD = "dp_grad"
EDGE_MOE_A2A = "moe_a2a"
EDGE_RING_KV = "ring_kv"
EDGE_PP_ACT = "pp_act"
EDGE_POWERSGD_FACTOR = "powersgd_factor"
EDGE_XSLICE_DELTA = "xslice_delta"
EDGE_KV_PAGE = "kv_page"
EDGE_PARAM_PAGE = "param_page"

EDGE_KINDS = (
    EDGE_DP_GRAD,
    EDGE_MOE_A2A,
    EDGE_RING_KV,
    EDGE_PP_ACT,
    EDGE_POWERSGD_FACTOR,
    EDGE_XSLICE_DELTA,
    EDGE_KV_PAGE,
    EDGE_PARAM_PAGE,
)

# Peer compressors the dispatcher can put behind an edge (max-min
# quantization is the default; PowerSGD low-rank and top-k sparsification
# ride the same surface — docs/COMPRESSION_GUIDE.md).
COMPRESSOR_QUANTIZE = "quantize"
COMPRESSOR_POWERSGD = "powersgd"
COMPRESSOR_TOPK = "topk"
COMPRESSORS = (COMPRESSOR_QUANTIZE, COMPRESSOR_POWERSGD, COMPRESSOR_TOPK)


@dataclasses.dataclass(frozen=True)
class EdgeConfig:
    """One edge's wire treatment.

    ``cc`` — the max-min quantization config (``bits``/``bucket_size``/
    stochastic, zeros back-filled from the env default at resolution).
    ``compressor`` — which scheme ships the payload: "quantize" (the
    codec), "powersgd" (rank-``rank`` low-rank factors, p2p edges only),
    or "topk" (the ``ratio`` largest-magnitude coordinates as
    index/value pairs, p2p edges only).
    ``error_feedback`` — carry a per-edge residual for aggressive
    bit-widths; callers thread the state explicitly
    (``wire_ppermute(..., ef=...)`` — docs/COMPRESSION_GUIDE.md "EF on
    wire edges").
    """

    cc: CompressionConfig = dataclasses.field(
        default_factory=lambda: CompressionConfig(bits=0, bucket_size=0)
    )
    compressor: str = COMPRESSOR_QUANTIZE
    error_feedback: bool = False
    rank: int = 4  # powersgd
    ratio: float = 0.01  # topk

    def __post_init__(self):
        if self.compressor not in COMPRESSORS:
            raise ValueError(
                f"unknown edge compressor {self.compressor!r}; expected one "
                f"of {COMPRESSORS}"
            )
        if self.rank < 1:
            raise ValueError(f"powersgd rank must be >= 1, got {self.rank}")
        if not 0.0 < self.ratio < 1.0:
            raise ValueError(
                f"topk ratio must be in (0, 1), got {self.ratio!r}"
            )

    def resolved(self) -> "EdgeConfig":
        """Zeros back-filled from the env default (the layer registry's
        ``merged_with_default`` semantics applied to the edge's cc)."""
        return dataclasses.replace(
            self, cc=self.cc.merged_with_default(
                cfg_mod.default_compression_config()
            )
        )


# (kind, pattern) -> EdgeConfig, insertion-ordered: later registrations win,
# like the name-pattern layer registry.
_edge_configs: Dict[Tuple[str, str], EdgeConfig] = {}

# Resolution memo: edge resolution runs at trace time on hot paths (every
# ring hop site, every pipeline build); (kind, name, registry version,
# env default, wire bits) -> Optional[EdgeConfig]. Bounded implicitly —
# the key space is the set of distinct edges, a handful per model.
_resolve_cache: Dict[Tuple, Optional[EdgeConfig]] = {}

# Reset hooks: owners of derived per-edge state (the controller's cadence,
# user-registered EF zeroers) register a callable; reset_edge_state() runs
# them all — the post-recovery analogue of allreduce.reset_qerr_sampling
# (a stale edge cadence after a reconfigure mirrors the PR 6 qerr bug).
# cgx-analysis: allow(orphan-memo) — registration CONFIG, not derived state: the hooks themselves are what reset_edge_state runs; clearing the list would disconnect owners from the cascade
_reset_hooks: List[Callable[[], None]] = []


def _check_kind(kind: str) -> None:
    if kind not in EDGE_KINDS:
        raise ValueError(
            f"unknown edge kind {kind!r}; expected one of {EDGE_KINDS}"
        )


def set_edge_config(kind: str, pattern: str, config: EdgeConfig) -> None:
    """Register an edge config for every edge of ``kind`` whose name
    matches ``pattern`` (regex via ``re.search``; later registrations
    win). Bumps the config registry version — every cached trace/layout
    keyed on it re-derives, so the new bits take effect on the next
    step."""
    _check_kind(kind)
    re.compile(pattern)  # validate eagerly
    if not isinstance(config, EdgeConfig):
        raise TypeError(
            f"set_edge_config expects an EdgeConfig, got {type(config)!r}"
        )
    key = (kind, pattern)
    # re-registration moves to the end (later wins), like dict re-insert
    _edge_configs.pop(key, None)
    _edge_configs[key] = config
    _resolve_cache.clear()
    cfg_mod._bump_registry_version()


def resolve_edge(kind: str, name: str) -> Optional[EdgeConfig]:
    """The config this edge sends under, or None (raw wire).

    Registered ``(kind, pattern)`` entries win (last match), then the
    ``CGX_WIRE_BITS`` env default for non-``dp_grad`` kinds. The result
    is env-back-filled (:meth:`EdgeConfig.resolved`)."""
    _check_kind(kind)
    key = (
        kind,
        name,
        cfg_mod.registry_version(),
        cfg_mod.default_compression_config(),
        cfg_mod.wire_default_bits(),
    )
    if key in _resolve_cache:
        return _resolve_cache[key]
    match: Optional[EdgeConfig] = None
    for (k, pattern), ec in _edge_configs.items():
        if k == kind and re.search(pattern, name):
            match = ec
    if match is None and kind not in (
        EDGE_DP_GRAD, EDGE_KV_PAGE, EDGE_PARAM_PAGE
    ):
        # kv_page skips the CGX_WIRE_BITS fallback like dp_grad skips it:
        # its env default is CGX_KV_BITS, consulted by the serving
        # resolver (serving/kv_cache.py resolve_kv_config) — a training
        # wire knob must not silently re-width the serving KV pages.
        # param_page likewise: its default is LOSSLESS (raw pages — the
        # joiner's bit-identity guarantee), so only an explicitly
        # registered edge may make the join wire lossy.
        bits = cfg_mod.wire_default_bits()
        if bits:
            match = EdgeConfig(cc=CompressionConfig(bits=bits, bucket_size=0))
    out = match.resolved() if match is not None else None
    _resolve_cache[key] = out
    return out


def resolve_dp_grad(path: str) -> Optional[CompressionConfig]:
    """dp_grad resolution hook for ``allreduce.resolve_leaf_config``: a
    registered dp_grad edge matching this leaf path wins over the legacy
    name-pattern registry; None falls through to it. Only the quantize
    compressor applies on the allreduce plane (PowerSGD/top-k gradients
    go through their own transforms).

    Gated on the same ``CGX_WIRE`` engagement as every other edge kind —
    "off: every edge sends raw" must mean dp_grad edge entries too, or
    the knob cannot bisect a convergence problem (the legacy
    name-pattern registry remains the ungated per-layer surface)."""
    from . import dispatch as _dispatch

    if not _dispatch.engaged():
        return None
    ec = resolve_edge(EDGE_DP_GRAD, path)
    if ec is None or ec.compressor != COMPRESSOR_QUANTIZE:
        return None
    return ec.cc


def registered_edges() -> List[Tuple[str, str, EdgeConfig]]:
    """(kind, pattern, config) rows in registration order (tooling)."""
    return [(k, p, ec) for (k, p), ec in _edge_configs.items()]


def clear_edges() -> None:
    """Drop every registered edge config (version bumped so cached
    traces from the configured era can never be hit)."""
    if _edge_configs:
        _edge_configs.clear()
        cfg_mod._bump_registry_version()
    _resolve_cache.clear()


def register_reset_hook(fn: Callable[[], None]) -> None:
    """Register a zeroer for derived per-edge state (controller cadence,
    EF stores); run by :func:`reset_edge_state`. Idempotent on identity."""
    if fn not in _reset_hooks:
        _reset_hooks.append(fn)


def reset_edge_state(reason: str = "reset") -> None:
    """Clear DERIVED per-edge state — the resolution memo, the
    dispatcher's numel/bits side table, and every registered reset hook
    (controller cadence, EF zeroers) — WITHOUT touching the registered
    configs. Called by ``supervisor.invalidate_trace_caches`` after a
    recovery reconfiguration (a stale edge cadence would mirror the PR 6
    qerr-cadence bug) and by ``config.reset_registries``."""
    import sys as _sys

    _resolve_cache.clear()
    disp = _sys.modules.get("torch_cgx_tpu.wire.dispatch")
    if disp is not None:
        disp.reset_edge_tables()
    for fn in list(_reset_hooks):
        fn()
    from ..utils.logging import metrics

    metrics.add("cgx.wire.state_resets")
    from ..utils.logging import get_logger

    get_logger().info("wire edge state reset (%s)", reason)


def cache_key_component() -> Tuple:
    """The wire plane's contribution to trace/layout cache keys: the
    engagement mode and env-default bits (registered-config changes are
    covered by the registry version those keys already carry). A
    ``CGX_WIRE``/``CGX_WIRE_BITS`` flip must retrace, never serve a
    staged program from another wire era."""
    return (cfg_mod.wire_mode(), cfg_mod.wire_default_bits())
