"""Decoupled DCN sender/receiver for the asynchronous cross-slice plane.

The synchronous bridge puts every cross-slice byte on the train step's
critical path: a collective's cross stage blocks in ``_take`` until the
slowest DCN edge answers. This module is the transport half of the PR 13
async plane (``parallel/async_plane.py``): one **dedicated sender
thread** per group drains a post queue onto the shm/store bridge, so the
train step *never* blocks on DCN — ``post()`` is an enqueue, ``poll()``
is a counter read plus gets of payloads already published, and every
wait inside the thread body is bounded (``tools/lint.py
check_async_sender_blocking`` rejects unbounded ``.result()`` /
``_wait_key`` waits in this file's sender bodies).

Wire protocol (one stream per slice, generation-namespaced by the
caller's ``ns`` function so pre-recovery rounds can never alias into a
reconfigured group — the PR 5 key discipline):

* ``cgxasync/s<slice>/n`` — a store counter, bumped AFTER the payload
  key is set (publish-after-write: a reader that observes seq ``k`` can
  get key ``k`` without waiting);
* ``cgxasync/s<slice>/<seq>`` — one outer round's framed delta:
  an 8-byte little-endian round index, then the codec wire bytes.

The ``slow_rank:...@edge=dcn`` fault (robustness/faults.py) injects its
delay inside the sender thread — the slow DCN edge slows *delivery*, not
the train step, which is the whole measurement ``bench.py --async-dcn``
commits.
"""

from __future__ import annotations

import queue as _queue
import struct
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..observability import timeline
from ..utils.logging import get_logger, metrics

log = get_logger()

_HDR = struct.Struct("<Q")  # outer round index

# Sender-loop tick: the queue wait is sliced so a stop request is honored
# within one tick even when no post ever arrives.
_TICK_S = 0.2

# A transient store error must not silently drop an outer round — every
# peer's anchor would be missing that contribution forever (EF carries
# quantization residual, not lost sends). Bounded retries with backoff;
# a round that still fails is counted + flight-recorded as a failure.
_SHIP_RETRIES = 3
_SHIP_BACKOFF_S = 0.05


def frame(round_idx: int, payload: bytes) -> bytes:
    """One outer round's wire frame: round header + codec bytes."""
    return _HDR.pack(int(round_idx)) + payload


def unframe(buf: bytes) -> Tuple[int, bytes]:
    (round_idx,) = _HDR.unpack_from(buf)
    return int(round_idx), bytes(buf[_HDR.size:])


class AsyncBridgeSender:
    """Non-blocking outer-exchange transport over a c10d-style store.

    ``store`` needs ``set``/``get``/``add`` (the same subset the bridge
    collectives use); ``ns`` namespaces keys (pass the group's ``_ns``
    so streams are generation-tagged); ``slice_idx`` is this slice's
    position among ``n_slices`` slice streams; ``injector`` is the
    optional fault injector whose ``slow_rank@edge=dcn`` delay fires in
    the sender thread (off the train step's critical path — the point).

    Lifecycle: the thread starts lazily on the first :meth:`post` and is
    joined by :meth:`stop` (bounded). A send failure is logged and
    counted (``cgx.async.send_errors``), never raised into the training
    loop — staleness detection is the async plane's job, and a dead
    store will surface there as peers' rounds ceasing to arrive.
    """

    def __init__(
        self,
        store,
        slice_idx: int,
        n_slices: int,
        *,
        ns: Optional[Callable[[str], str]] = None,
        injector=None,
        generation: int = 0,
        readers_by_slice: Optional[Dict[int, int]] = None,
    ):
        if not 0 <= slice_idx < n_slices:
            raise ValueError(
                f"slice_idx {slice_idx} out of range for {n_slices} slices"
            )
        self._store = store
        self.slice_idx = int(slice_idx)
        self.n_slices = int(n_slices)
        self.generation = int(generation)
        self._ns = ns or (lambda k: k)
        self._injector = injector
        self._q: "_queue.Queue" = _queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_lock = threading.Lock()
        # per-peer consumed sequence numbers (poll bookkeeping)
        self._seen: Dict[int, int] = {
            p: 0 for p in range(n_slices) if p != slice_idx
        }
        # how many ranks consume each slice's stream (refcounted delete,
        # the backend _take readers discipline); 1 = single consumer
        self._readers_by_slice = dict(readers_by_slice or {})
        self._store_can_delete: Optional[bool] = None

    # -- keys --------------------------------------------------------------

    def _counter_key(self, slice_idx: int) -> str:
        return self._ns(f"cgxasync/s{slice_idx}/n")

    def _payload_key(self, slice_idx: int, seq: int) -> str:
        return self._ns(f"cgxasync/s{slice_idx}/{seq}")

    # -- sender side -------------------------------------------------------

    def post(self, round_idx: int, payload: bytes) -> None:
        """Enqueue one outer round's framed delta for the sender thread.
        Returns immediately — the train step never blocks on DCN."""
        self._ensure_thread()
        self._q.put((int(round_idx), bytes(payload)))
        metrics.add("cgx.async.posted")

    def pending(self) -> int:
        """Posts enqueued but not yet shipped (sender-thread backlog —
        a growing number means the DCN edge is slower than the outer
        cadence; it shows up in ``cgx.async.backlog`` too)."""
        return self._q.qsize()

    def _ensure_thread(self) -> None:
        with self._thread_lock:
            if self._thread is None or not self._thread.is_alive():
                self._stop.clear()
                self._thread = threading.Thread(
                    target=self._run, name="cgx-async-send", daemon=True
                )
                self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                round_idx, payload = self._q.get(timeout=_TICK_S)
            except _queue.Empty:
                continue
            for attempt in range(_SHIP_RETRIES):
                try:
                    self._ship(round_idx, payload)
                    break
                except Exception as e:
                    # A dropped round would desynchronize every peer's
                    # anchor from this slice's forever — retry with
                    # backoff; only after the last attempt is it a
                    # counted, flight-recorded loss (a staleness event
                    # on the peers, never a train-step failure here).
                    metrics.add("cgx.async.send_errors")
                    log.warning(
                        "async sender: shipping round %d failed "
                        "(attempt %d/%d): %s",
                        round_idx, attempt + 1, _SHIP_RETRIES, e,
                    )
                    if attempt + 1 == _SHIP_RETRIES:
                        metrics.add("cgx.async.rounds_lost")
                        from ..observability import flightrec

                        flightrec.record(
                            "async_send_lost", round=round_idx,
                            generation=self.generation, error=str(e)[:160],
                        )
                    elif self._stop.wait(_SHIP_BACKOFF_S * (1 << attempt)):
                        break  # stopping: abandon the retry loop

    def _ship(self, round_idx: int, payload: bytes) -> None:
        if self._injector is not None:
            # The injected slow DCN edge lives HERE — delivery slows,
            # the train step does not (bench.py --async-dcn's contrast).
            self._injector.delay_edge("slow_rank", "dcn")
        buf = frame(round_idx, payload)
        t0 = time.perf_counter()
        seq = int(self._store.add(self._counter_key(self.slice_idx), 0)) + 1
        self._store.set(self._payload_key(self.slice_idx, seq), buf)
        # publish-after-write: the counter moves only once the payload
        # key is readable, so poll() never waits on a half-posted round
        self._store.add(self._counter_key(self.slice_idx), 1)
        dt = time.perf_counter() - t0
        metrics.add("cgx.async.rounds_shipped")
        metrics.add("cgx.async.bytes_wire", float(len(buf)))
        metrics.set("cgx.async.backlog", float(self._q.qsize()))
        if dt > 0:
            metrics.set(
                "cgx.async.wire_gbps", round(len(buf) / dt / 1e9, 6)
            )
        timeline.record(
            "async.post", timeline.CAT_WIRE, t0, dt,
            bytes=len(buf), round=round_idx, generation=self.generation,
        )

    # -- receiver side -----------------------------------------------------

    def poll(self) -> List[Tuple[int, int, bytes]]:
        """Drain every peer slice's newly-published rounds WITHOUT
        blocking on unpublished ones: ``(peer_slice, round, payload)``
        tuples in (peer, seq) order. Each peer's counter is read with
        ``add(0)``; only seqs at or below it are fetched — and those keys
        exist by the publish-after-write ordering, so the gets return
        promptly (and are store-timeout-bounded regardless)."""
        out: List[Tuple[int, int, bytes]] = []
        for peer in sorted(self._seen):
            try:
                n = int(self._store.add(self._counter_key(peer), 0))
            except Exception as e:
                metrics.add("cgx.async.poll_errors")
                log.warning("async poll: counter read for slice %d "
                            "failed: %s", peer, e)
                continue
            for seq in range(self._seen[peer] + 1, n + 1):
                key = self._payload_key(peer, seq)
                try:
                    buf = bytes(self._store.get(key))
                except Exception as e:
                    metrics.add("cgx.async.poll_errors")
                    log.warning(
                        "async poll: get(%s) failed: %s", key, e
                    )
                    break
                self._seen[peer] = seq
                self._consume(key, self._readers_by_slice.get(peer, 1))
                round_idx, payload = unframe(buf)
                metrics.add("cgx.async.rounds_received")
                out.append((peer, round_idx, payload))
        return out

    def _consume(self, key: str, readers: int) -> None:
        """Refcounted consume-side GC (the backend ``_take`` discipline):
        the last of ``readers`` consumers deletes the payload key and the
        ack counter — earlier ones only ack, so same-slice peers reading
        the same stream never race a delete."""
        if readers <= 1:
            self._delete_key(key)
            return
        try:
            acks = int(self._store.add(key + "/ack", 1))
        except Exception as e:
            metrics.add("cgx.async.poll_errors")
            log.debug("async ack(%r) failed: %s", key, e)
            return
        if acks >= readers:
            self._delete_key(key)
            self._delete_key(key + "/ack")

    def _delete_key(self, key: str) -> None:
        """Best-effort consume-side GC with a one-time capability probe
        (the backend ``_delete_key`` contract: stores without delete keep
        their keys — a bounded leak of one key per outer round)."""
        if self._store_can_delete is False:
            return
        try:
            self._store.delete_key(key)
            self._store_can_delete = True
        except (NotImplementedError, AttributeError):
            self._store_can_delete = False
        except Exception as e:
            self._store_can_delete = False
            log.debug("async store delete(%r) failed: %s", key, e)

    # -- lifecycle ---------------------------------------------------------

    def stop(self, timeout: float = 2.0) -> None:
        """Stop the sender thread (bounded join; enqueued-but-unshipped
        posts are dropped — by then the group is reconfiguring and the
        stream's generation namespace is dead anyway)."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None


class IntraBroadcast:
    """Intra-slice agreement channel for the outer fold.

    Ranks of one slice must apply IDENTICAL outer updates or their
    params diverge and the 'one writer per stream' invariant (a slice's
    delta is the same on every member) breaks: peer rounds arrive at
    each rank's poll at different instants, so independent folding is
    not deterministic across slice members. The fix is the two-level
    leader scheme applied to the outer loop — the LEADER computes the
    fold and broadcasts the resulting anchor-update bytes intra-slice;
    non-leaders apply exactly those bytes.

    This wait is intra-slice (the FAST tier — the same fabric the sync
    intra stage already blocks on every step), never DCN, so it does not
    violate the plane's never-block-on-DCN contract; it is bounded by
    ``timeout_s`` and raises ``BridgeTimeoutError`` on expiry (a leader
    that died or raised mid-boundary surfaces to the recovery ladder on
    every member). Publish-after-write ordering as everywhere else.
    """

    _POLL_S = 0.002

    def __init__(
        self,
        store,
        slice_idx: int,
        *,
        n_local: int,
        ns: Optional[Callable[[str], str]] = None,
        timeout_s: float = 60.0,
        generation: int = 0,
    ):
        self._store = store
        self.slice_idx = int(slice_idx)
        self.n_local = int(n_local)
        self.generation = int(generation)
        self._ns = ns or (lambda k: k)
        self._timeout_s = float(timeout_s)
        self._store_can_delete: Optional[bool] = None

    def _payload_key(self, round_idx: int) -> str:
        return self._ns(f"cgxasyncb/s{self.slice_idx}/r{round_idx}")

    def publish(self, round_idx: int, payload: bytes) -> None:
        """Leader side: post round ``round_idx``'s fold result for the
        slice's non-leaders (payload key first, per-round publish flag
        after — a PER-ROUND flag, not a cumulative counter: outer rounds
        survive a generation bump while the namespace resets, so a
        cumulative count restarted at 0 under ``g<N>/`` could never
        reach an absolute round index again and would livelock every
        post-recovery fetch)."""
        key = self._payload_key(round_idx)
        self._store.set(key, bytes(payload))
        self._store.add(key + "/pub", 1)
        metrics.add("cgx.async.intra_published")

    def fetch(self, round_idx: int) -> bytes:
        """Non-leader side: the leader's round-``round_idx`` fold bytes.
        Bounded intra-slice wait (poll the round's publish flag, then
        get the key — which exists by publish-after-write); expiry
        raises ``BridgeTimeoutError`` naming the wait, entering the
        recovery ladder like any other expired bridge wait."""
        from ..robustness.errors import BridgeTimeoutError

        deadline = time.monotonic() + self._timeout_s
        key = self._payload_key(round_idx)
        while int(self._store.add(key + "/pub", 0)) < 1:
            if time.monotonic() >= deadline:
                raise BridgeTimeoutError(
                    f"async intra broadcast: leader of slice "
                    f"{self.slice_idx} never published outer round "
                    f"{round_idx} within {self._timeout_s:g}s",
                    key=key,
                )
            time.sleep(self._POLL_S)
        buf = bytes(self._store.get(key))
        metrics.add("cgx.async.intra_fetched")
        # refcounted consume: the last non-leader deletes
        if self.n_local <= 2:
            self._delete(key)
            self._delete(key + "/pub")
        else:
            try:
                acks = int(self._store.add(key + "/ack", 1))
            except Exception as e:
                log.debug("async intra ack(%r) failed: %s", key, e)
                return buf
            if acks >= self.n_local - 1:
                self._delete(key)
                self._delete(key + "/ack")
                self._delete(key + "/pub")
        return buf

    def _delete(self, key: str) -> None:
        if self._store_can_delete is False:
            return
        try:
            self._store.delete_key(key)
            self._store_can_delete = True
        except (NotImplementedError, AttributeError):
            self._store_can_delete = False
        except Exception as e:
            self._store_can_delete = False
            log.debug("async intra delete(%r) failed: %s", key, e)


class LocalAsyncTransport:
    """In-process stand-in for tests and the single-host chaos soak: the
    same post/poll surface over a plain shared dict (thread-safe), with
    an optional per-slice ``delay_s`` map modeling a slow DCN edge
    (delivery delayed, post still instantaneous — the sender-thread
    decoupling in miniature)."""

    def __init__(self, n_slices: int, delay_s: Optional[Dict[int, float]] = None):
        self.n_slices = int(n_slices)
        self._lock = threading.Lock()
        self._streams: Dict[int, List[Tuple[int, bytes, float]]] = {
            s: [] for s in range(n_slices)
        }
        self._seen: Dict[Tuple[int, int], int] = {}
        self._delay = dict(delay_s or {})

    def bind(self, slice_idx: int) -> "LocalAsyncTransport._Endpoint":
        return LocalAsyncTransport._Endpoint(self, slice_idx)

    class _Endpoint:
        def __init__(self, parent: "LocalAsyncTransport", slice_idx: int):
            self._p = parent
            self.slice_idx = int(slice_idx)

        def post(self, round_idx: int, payload: bytes) -> None:
            visible = time.monotonic() + self._p._delay.get(
                self.slice_idx, 0.0
            )
            with self._p._lock:
                self._p._streams[self.slice_idx].append(
                    (int(round_idx), bytes(payload), visible)
                )
            metrics.add("cgx.async.posted")

        def pending(self) -> int:
            return 0

        def poll(self) -> List[Tuple[int, int, bytes]]:
            now = time.monotonic()
            out: List[Tuple[int, int, bytes]] = []
            with self._p._lock:
                for peer in sorted(self._p._streams):
                    if peer == self.slice_idx:
                        continue
                    stream = self._p._streams[peer]
                    start = self._p._seen.get((self.slice_idx, peer), 0)
                    taken = start
                    for round_idx, payload, visible in stream[start:]:
                        if visible > now:
                            break  # delayed edge: later rounds still queued
                        out.append((peer, round_idx, payload))
                        taken += 1
                    self._p._seen[(self.slice_idx, peer)] = taken
            return out

        def stop(self, timeout: float = 0.0) -> None:
            del timeout

    def set_delay(self, slice_idx: int, delay_s: float) -> None:
        """Fault control for the chaos soak: future posts from
        ``slice_idx`` become visible ``delay_s`` late."""
        with self._lock:
            self._delay[int(slice_idx)] = float(delay_s)
