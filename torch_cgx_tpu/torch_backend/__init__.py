"""Torch bridge: ``import torch_cgx_tpu.torch_backend`` registers the
``"cgx"`` torch.distributed backend (the import-time side effect mirrors the
reference's static constructor, ProcessGroupCGX.h:258-263), after which

    dist.init_process_group("cgx", ...)
    model = DistributedDataParallel(model)
    state = CGXState(None, compression_params={"bits": 4, "bucket_size": 1024})
    model.register_comm_hook(state, cgx_hook)

works as a drop-in for the reference's ``torch_cgx`` module. The per-layer
setters are re-exported here for parity with the reference pybind surface
(ProcessGroupCGX.cc:852-857).
"""

from ..config import (  # noqa: F401 — parity re-exports
    register_layer,
    set_quantization_bits,
    set_quantization_bucket_size,
)
from .backend import BACKEND_NAME, ProcessGroupCGX, register_backend
from .hooks import CGXState, cgx_hook

register_backend()

__all__ = [
    "BACKEND_NAME",
    "ProcessGroupCGX",
    "register_backend",
    "CGXState",
    "cgx_hook",
    "register_layer",
    "set_quantization_bits",
    "set_quantization_bucket_size",
]
