"""Same-host shared-memory byte plane for the ``"cgx"`` bridge.

The reference's default intra-node transport is a zero-copy POSIX SHM data
plane with IPC-event signalling (/root/reference/src/common/
shm_communicator.cc:116-177, shm_utils.cc:24-48): each pair of node-local
ranks exchanges payloads through ``shm_open``'d windows instead of the
network stack. The bridge's portable transport is the c10d Store, which
ships every byte through TCP/file puts — fine across hosts, a throughput
class below SHM between processes that share RAM.

This module is the TPU-host re-expression: the **Store stays the control
plane** (tiny per-message headers, ordering, refcounted acks — replacing
the reference's IPC events and MPI_Barrier'd window setup), while payload
bytes ride mmap'd files under ``/dev/shm``:

* :class:`ShmArena` — the writer side. One rank owns a generation-numbered
  ring of mmap'd files (``shm_open``/``ftruncate``/``mmap`` analogue, done
  with plain ``os.open`` + ``mmap`` so no ``multiprocessing`` resource
  tracker interferes). Allocation is a circular bump allocator; regions
  are reclaimed when every reader has acked through the Store. When the
  ring can't satisfy a request the arena *grows* a new generation instead
  of blocking — a put can therefore never deadlock against a slow reader;
  drained generations are unlinked.
* :class:`ShmChannel` — put/take with Store-get semantics: ``put`` copies
  the payload into the arena and publishes a small text header (path,
  generation, offset, size, crc32) under the
  message key; ``take`` resolves the header, maps the writer's file
  (attachments are cached per path), copies the payload out and bumps the
  ack counter. One memcpy per side versus the Store's
  serialize→socket→deserialize of the full payload.

Host identity for the rendezvous is hostname + kernel ``boot_id`` (two
containers with the same hostname on different machines must not try to
share ``/dev/shm``). ``CGX_SHM_HOST_ID`` overrides the fingerprint — the
test hook that simulates a multi-host topology on one box, and an escape
hatch for containers that share hostname+boot_id but not ``/dev/shm``
(set distinct ids to force the Store path).

Hardened data plane (docs/ROBUSTNESS.md): every payload header carries a
crc32 verified on ``take`` (one fresh re-read, then
:class:`WireCorruptionError`), standalone takes are bounded by
``CGX_BRIDGE_TIMEOUT_MS`` (:class:`BridgeTimeoutError` naming the key and
any stale heartbeat), arena growth is capped by ``CGX_SHM_MAX_MB`` with a
backoff-and-reclaim pressure path, and the ``CGX_FAULTS`` injector
(``robustness/faults.py``) can drop puts, delay takes, corrupt payloads
and stall acks deterministically to rehearse all of the above.
"""

from __future__ import annotations

import atexit
import mmap
import os
import re
import socket
import threading
import time
import uuid
import weakref
import zlib
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from .. import config as cfg
from ..observability import flightrec
from ..observability import memledger
from ..observability import timeline
from ..robustness import faults as faults_mod
from ..robustness import retry as retry_mod
from ..robustness.errors import (
    BridgeTimeoutError,
    StaleGenerationError,
    WireCorruptionError,
)
from ..utils.logging import get_logger, metrics

log = get_logger()

_ALIGN = 64  # region alignment (cache line)

# Wire checksum cost model: full crc32 runs ~0.8 GB/s in this container —
# free for codec frames (a 4-bit chunk of a 3M-float bucket is ~1.6 MB,
# ~2 ms) but ~80 ms per side on a jumbo 64 MB raw broadcast, which would
# hand back much of the plane's win over the store. Above _CRC_FULL_MAX
# the checksum covers a deterministic sample (length + head + middle +
# tail slices) at constant cost — still catching truncation, offset/gen
# mixups and corruption in the sampled spans.
_CRC_FULL_MAX = 4 << 20
_CRC_SAMPLE = 256 << 10


def _wire_checksum(buf) -> int:
    """crc32 of the payload (full below _CRC_FULL_MAX, sampled above).
    Writer and reader must agree byte-for-byte, so both sides call this."""
    n = len(buf)
    if n <= _CRC_FULL_MAX:
        return zlib.crc32(buf)
    c = n // 2
    crc = zlib.crc32(n.to_bytes(8, "little"))
    crc = zlib.crc32(buf[:_CRC_SAMPLE], crc)
    crc = zlib.crc32(buf[c - _CRC_SAMPLE // 2 : c + _CRC_SAMPLE // 2], crc)
    crc = zlib.crc32(buf[n - _CRC_SAMPLE :], crc)
    return crc


def host_fingerprint() -> str:
    """Identity of "a host whose processes can share /dev/shm"."""
    override = os.environ.get("CGX_SHM_HOST_ID")
    if override:
        return override
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            boot = f.read().strip()
    except OSError:
        boot = "noboot"
    return f"{socket.gethostname()}:{boot}"


def default_dir() -> str:
    d = os.environ.get("CGX_SHM_DIR")
    if d:
        return d
    return "/dev/shm" if os.path.isdir("/dev/shm") else "/tmp"


def _round_up(n: int, a: int) -> int:
    return -(-n // a) * a


_PID_RE = re.compile(r"^cgx-[0-9a-f]+-p(\d+)-r\d+-g\d+$")
_REAP_GRACE_S = 120.0


def _reap_dead_arenas(directory: str) -> None:
    """Unlink arena files whose owner is gone (shm_utils.cc-style hygiene
    for the crash path: SIGKILL skips atexit, so files would pin tmpfs
    forever).

    Ownership is probed with a non-blocking ``flock`` — the writer holds
    an exclusive lock on every generation file for its lifetime, and the
    kernel releases it on ANY death including SIGKILL. Unlike a pid
    liveness check, this is correct across PID namespaces (containers
    sharing /dev/shm but not a pid namespace). Files younger than a grace
    window are spared even when orphaned, so a reader racing to complete
    a just-dead writer's in-flight message usually still can; losers of
    that race get :class:`RuntimeError` from ``take`` (see ``_read``),
    not a raw FileNotFoundError."""
    import fcntl
    import time as _time

    try:
        entries = os.listdir(directory)
    except OSError:
        return
    now = _time.time()
    for name in entries:
        if not _PID_RE.match(name):
            continue
        path = os.path.join(directory, name)
        try:
            if now - os.stat(path).st_mtime < _REAP_GRACE_S:
                continue
            fd = os.open(path, os.O_RDWR)
        except OSError:
            continue
        try:
            try:
                fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # a live writer (any namespace) holds the lock
            try:
                os.unlink(path)
                log.debug("reaped orphaned shm arena %s", name)
            except OSError:
                pass
        finally:
            os.close(fd)


class _Region:
    __slots__ = ("gen", "off", "size", "ack_key", "readers", "freed",
                 "copying", "t_birth")

    def __init__(self, gen: int, off: int, size: int, ack_key: str, readers: int):
        self.gen = gen
        self.off = off
        self.size = size
        self.ack_key = ack_key
        self.readers = readers
        self.freed = False
        # Allocation timestamp (monotonic): the pressure post-mortem and
        # the memory ledger's region table report age, so a dump names
        # the *hoarder* (oldest un-acked owner), not just the symptom.
        self.t_birth = time.monotonic()
        # Payload memcpy in flight outside the arena lock (ShmArena.write):
        # an epoch-bump abandon must not mark this region freed — freed
        # bytes can be re-allocated, and the new frame would interleave
        # with our copy.
        self.copying = False


class _GenFile:
    """One mmap'd backing file: a circular bump allocator.

    The creating process holds an exclusive ``flock`` on the fd for the
    file's lifetime — the liveness signal :func:`_reap_dead_arenas`
    probes (released by the kernel on any death, SIGKILL included)."""

    def __init__(self, path: str, capacity: int):
        import fcntl

        self.path = path
        self.capacity = capacity
        self.fd = os.open(path, os.O_CREAT | os.O_EXCL | os.O_RDWR, 0o600)
        try:
            os.ftruncate(self.fd, capacity)
            fcntl.flock(self.fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            self.mm = mmap.mmap(self.fd, capacity)
        except Exception:
            os.close(self.fd)
            raise
        self.head = 0  # next write offset
        self.tail = 0  # oldest live byte
        self.live = 0  # bytes in flight (incl. wrap gaps)
        # In-flight payload copies running OUTSIDE the arena lock (the
        # pipelined bridge's encoder thread overlaps its memcpys with the
        # worker thread's puts — see ShmArena.write). A pinned map must
        # not be unmapped by reclaim/abandon racing the copy.
        self.pins = 0

    def space_at_head(self) -> Tuple[int, int]:
        """(contiguous bytes at head, gap-to-end if a wrap would be needed)."""
        if self.head >= self.tail and self.live < self.capacity:
            return self.capacity - self.head, self.tail
        if self.live >= self.capacity:
            return 0, 0
        return self.tail - self.head, 0

    def close(self, unlink: bool = True) -> None:
        try:
            self.mm.close()
        except (OSError, ValueError, BufferError):
            pass  # exported buffers may pin the map; fd close still runs
        try:
            os.close(self.fd)  # releases the ownership flock
        except OSError:
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# Live arenas, for the memory ledger's pull-model samplers (the ledger
# never holds a strong ref — a closed bridge's arena must stay
# collectable). Dead arenas self-evict.
# cgx-analysis: allow(orphan-memo) — weak liveness set: each member's bytes drain through abandon_pending/close (reached from the recovery cascade); clearing the set itself would only blind the memory ledger to live arenas
_LIVE_ARENAS: "weakref.WeakSet" = weakref.WeakSet()


class ShmArena:
    """Writer-owned payload ring (grow-don't-block reclaim policy, capped
    at ``max_bytes`` total — past the cap, writes enter a bounded
    backoff-and-reclaim pressure wait instead of growing forever under a
    dead reader, and expire with :class:`BridgeTimeoutError` naming the
    oldest un-acked key)."""

    def __init__(
        self,
        directory: str,
        name: str,
        poll_ack: Callable[[str], int],
        drop_keys: Callable[[List[str]], None],
        min_capacity: int = 1 << 23,  # 8 MB
        max_bytes: Optional[int] = None,
        pressure_timeout_s: Optional[float] = None,
    ):
        self._dir = directory
        self._name = name
        self._poll_ack = poll_ack  # ack_key -> acks so far (non-blocking)
        self._drop_keys = drop_keys  # best-effort control-key GC
        self._gens: Dict[int, _GenFile] = {}
        self._gen = 0
        self._pending: List[_Region] = []  # allocation order
        self._lock = threading.Lock()
        self._max_bytes = (
            max_bytes if max_bytes is not None else cfg.shm_max_mb() << 20
        )
        bt = cfg.bridge_timeout_ms()
        self._pressure_timeout_s = (
            pressure_timeout_s
            if pressure_timeout_s is not None
            else (bt / 1000.0 if bt else 60.0)
        )
        self._new_gen(min_capacity)
        _LIVE_ARENAS.add(self)

    def path_of(self, gen: int) -> str:
        return os.path.join(self._dir, f"{self._name}-g{gen}")

    def region_table(self, limit: int = 8) -> List[Dict[str, object]]:
        """Oldest-first table of pending regions (owner = ack key, age,
        size, gen, acked-or-not) — the pressure post-mortem attachment
        and the ledger's fragmentation forensics. No Store RPCs: the
        freed flag reflects the last reclaim pass, which is exactly the
        state the stalled writer saw."""
        now = time.monotonic()
        with self._lock:
            pend = list(self._pending)
        pend.sort(key=lambda r: r.t_birth)
        return [
            {
                "owner": r.ack_key or "<wrap-filler>",
                "gen": r.gen,
                "off": r.off,
                "size": r.size,
                "age_s": round(now - r.t_birth, 3),
                "readers": r.readers,
                "freed": r.freed,
            }
            for r in pend[: max(limit, 1)]
        ]

    def mem_stats(self) -> Dict[str, object]:
        """Occupancy + fragmentation snapshot for the memory ledger.

        Free extents per generation ring follow straight from the bump
        allocator's head/tail: empty ring = one extent of ``capacity``;
        ``head >= tail`` (no wrap outstanding) = the two edge extents
        ``[head, capacity)`` and ``[0, tail)``; ``head < tail`` (wrapped)
        = the single middle extent ``[head, tail)``. Fragmentation is
        1 − largest-free-extent / total-free (0.0 = one contiguous hole,
        → 1.0 = free bytes shattered across rings); a multi-generation
        arena is inherently fragmented because no extent spans files."""
        extents: List[int] = []
        with self._lock:
            capacity = sum(gf.capacity for gf in self._gens.values())
            live = sum(gf.live for gf in self._gens.values())
            for gf in self._gens.values():
                if gf.live == 0:
                    extents.append(gf.capacity)
                elif gf.live >= gf.capacity:
                    pass  # full ring: no free extent
                elif gf.head >= gf.tail:
                    extents.extend(
                        e for e in (gf.capacity - gf.head, gf.tail) if e > 0
                    )
                else:
                    extents.append(gf.tail - gf.head)
            pending = len(self._pending)
            gens = len(self._gens)
        total_free = sum(extents)
        largest = max(extents) if extents else 0
        frag = (1.0 - largest / total_free) if total_free > 0 else 0.0
        return {
            "name": self._name,
            "gens": gens,
            "capacity_bytes": capacity,
            "live_bytes": live,
            "free_bytes": total_free,
            "largest_free_bytes": largest,
            "frag": round(frag, 4),
            "pending_regions": pending,
            "cap_bytes": self._max_bytes,
        }

    def _new_gen(self, capacity: int) -> None:
        self._gen += 1
        self._gens[self._gen] = _GenFile(self.path_of(self._gen), capacity)

    def _reclaim(self) -> None:
        """Free acked pending regions; advance ring tails over freed
        prefixes; unlink fully-drained non-current generations.

        Called only when an allocation cannot be satisfied (write() tries
        the free ring first), and polls only each generation's FIFO *head*
        run: the tail cannot advance past the first un-acked region, so
        polling regions behind it is pure Store-RPC waste — this keeps a
        ws-wide collective at O(1) ack polls per pressure event instead of
        O(ws) per put."""
        drop: List[str] = []
        blocked_gens = set()
        for r in self._pending:
            if r.gen in blocked_gens:
                continue
            if not r.freed and self._poll_ack(r.ack_key) >= r.readers:
                r.freed = True
                drop.append(r.ack_key)
                drop.append(r.ack_key[: -len("/ack")])
            if not r.freed:
                blocked_gens.add(r.gen)
        # Pop the freed prefix per generation (regions are FIFO per gen).
        still: List[_Region] = []
        for r in self._pending:
            gf = self._gens.get(r.gen)
            if r.freed and gf is not None and r.off == gf.tail % gf.capacity:
                gf.tail = (gf.tail + r.size) % gf.capacity
                gf.live -= r.size
                if gf.live == 0:
                    gf.head = gf.tail = 0
            elif r.freed and gf is None:
                pass
            else:
                still.append(r)
        # Out-of-order acks: a freed region behind an unfreed one stays in
        # `still` (its bytes aren't reusable yet) — keep it for next pass.
        kept = {id(r) for r in still}
        for r in self._pending:
            if id(r) not in kept and r.ack_key:
                memledger.note_release("shm.arena", nbytes=r.size)
        self._pending = [r for r in still]
        for g, gf in list(self._gens.items()):
            if g != self._gen and gf.live == 0 and gf.pins == 0 and not any(
                r.gen == g for r in self._pending
            ):
                gf.close()
                del self._gens[g]
        if drop:
            self._drop_keys(drop)

    def _try_alloc(self, size: int) -> int:
        """Offset in the current generation's ring, or -1 (caller holds the
        lock)."""
        gf = self._gens[self._gen]
        if size > gf.capacity:
            return -1
        at_head, wrap_tail = gf.space_at_head()
        if at_head >= size:
            off = gf.head
            gf.head = (gf.head + size) % gf.capacity
            gf.live += size
            return off
        if gf.head > gf.tail and wrap_tail >= size:
            # wrap: burn the gap [head, capacity) as a freed filler
            gap = gf.capacity - gf.head
            filler = _Region(self._gen, gf.head, gap, "", 0)
            filler.freed = True
            self._pending.append(filler)
            gf.live += gap
            gf.head = size % gf.capacity
            gf.live += size
            return 0
        return -1

    def write(self, data, ack_key: str, readers: int) -> Tuple[int, int, int]:
        """Copy ``data`` (any C-contiguous buffer) into the ring; returns
        (gen, offset, size) for the header. Grows a new generation when the
        ring is full — up to ``max_bytes`` total, past which the write
        backs off (exponential, lock released) polling acks, and finally
        raises :class:`BridgeTimeoutError` naming the stalled key."""
        data = memoryview(data).cast("B")
        size = max(_round_up(len(data), _ALIGN), _ALIGN)
        if size > self._max_bytes:
            raise RuntimeError(
                f"cgx shm: payload of {size} bytes exceeds the arena cap "
                f"({self._max_bytes} bytes); raise CGX_SHM_MAX_MB"
            )
        deadline = None
        backoff = 0.001
        while True:
            with self._lock:
                off = self._try_alloc(size)
                if off < 0:
                    # Pressure path only: poll acks, then retry.
                    self._reclaim()
                    off = self._try_alloc(size)
                if off < 0:
                    total = sum(gf.capacity for gf in self._gens.values())
                    want = max(2 * self._gens[self._gen].capacity, 4 * size)
                    if total + want > self._max_bytes:
                        want = size  # minimal growth under the cap
                    if total + want <= self._max_bytes:
                        self._new_gen(want)
                        gf = self._gens[self._gen]
                        off = 0
                        gf.head = size % gf.capacity
                        gf.live += size
                if off >= 0:
                    gen = self._gen
                    gf = self._gens[gen]
                    # Reserve the region under the lock, COPY OUTSIDE it:
                    # the pipelined bridge runs an encoder thread whose
                    # multi-MB frame memcpys must overlap the worker
                    # thread's own puts, not serialize them behind the
                    # arena lock. Safe because nothing reads the region
                    # until the caller publishes its header (after this
                    # returns), reclaim cannot free it before its acks
                    # arrive, the ``copying`` flag keeps an epoch-bump
                    # abandon from freeing (and re-allocating) the bytes
                    # mid-copy, and the pin keeps the mmap itself alive.
                    region = _Region(gen, off, size, ack_key, readers)
                    region.copying = True
                    self._pending.append(region)
                    gf.pins += 1
                    memledger.note_alloc("shm.arena", nbytes=size)
            if off >= 0:
                try:
                    t_copy = time.perf_counter()
                    gf.mm[off : off + len(data)] = data
                    metrics.observe(
                        "cgx.shm.put_copy_s", time.perf_counter() - t_copy
                    )
                finally:
                    with self._lock:
                        gf.pins -= 1
                        region.copying = False
                return gen, off, len(data)
            with self._lock:
                stalled = next(
                    (r for r in self._pending if not r.freed and r.ack_key),
                    None,
                )
            # Over the capacity cap with nothing reclaimable: bounded
            # pressure wait (outside the lock — takers may be acking).
            now = time.monotonic()
            if deadline is None:
                deadline = now + self._pressure_timeout_s
            if now >= deadline:
                detail = (
                    f"oldest un-acked key {stalled.ack_key!r} "
                    f"({self._poll_ack(stalled.ack_key)}/{stalled.readers} "
                    "acks)"
                    if stalled is not None
                    else "no pending regions (cap too small for burst?)"
                )
                metrics.add("cgx.bridge_timeout")
                err = BridgeTimeoutError(
                    f"cgx shm: arena at its {self._max_bytes >> 20} MB cap "
                    f"for {self._pressure_timeout_s:.1f}s and readers are "
                    f"not draining — {detail}; a reader is dead or stalled",
                    key=stalled.ack_key if stalled is not None else None,
                )
                # Post-mortem forensics: the per-region owner/age/size
                # table names the hoarder (oldest un-acked ack key), not
                # just the pressure symptom — without it a dump says "at
                # cap" and nothing about WHOSE bytes pinned the ring.
                flightrec.record_failure(
                    err, op="shm.put", key=err.key, bytes=len(data),
                    regions=self.region_table(limit=8),
                )
                raise err
            metrics.add("cgx.arena_pressure_waits")
            time.sleep(min(backoff, deadline - now if deadline > now else 0))
            backoff = min(backoff * 2, 0.2)

    def abandon_pending(self) -> int:
        """Mark every pending region freed and reclaim — the epoch-bump
        drain: messages framed under a pre-recovery generation will never
        be acked (their readers were evicted, died, or discarded the
        stale header), so their bytes must not pin the ring forever.
        Returns the number of regions abandoned."""
        with self._lock:
            n = 0
            drop: List[str] = []
            for r in self._pending:
                if r.copying:
                    # A writer thread is mid-memcpy into these bytes
                    # (ShmArena.write's out-of-lock copy): freeing them
                    # now would let a post-recovery put re-allocate the
                    # range and interleave the two copies. Leave the
                    # region pending — the next reclaim/abandon drains it
                    # once the copy finishes.
                    continue
                if not r.freed:
                    r.freed = True
                    n += 1
                    if r.ack_key:
                        drop.append(r.ack_key)
                        drop.append(r.ack_key[: -len("/ack")])
            # With every region freed, _reclaim's ack polls all skip and
            # its tail-advance/generation-close passes do the drain.
            self._reclaim()
            self._drop_keys(drop)
        return n

    def close(self) -> None:
        with self._lock:
            for g, gf in list(self._gens.items()):
                if gf.pins:
                    # A copy is in flight on another thread (pipelined
                    # encoder at shutdown): unmapping under it would
                    # fault. Leave the map; the dead-arena reaper unlinks
                    # the file once the owning process exits.
                    continue
                gf.close()
                del self._gens[g]
            self._pending.clear()


class ShmChannel:
    """Store-controlled same-host byte channel (put/take semantics of the
    bridge's Store transport, payloads via :class:`ShmArena`)."""

    HDR = "cgxshm/"

    def __init__(
        self,
        store,
        rank: int,
        directory: Optional[str] = None,
        wait_key: Optional[Callable[[str], None]] = None,
    ):
        self._store = store
        self._rank = rank
        self._dir = directory or default_dir()
        self._wait_key = wait_key  # blocking "key exists" (abort-aware)
        # Every writer coins its own arena name and ships it in each
        # message header — no group-wide session rendezvous (which would
        # need an elected coiner and deadlock if that rank had no local
        # peers of its own). The owner PID is embedded so a later channel
        # can reap arenas whose writer died without running atexit
        # (SIGKILL/OOM — close() never fires there).
        _reap_dead_arenas(self._dir)
        flightrec.bind_rank(rank)
        timeline.bind_rank(rank)
        name = f"cgx-{uuid.uuid4().hex[:12]}-p{os.getpid()}-r{rank}"
        self._injector = faults_mod.get_injector(rank)
        self._checksum = cfg.wire_checksum()
        # Recovery generation (epoch) of the group this channel serves.
        # 0 = never reconfigured: headers keep the legacy 5-field format
        # byte-for-byte. After a bump, headers carry a trailing ``e<N>``
        # field and takes discard any message tagged with an older epoch
        # instead of decoding it into the new group (supervisor.py).
        self._epoch = 0
        bt = cfg.bridge_timeout_ms()
        self._timeout_s = bt / 1000.0 if bt else 300.0
        self._arena = ShmArena(
            self._dir, name, self._ack_count, self._drop_keys
        )
        self._attached: Dict[str, mmap.mmap] = {}
        self._attach_lock = threading.Lock()
        # Plane-usage counters (observability + routing tests).
        self.n_puts = 0
        self.n_takes = 0
        # Safety net: unlink /dev/shm files even when the owner never calls
        # ProcessGroup.shutdown() (crash/KeyboardInterrupt paths). close()
        # is idempotent.
        atexit.register(self.close)

    # -- store helpers ----------------------------------------------------

    def _ack_count(self, ack_key: str) -> int:
        if self._injector is not None and self._injector.fire("stall_ack"):
            return 0  # simulated dead reader: acks never observed
        try:
            return int(self._store.add(ack_key, 0))
        except Exception:
            return 0

    def _drop_keys(self, keys: List[str]) -> None:
        for k in keys:
            if not k:
                continue
            try:
                self._store.delete_key(k)
            except Exception:
                return  # store without delete support: keys persist

    # -- data plane -------------------------------------------------------

    def put(self, key: str, data, readers: int = 1) -> None:
        """``data``: bytes or any C-contiguous buffer (uint8 ndarray views
        included — one memcpy into the arena, no staging copy). The header
        carries a crc32 of the payload (``CGX_WIRE_CHECKSUM``, -1 when
        disabled) that ``take`` verifies."""
        hkey = self.HDR + key
        t0 = time.perf_counter()
        mv = memoryview(data).cast("B")
        crc = _wire_checksum(mv) if self._checksum else -1
        inj = self._injector
        # len check FIRST: an empty payload is not a corruptible event —
        # firing on it would advance the injector's counter and report a
        # fault that never exercised the verify-on-take defense.
        if inj is not None and len(mv) and inj.fire("corrupt_wire"):
            # Damage the bytes AFTER the checksum: models tmpfs/DMA
            # corruption the verify-on-take defense exists to catch.
            buf = bytearray(mv)
            buf[len(buf) // 2] ^= 0xFF
            mv = memoryview(buf)
        gen, off, size = self._arena.write(mv, hkey + "/ack", readers)
        if inj is not None and inj.fire("drop_put"):
            return  # header never published: the reader's bounded wait fires
        path = self._arena.path_of(gen)
        hdr = f"{path}:{gen}:{off}:{size}:{crc}"
        if self._epoch:
            hdr += f":e{self._epoch}"  # generation tag (parsed by take)
        if inj is not None:
            flap_s = inj.flap_delay()
            if flap_s is not None:
                # Transient drop-then-recover: publish the header LATE from
                # a timer thread. The reader's first bounded wait may
                # expire; the recovery retry rung's re-armed wait succeeds.
                threading.Timer(
                    flap_s, self._store.set, (hkey, hdr.encode())
                ).start()
                return
        self._store.set(hkey, hdr.encode())
        dt = time.perf_counter() - t0
        metrics.observe("cgx.shm.put_s", dt)
        metrics.add("cgx.shm.put_bytes", float(size))
        flightrec.record(
            "shm_put", key=key, bytes=size, readers=readers,
            seconds=round(dt, 6),
        )
        timeline.record(
            "shm.put", timeline.CAT_WIRE, t0, dt, key=key, bytes=size
        )
        with self._attach_lock:  # worker + p2p pool threads share us
            self.n_puts += 1

    def take(self, key: str) -> np.ndarray:
        hkey = self.HDR + key
        t0 = time.perf_counter()
        try:
            if self._wait_key is not None:
                self._wait_key(hkey)
                hdr_raw = self._store.get(hkey)
            else:
                # Standalone channel (no group wait): bounded header wait.
                hdr_raw = self._bounded_get(hkey)
        except BaseException:
            # A wait that ends in BridgeTimeoutError is exactly the
            # interval the trace exists to show: record it as a failed
            # wait span before propagating.
            timeline.record(
                "shm.take.wait", timeline.CAT_WAIT, t0,
                time.perf_counter() - t0, key=key, ok=False,
            )
            raise
        t_hdr = time.perf_counter()  # queue wait ends when the header lands
        timeline.record(
            "shm.take.wait", timeline.CAT_WAIT, t0, t_hdr - t0, key=key
        )
        hdr = bytes(hdr_raw).decode()
        # Optional trailing generation tag (``:e<N>``): unambiguous against
        # the legacy 5-field format because the crc field is a plain int.
        epoch = 0
        head, _, tail = hdr.rpartition(":")
        if tail.startswith("e") and tail[1:].isdigit():
            epoch = int(tail[1:])
            hdr = head
        if epoch != self._epoch:
            # A message from another generation must be DISCARDED, never
            # decoded: its bytes describe a group (chunking, survivor set)
            # that no longer exists. Ack it so the writer's arena drains.
            metrics.add("cgx.recovery.stale_discards")
            self._store.add(hkey + "/ack", 1)
            err = StaleGenerationError(
                f"cgx shm: message {key!r} is tagged generation {epoch} "
                f"but this channel is at generation {self._epoch} — "
                "stale pre-recovery traffic discarded",
                found=epoch,
                current=self._epoch,
            )
            flightrec.record_failure(err, op="shm.take", key=key)
            raise err
        path, _gen, off_s, size_s, crc_s = hdr.rsplit(":", 4)
        off, size, crc = int(off_s), int(size_s), int(crc_s)
        try:
            if self._injector is not None:
                self._injector.delay("delay_take")
            out = self._read(path, off, size)
            if crc >= 0:
                got = _wire_checksum(out)
                if got != crc:
                    metrics.add("cgx.wire_corrupt")
                    log.warning(
                        "cgx shm: checksum mismatch for %r (want %08x got "
                        "%08x); re-reading once with a fresh mapping",
                        key, crc, got,
                    )
                    out = self._read(path, off, size, refresh=True)
                    if _wire_checksum(out) != crc:
                        err = WireCorruptionError(
                            f"cgx shm: payload checksum mismatch for {key!r} "
                            f"after one re-read ({path}:{off}+{size}) — the "
                            "wire payload is corrupted"
                        )
                        flightrec.record_failure(
                            err, op="shm.take", key=key, path=path,
                            bytes=size,
                        )
                        raise err
                    metrics.add("cgx.wire_reread_ok")
        except BaseException:
            # A copy that ends in WireCorruptionError (or a vanished
            # arena) still leaves its interval in the trace.
            timeline.record(
                "shm.take.copy", timeline.CAT_WIRE, t_hdr,
                time.perf_counter() - t_hdr, key=key, bytes=size, ok=False,
            )
            raise
        self._store.add(hkey + "/ack", 1)
        t1 = time.perf_counter()
        metrics.observe("cgx.shm.take_wait_s", t_hdr - t0)
        metrics.observe("cgx.shm.take_copy_s", t1 - t_hdr)
        metrics.add("cgx.shm.take_bytes", float(size))
        flightrec.record(
            "shm_take", key=key, bytes=size,
            wait_s=round(t_hdr - t0, 6), copy_s=round(t1 - t_hdr, 6),
        )
        timeline.record(
            "shm.take.copy", timeline.CAT_WIRE, t_hdr, t1 - t_hdr,
            key=key, bytes=size,
        )
        with self._attach_lock:
            self.n_takes += 1
        return out

    def _bounded_get(self, hkey: str) -> bytes:
        """Header fetch bounded by ``CGX_BRIDGE_TIMEOUT_MS``, then
        :class:`BridgeTimeoutError` naming the key (a hang becomes an
        actionable error).

        Real c10d stores park *inside* a bare ``get`` for the store's own
        timeout, which would let that timeout trump ours — so when the
        store supports ``wait(keys, timeout)`` the park happens in 200 ms
        slices with our deadline checked between them; stores without
        ``wait`` (test doubles) are polled with exponential backoff.

        With ``CGX_RECOVERY_RETRIES`` set, an expired deadline is re-armed
        through the shared :class:`~..robustness.retry.WaitRetry` rung
        before the error raises — the recovery ladder's rung 1, which
        absorbs transient ``flap``/straggler faults without any
        cross-rank coordination. (A standalone channel has no heartbeat
        peer map, so the suspect short-circuit never engages here.)"""
        import datetime as _dt

        # Lazy: the env-derived retry policy is only read on an expired
        # deadline, never on the per-message fast path.
        retry: Optional[retry_mod.WaitRetry] = None
        deadline = time.monotonic() + self._timeout_s
        backoff = 0.0005
        slice_ = _dt.timedelta(milliseconds=200)
        can_wait: Optional[bool] = None
        while True:
            if can_wait is not False:
                try:
                    self._store.wait([hkey], slice_)
                    return self._store.get(hkey)
                except (NotImplementedError, AttributeError, TypeError):
                    can_wait = False  # store double without wait support
                except Exception:
                    can_wait = True  # a real wait that timed out its slice
            else:
                try:
                    return self._store.get(hkey)
                except (KeyError, IndexError, OSError, RuntimeError,
                        ValueError):
                    pass  # key not there yet: poll again below
            if time.monotonic() >= deadline:
                if retry is None:
                    retry = retry_mod.WaitRetry("shm.take")
                if retry.attempt(hkey):
                    deadline = time.monotonic() + self._timeout_s
                    continue
                metrics.add("cgx.bridge_timeout")
                err = BridgeTimeoutError(
                    f"cgx shm: timed out after {self._timeout_s:.1f}s "
                    f"waiting for {hkey!r} (writer dead, or its put "
                    "dropped?)",
                    key=hkey,
                )
                flightrec.record_failure(err, op="shm.take", key=hkey)
                raise err
            if can_wait is False:
                time.sleep(backoff)
                backoff = min(backoff * 2, 0.05)

    @staticmethod
    def _split_gen(path: str) -> Tuple[str, int]:
        """(writer prefix, generation) of an arena file path."""
        prefix, g = path.rsplit("-g", 1)
        return prefix, int(g)

    def _read(
        self, path: str, off: int, size: int, refresh: bool = False
    ) -> np.ndarray:
        """Copy a payload out of a writer's arena file. The copy runs under
        the attach lock so generation eviction can never close a map that a
        concurrent take is still reading (the memcpy is fast; only this
        process's own reader threads serialize). ``refresh`` drops any
        cached mapping first — the checksum retry path, which must rule out
        a stale map before declaring the payload corrupt."""
        with self._attach_lock:
            mm = self._attached.get(path)
            if refresh and mm is not None:
                mm.close()
                del self._attached[path]
                mm = None
            if mm is None:
                try:
                    fd = os.open(path, os.O_RDONLY)
                except FileNotFoundError:
                    raise RuntimeError(
                        f"cgx shm: writer's arena {path!r} is gone — the "
                        "sending rank died (its orphaned arena may have "
                        "been reaped past the grace window)"
                    ) from None
                try:
                    mm = mmap.mmap(fd, 0, prot=mmap.PROT_READ)
                finally:
                    os.close(fd)
                self._attached[path] = mm
                # Evict this writer's OLDER generations: once the writer
                # grows, drained old files get unlinked — a cached reader
                # map would pin their tmpfs pages for the process lifetime.
                # A straggler message still in an old gen re-attaches by
                # path (the writer keeps the file until that message acks).
                writer, gen = self._split_gen(path)
                for p in [
                    q for q in self._attached
                    if q != path and self._split_gen(q)[0] == writer
                    and self._split_gen(q)[1] < gen
                ]:
                    self._attached[p].close()
                    del self._attached[p]
            return np.frombuffer(mm, np.uint8, count=size, offset=off).copy()

    def bump_epoch(self, epoch: int) -> None:
        """Advance this channel's recovery generation: newly framed
        headers carry the tag, takes discard older-tagged messages, every
        cached reader mapping is dropped (a peer may be rebuilding its
        arena), and the writer's own pending regions are abandoned — the
        drain-on-epoch-bump contract (docs/ROBUSTNESS.md Recovery)."""
        if epoch <= self._epoch:
            return
        self._epoch = epoch
        abandoned = self._arena.abandon_pending()
        with self._attach_lock:
            for mm in self._attached.values():
                try:
                    mm.close()
                except (OSError, ValueError, BufferError):
                    pass
            self._attached.clear()
        metrics.add("cgx.recovery.epoch_bumps")
        flightrec.record(
            "recovery", phase="shm_epoch_bump", epoch=epoch,
            abandoned_regions=abandoned,
        )
        log.info(
            "cgx shm: channel advanced to generation %d (%d stale pending "
            "regions abandoned)", epoch, abandoned,
        )

    def close(self) -> None:
        try:  # drop the crash-path safety net: a closed channel must not
            # be pinned (store handle + mmap cache) for the process life
            atexit.unregister(self.close)
        except (ValueError, RuntimeError):
            pass  # never registered / interpreter shutting down
        self._arena.close()
        with self._attach_lock:
            for mm in self._attached.values():
                try:
                    mm.close()
                except (OSError, ValueError, BufferError):
                    pass
            self._attached.clear()
