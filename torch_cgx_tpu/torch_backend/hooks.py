"""DDP communication hook for the ``"cgx"`` backend.

Mirrors the reference's Python integration layer
(/root/reference/cgx_utils/allreduce_hooks.py — SURVEY.md §2.2, §3.2):

* :class:`CGXState` carries the process group, compression parameters
  (from ``compression_params`` or the ``CGX_COMPRESSION_*`` env vars), a
  ``layer_min_size`` floor, and the DDP step counter.
* ``should_compress_``: tensors with dim <= 1 (biases, norms) or fewer than
  ``layer_min_size`` elements stay uncompressed (allreduce_hooks.py:42-45).
* :func:`cgx_hook` registers every bucket's layer layout at **step 2** —
  DDP rebuilds its buckets after iteration 0, so registration waits until
  shapes stabilize (allreduce_hooks.py:65-69, SURVEY.md §8.6) — and always
  returns a gradient-averaging future: divide by world size *first*, then
  allreduce-SUM, so quantization operates on pre-divided gradients
  (allreduce_hooks.py:53-54, SURVEY.md §8.12).
"""

# NOTE: no `from __future__ import annotations` here — DDP's
# register_comm_hook validates the hook signature by annotation *identity*
# (bucket must be literally dist.GradBucket, return literally
# torch.futures.Future[torch.Tensor]); stringified annotations fail it.

import itertools
from typing import Optional

import torch
import torch.distributed as dist

from .. import config as cfg

REGISTRATION_STEP = 2

# Each CGXState registers its buckets under its own namespace so two DDP
# models (or a re-wrapped model) in one process cannot collide on
# ``bucket.index()`` and silently mix per-layer configs.
_ns_counter = itertools.count()


class CGXState:
    """State object passed to :func:`cgx_hook` via
    ``model.register_comm_hook(state, cgx_hook)``."""

    def __init__(
        self,
        process_group: Optional[dist.ProcessGroup] = None,
        compression_params: Optional[dict] = None,
        layer_min_size: int = 1024,
    ):
        self.process_group = process_group
        self.step = 0
        self._registry_ns = next(_ns_counter)
        default = cfg.default_compression_config()
        params = compression_params or {}
        self.quantization_bits = int(params.get("bits", default.bits))
        self.quantization_bucket_size = int(
            params.get("bucket_size", default.bucket_size)
        )
        self.layer_min_size = max(int(layer_min_size), cfg.minimal_size())

    def should_compress_(self, tensor: torch.Tensor) -> bool:
        return tensor.dim() > 1 and tensor.numel() >= self.layer_min_size


def _allreduce_fut(
    process_group: Optional[dist.ProcessGroup], tensor: torch.Tensor
) -> torch.futures.Future:
    """Average gradients: divide locally, then allreduce-SUM asynchronously
    (the backend only ever sums — allreduce_hooks.py:48-59)."""
    group = process_group if process_group is not None else dist.group.WORLD
    tensor.div_(dist.get_world_size(group=group))
    fut = dist.all_reduce(tensor, group=group, async_op=True).get_future()
    return fut.then(lambda f: f.value()[0])


def cgx_hook(
    state: CGXState, bucket: dist.GradBucket
) -> torch.futures.Future[torch.Tensor]:
    bucket_key = (state._registry_ns, bucket.index())
    if state.step == REGISTRATION_STEP:
        for layer_idx, grad in enumerate(bucket.gradients()):
            bits = (
                state.quantization_bits
                if state.should_compress_(grad)
                else 32
            )
            cfg.register_layer(
                bucket_key,
                layer_idx,
                grad.numel(),
                bits,
                state.quantization_bucket_size,
            )
    if bucket.is_last():
        state.step += 1
    # Tag the allreduce about to happen so the backend resolves this exact
    # bucket's layer layout (consumed synchronously inside _allreduce_fut).
    cfg.set_current_bucket(bucket_key)
    return _allreduce_fut(state.process_group, bucket.buffer())
