"""Supervised cross-rank data plane: the ``Transport`` interface.

Every cross-host payload byte — bridge collectives, async DCN deltas,
serving KV-page ships, elastic join snapshot pages — historically rode
c10d store keys polled with backoff (plus the same-host shm arena).
ROADMAP item 3 names that the wrong substrate for a fleet; this module
is the TPU-native answer to the reference CGX's MPI plumbing
(ProcessGroupCGX.cc): a real TCP data plane, built robustness-first.

Three implementations of one contract (post / poll / fetch, preserving
the publish-after-write counter-stream semantics every existing plane
obeys: a payload is fetchable the moment its publication signal is
observable):

* :class:`StoreTransport` — the legacy store path, byte-identical
  (``store.set(key, payload)`` / bounded-poll ``get``).
* :class:`ShmTransport` — the same-host arena
  (:class:`~.shm.ShmChannel`), byte-identical.
* :class:`SocketTransport` — persistent per-peer TCP connections
  (stdlib only): an address exchange over the store control plane,
  length-prefixed scatter/gather frames carrying a crc32 (the serving
  wire's checksum discipline), bounded deadlines on EVERY socket
  operation, and a dedicated per-peer sender thread (the
  ``AsyncBridgeSender`` pattern — posting never blocks the collective's
  critical path).

The robustness layer is the headline. Sequence numbers are assigned at
*post* time and a bounded resend ring keeps every un-acked frame (the
PR 15 retry-reuses-seq rule generalized: a replayed frame reuses its
seq, the receiver dedups on a per-peer watermark). A
:class:`ConnectionSupervisor` health-checks links (idle pings,
write-error and stale-ack detection), reconnects with
:class:`~..robustness.retry.WaitRetry` backoff + jitter, and — after
``CGX_TRANSPORT_RETRIES`` failed reconnects — *degrades the peer edge
to the store plane mid-run*: counted, flight-recorded, bit-identical
payload bytes on the same keys, a ``link_down`` HealthEvent for the
PR 6 plane, and no exception ever raised out of a collective. The
receive side never depends on both ends agreeing on the degrade state:
:meth:`SocketTransport.fetch` probes BOTH its socket mailbox and the
store every slice.

Fault injection (``CGX_FAULTS``): ``conn_reset:<dur>@rank=N``,
``partial_write``, ``slow_link:<dur>@edge=tcp`` and
``partition:<dur>@ranks=a,b`` all fire inside this module's send /
connect sites — chaos runs rehearse exactly the production failure
surface (tests/test_transport.py).

Lock discipline (tools/analysis/locks.py runs over this file; the
bounded-io rule ``check_transport_bounded_io`` is specific to it): no
socket call ever happens under a lock, every ``recv``/``connect``
carries a deadline, and created sockets are closed in ``finally``/
error paths.
"""

from __future__ import annotations

import socket
import struct
import threading
import time
import zlib
from collections import OrderedDict, deque
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import config as cfg
from ..observability import flightrec
from ..robustness import faults as faults_mod
from ..robustness.retry import WaitRetry
from ..utils.logging import get_logger, metrics

log = get_logger()

# Frame header: magic, kind(u8), flags(u8), key_len(u16), seq(u64),
# payload_len(u32), crc32(u32; _NO_CRC = unchecked) — length-prefixed,
# so a reader always knows exactly how many bytes complete the frame.
_MAGIC = b"CGXT"
_FRAME = struct.Struct("<4sBBHQII")

_K_HELLO = 0  # key = sender's peer id; opens an inbound connection
_K_DATA = 1  # key + payload; seq assigned at post time
_K_ACK = 2  # seq = receiver's cumulative delivered watermark
_K_PING = 3  # supervisor idle health-check; answered with an ACK

# Checksum-off sentinel (serving/transport.py convention). A real crc32
# landing ON the sentinel (p = 2^-32) skips one frame's verify — safe.
_NO_CRC = 0xFFFFFFFF

_KEY_ENC = "utf-8"

# Cadences. Socket operations use the CGX_TRANSPORT_IO_TIMEOUT_MS
# deadline; these are the short *slices* inside bounded waits so stop
# flags and abort probes stay responsive.
_ACCEPT_TICK_S = 0.5
_IDLE_TICK_S = 0.2
_FETCH_TICK_S = 0.05
_STORE_PROBE_S = 0.25
_ADDR_POLL_S = 0.05


class TransportTimeout(RuntimeError):
    """A bounded fetch expired: ``key`` never arrived on the socket
    plane nor on the store fallback within the deadline."""

    def __init__(self, key: str, waited_s: float):
        super().__init__(
            f"transport fetch for {key!r} expired after {waited_s:.1f}s"
        )
        self.key = key
        self.waited_s = waited_s


class _Degraded(Exception):
    """Internal control flow: the edge degraded mid-operation (the
    payload is already safe on the store path — nothing to re-raise)."""


def _wire_crc(payload) -> int:
    if not cfg.wire_checksum():
        return _NO_CRC
    return zlib.crc32(memoryview(payload).cast("B")) & 0xFFFFFFFF


def _peer_rank(peer_id: str) -> Optional[int]:
    """Group-local rank behind a peer id (``"3"`` → 3); serving/elastic
    endpoint names carry no rank and fault rank-gates simply never
    match them."""
    try:
        return int(peer_id)
    except ValueError:
        return None


# ---------------------------------------------------------------------------
# The interface + the two byte-identical wrappers.
# ---------------------------------------------------------------------------


class Transport:
    """post/poll/fetch over some byte plane. ``post`` publishes a
    payload under a key toward ``to`` peers; ``poll`` is a non-blocking
    arrival probe; ``fetch`` is the bounded blocking read. The contract
    matches the repo-wide publish-after-write discipline: whatever
    signal the caller publishes AFTER ``post`` returns (a store counter
    bump), a peer observing that signal can ``fetch`` the payload."""

    name = "?"

    def post(
        self, key: str, payload: bytes, to: Sequence[str] = ()
    ) -> None:
        raise NotImplementedError

    def poll(self, key: str) -> bool:
        raise NotImplementedError

    def fetch(
        self,
        key: str,
        timeout_s: float,
        abort_check: Optional[Callable[[], None]] = None,
        peer: Optional[str] = None,
    ) -> bytes:
        raise NotImplementedError

    def close(self) -> None:
        pass


class StoreTransport(Transport):
    """The legacy store hop, byte-identical: ``post`` is exactly
    ``store.set(key, payload)`` — the same key, the same bytes every
    pre-transport release wrote."""

    name = "store"

    def __init__(self, store):
        self._store = store

    def post(
        self, key: str, payload: bytes, to: Sequence[str] = ()
    ) -> None:
        self._store.set(key, payload)

    def poll(self, key: str) -> bool:
        try:
            return bool(self._store.check([key]))
        except Exception:
            return False

    def fetch(
        self,
        key: str,
        timeout_s: float,
        abort_check: Optional[Callable[[], None]] = None,
        peer: Optional[str] = None,
    ) -> bytes:
        deadline = time.monotonic() + timeout_s
        while True:
            if self.poll(key):
                return bytes(self._store.get(key))
            if abort_check is not None:
                abort_check()
            if time.monotonic() >= deadline:
                raise TransportTimeout(key, timeout_s)
            time.sleep(_FETCH_TICK_S)


class ShmTransport(Transport):
    """The same-host arena hop, byte-identical: a thin adapter over an
    existing :class:`~.shm.ShmChannel` (which already owns checksums,
    pressure bounds and its own bounded waits)."""

    name = "shm"

    def __init__(self, channel):
        self._ch = channel

    def post(
        self, key: str, payload: bytes, to: Sequence[str] = ()
    ) -> None:
        self._ch.put(key, payload, readers=max(len(to), 1))

    def poll(self, key: str) -> bool:
        return False  # the channel's take owns its own header poll

    def fetch(
        self,
        key: str,
        timeout_s: float,
        abort_check: Optional[Callable[[], None]] = None,
        peer: Optional[str] = None,
    ) -> bytes:
        return bytes(self._ch.take(key))


# ---------------------------------------------------------------------------
# The socket plane.
# ---------------------------------------------------------------------------

_ST_IDLE = "idle"
_ST_CONNECTED = "connected"
_ST_RETRYING = "retrying"
_ST_DEGRADED = "degraded"


def _recv_exact(
    sock: socket.socket, n: int, io_s: float, idle_ok: bool = False
) -> Optional[bytes]:
    """Read exactly ``n`` bytes with a bounded deadline. ``idle_ok``:
    a timeout with ZERO bytes read returns None (an idle link is not an
    error); a timeout mid-object is a torn wire and raises. EOF raises
    OSError — the caller tears the connection down either way."""
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    deadline = time.monotonic() + io_s
    while got < n:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            if idle_ok and got == 0:
                return None
            raise OSError(f"recv deadline expired at {got}/{n} bytes")
        sock.settimeout(min(remaining, _IDLE_TICK_S))
        try:
            k = sock.recv_into(view[got:], n - got)
        except socket.timeout:
            continue
        if k == 0:
            raise OSError("connection closed by peer")
        got += k
    return bytes(buf)


class _PeerLink:
    """One supervised outbound edge: a dedicated sender thread, a
    bounded resend ring of un-acked frames, and the reconnect /
    degrade ladder. All socket i/o happens OUTSIDE ``_cond``."""

    def __init__(self, plane: "SocketTransport", peer_id: str):
        self._plane = plane
        self.peer = peer_id
        self.peer_rank = _peer_rank(peer_id)
        self._cond = threading.Condition()
        self._queue: deque = deque()  # (kind, seq, key, payload)
        self._ring: "OrderedDict[int, Tuple[str, bytes]]" = OrderedDict()
        self._next_seq = 1
        self._acked = 0
        self._sock: Optional[socket.socket] = None
        self._force_reconnect = False
        self.state = _ST_IDLE
        self.last_send_t = time.monotonic()
        self.last_ack_t = time.monotonic()
        self.reconnects = 0
        self.resends = 0
        self._thread = threading.Thread(
            target=self._run, name=f"cgx-tp-tx-{peer_id}", daemon=True
        )
        self._thread.start()

    # -- producer side ---------------------------------------------------

    def post(self, key: str, payload: bytes) -> None:
        """Enqueue one frame (seq assigned HERE — a replay reuses it).
        A full resend ring bounds the producer: it waits for acks in
        slices and, past the cap, degrades the edge instead of blocking
        a collective forever."""
        cap_deadline = time.monotonic() + self._plane.post_cap_s
        while True:
            with self._cond:
                if self.state == _ST_DEGRADED:
                    break
                if len(self._ring) < self._plane.ring_cap:
                    seq = self._next_seq
                    self._next_seq += 1
                    self._ring[seq] = (key, payload)
                    self._queue.append((_K_DATA, seq, key, payload))
                    self._cond.notify_all()
                    metrics.add("cgx.transport.posts")
                    return
                self._cond.wait(_FETCH_TICK_S)
            if time.monotonic() >= cap_deadline:
                self.degrade("resend ring full past post deadline")
                break
        self._plane._store_post(key, payload)

    def enqueue_ping(self) -> None:
        with self._cond:
            if self.state != _ST_CONNECTED:
                return
            self._queue.append((_K_PING, 0, "", b""))
            self._cond.notify_all()
        metrics.add("cgx.transport.pings")

    def request_reconnect(self, why: str) -> None:
        """Supervisor verdict (stale acks): force a teardown/replay even
        though writes still succeed locally (the classic half-open)."""
        with self._cond:
            if self.state != _ST_CONNECTED:
                return
            self._force_reconnect = True
            self._cond.notify_all()
        flightrec.record(
            "transport_force_reconnect", peer=self.peer, why=why,
        )

    def on_ack(self, seq: int) -> None:
        with self._cond:
            while self._ring and next(iter(self._ring)) <= seq:
                self._ring.popitem(last=False)
            self._acked = max(self._acked, seq)
            self.last_ack_t = time.monotonic()
            self._cond.notify_all()
        metrics.add("cgx.transport.acks_rx")

    def snapshot(self) -> Dict[str, object]:
        with self._cond:
            return {
                "peer": self.peer,
                "state": self.state,
                "unacked": len(self._ring),
                "queued": len(self._queue),
                "reconnects": self.reconnects,
                "resends": self.resends,
                "last_send_age_s": time.monotonic() - self.last_send_t,
                "last_ack_age_s": time.monotonic() - self.last_ack_t,
            }

    # -- sender thread ---------------------------------------------------

    def _run(self) -> None:
        while not self._plane.stopped:
            force = False
            item = None
            with self._cond:
                if not self._queue and not self._force_reconnect:
                    self._cond.wait(_IDLE_TICK_S)
                if self._force_reconnect:
                    force, self._force_reconnect = True, False
                elif self._queue:
                    item = self._queue.popleft()
            if force:
                self._teardown("supervisor stale-ack reconnect")
                try:
                    self._ensure_connected()
                except _Degraded:
                    pass
                continue
            if item is None:
                # Idle with un-acked frames on a torn link: nothing else
                # re-enters the ladder (the supervisor only watches
                # CONNECTED links), so the lone-last-frame case must
                # reconnect-and-replay from here.
                with self._cond:
                    orphaned = bool(self._ring) and self.state == _ST_RETRYING
                if orphaned:
                    try:
                        self._ensure_connected()
                    except _Degraded:
                        pass
                continue
            kind, seq, key, payload = item
            if self.state == _ST_DEGRADED:
                if kind == _K_DATA:
                    self._plane._store_post(key, payload)
                continue
            try:
                sock = self._ensure_connected()
                self._send_frame(sock, kind, seq, key, payload)
            except _Degraded:
                continue  # the degrade flush already shipped the ring
            except OSError as e:
                # The frame (if DATA) is still in the ring: the
                # reconnect replay owns redelivery. PINGs just drop.
                self._teardown(f"send failed: {e}")

    def _ensure_connected(self) -> socket.socket:
        with self._cond:
            if self._sock is not None and self.state == _ST_CONNECTED:
                return self._sock
            was_connected = self.state == _ST_CONNECTED
            self.state = _ST_RETRYING
        retry = WaitRetry(
            f"transport:{self.peer}",
            retries=self._plane.retries,
            backoff_ms=self._plane.backoff_ms,
        )
        attempts = 0
        while not self._plane.stopped:
            attempts += 1
            try:
                sock = self._connect_once()
            except OSError as e:
                metrics.add("cgx.transport.conn_errors")
                if not retry.attempt(self.peer):
                    self.degrade(
                        f"reconnect ladder exhausted after {attempts} "
                        f"attempts: {e}"
                    )
                    raise _Degraded from None
                continue
            try:
                replay = self._install(sock, reconnect=was_connected or attempts > 1)
                for rseq, (rkey, rpayload) in replay:
                    self._send_frame(sock, _K_DATA, rseq, rkey, rpayload)
                    with self._cond:
                        self.resends += 1
                    metrics.add("cgx.transport.resends")
            except OSError as e:
                self._teardown(f"replay failed: {e}")
                if not retry.attempt(self.peer):
                    self.degrade(f"replay ladder exhausted: {e}")
                    raise _Degraded from None
                continue
            return sock
        raise _Degraded

    def _connect_once(self) -> socket.socket:
        inj = self._plane.injector
        if inj is not None and (
            inj.window("conn_reset")
            or inj.window("partition", peer=self.peer_rank)
        ):
            raise ConnectionResetError("injected fault window")
        host, port = self._plane._resolve_addr(self.peer)
        sock = socket.create_connection(
            (host, port), timeout=self._plane.io_s
        )
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(self._plane.io_s)
            hello = self._plane.my_id.encode(_KEY_ENC)
            hdr = _FRAME.pack(
                _MAGIC, _K_HELLO, 0, len(hello), 0, 0, _NO_CRC
            )
            sock.sendall(hdr + hello)
        except OSError:
            sock.close()
            raise
        return sock

    def _install(
        self, sock: socket.socket, reconnect: bool
    ) -> List[Tuple[int, Tuple[str, bytes]]]:
        with self._cond:
            self.state = _ST_CONNECTED
            self._sock = sock
            self.last_ack_t = time.monotonic()
            if reconnect:
                self.reconnects += 1
            # Everything un-acked replays from the ring in seq order;
            # queued DATA copies would only be dedup'd duplicates.
            self._queue = deque(
                i for i in self._queue if i[0] != _K_DATA
            )
            replay = list(self._ring.items())
        if reconnect:
            metrics.add("cgx.transport.reconnects")
            flightrec.record(
                "transport_reconnect", peer=self.peer,
                replay=len(replay),
            )
        threading.Thread(
            target=self._ack_loop, args=(sock,),
            name=f"cgx-tp-ack-{self.peer}", daemon=True,
        ).start()
        return replay

    def _send_frame(
        self, sock: socket.socket, kind: int, seq: int, key: str,
        payload: bytes,
    ) -> None:
        inj = self._plane.injector
        if inj is not None:
            if inj.window("conn_reset") or inj.window(
                "partition", peer=self.peer_rank
            ):
                self._teardown("injected fault window")
                raise ConnectionResetError("injected fault window")
            inj.delay_edge("slow_link", "tcp")
        kb = key.encode(_KEY_ENC)
        crc = _wire_crc(payload) if kind == _K_DATA else _NO_CRC
        hdr = _FRAME.pack(
            _MAGIC, kind, 0, len(kb), seq, len(payload), crc
        )
        if self._plane.throttle is not None:
            self._plane.throttle.acquire(
                _FRAME.size + len(kb) + len(payload)
            )
        if inj is not None and kind == _K_DATA and inj.fire("partial_write"):
            torn = (hdr + kb + payload)[: (_FRAME.size + len(kb) + len(payload)) // 2]
            try:
                sock.settimeout(self._plane.io_s)
                sock.sendall(torn)
            finally:
                self._teardown("injected partial_write")
            raise ConnectionResetError("injected partial_write")
        sock.settimeout(self._plane.io_s)
        # Scatter/gather: header + key + payload leave in one syscall
        # with no staging concat of the payload bytes.
        total = _FRAME.size + len(kb) + len(payload)
        sent = sock.sendmsg([hdr, kb, payload])
        if sent < total:
            rest = (hdr + kb + bytes(payload))[sent:]
            sock.sendall(rest)
        with self._cond:
            self.last_send_t = time.monotonic()
        if kind == _K_DATA:
            metrics.add("cgx.transport.frames_tx")
            metrics.add("cgx.transport.bytes_tx", total)

    def _ack_loop(self, sock: socket.socket) -> None:
        """Per-connection ACK reader (dies with its socket): cumulative
        watermarks pop the resend ring and feed the supervisor's
        stale-ack detector."""
        try:
            while not self._plane.stopped and self._sock is sock:
                hdr = _recv_exact(
                    sock, _FRAME.size, self._plane.io_s, idle_ok=True
                )
                if hdr is None:
                    continue  # idle — deadline per slice, loop re-arms
                magic, kind, _, klen, seq, plen, _ = _FRAME.unpack(hdr)
                if magic != _MAGIC:
                    raise OSError("bad frame magic on ack channel")
                if klen or plen:
                    _recv_exact(sock, klen + plen, self._plane.io_s)
                if kind == _K_ACK:
                    self.on_ack(seq)
        except OSError:
            pass  # sender thread discovers on its next write

    def _teardown(self, why: str) -> None:
        with self._cond:
            sock, self._sock = self._sock, None
            if self.state == _ST_CONNECTED:
                self.state = _ST_RETRYING
        if sock is not None:
            try:
                sock.close()
            finally:
                flightrec.record(
                    "transport_teardown", peer=self.peer, why=why,
                )

    def degrade(self, why: str) -> None:
        """Exhausted ladder → the edge leaves the socket plane for good
        (this generation): flush every un-acked frame to the store path
        — same keys, bit-identical payload bytes — and tell the health
        plane. Never raises."""
        with self._cond:
            if self.state == _ST_DEGRADED:
                return
            self.state = _ST_DEGRADED
            sock, self._sock = self._sock, None
            flush = list(self._ring.items())
            self._ring.clear()
            self._queue.clear()
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for _, (key, payload) in flush:
            self._plane._store_post(key, payload)
        metrics.add("cgx.transport.link_down")
        metrics.set(
            "cgx.transport.degraded_edges",
            float(self._plane.degraded_count()),
        )
        flightrec.record(
            "transport_link_down", peer=self.peer, why=why,
            flushed=len(flush), retries=self._plane.retries,
        )
        log.warning(
            "transport edge to peer %s degraded to store (%s; %d frames "
            "flushed)", self.peer, why, len(flush),
        )
        self._plane._notify_link_down(self)

    def close(self) -> None:
        with self._cond:
            sock, self._sock = self._sock, None
            self._cond.notify_all()
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


class ConnectionSupervisor:
    """Per-rank link health thread: idle pings keep ack watermarks
    flowing on quiet links; a connected link with un-acked frames and a
    stale ack watermark is forced through the reconnect ladder (the
    half-open TCP case writes cannot detect)."""

    def __init__(self, plane: "SocketTransport"):
        self._plane = plane
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="cgx-tp-supervisor", daemon=True
        )
        self._thread.start()

    def _run(self) -> None:
        ping_s = self._plane.ping_s
        stale_s = self._plane.stale_s
        while not self._stop.wait(ping_s):
            now = time.monotonic()
            for link in self._plane.links():
                if link.state != _ST_CONNECTED:
                    continue
                with link._cond:
                    idle = now - link.last_send_t
                    ack_age = now - link.last_ack_t
                    unacked = len(link._ring)
                if unacked and ack_age > stale_s:
                    link.request_reconnect(
                        f"{unacked} un-acked frames, last ack "
                        f"{ack_age:.1f}s ago"
                    )
                elif idle > ping_s:
                    link.enqueue_ping()

    def stop(self) -> None:
        self._stop.set()


class SocketTransport(Transport):
    """The supervised TCP plane (module docstring has the contract)."""

    name = "socket"

    def __init__(
        self,
        store,
        my_id: str,
        addr_key: Callable[[str], str],
        rank: Optional[int] = None,
        io_timeout_s: Optional[float] = None,
        retries: Optional[int] = None,
        backoff_ms: Optional[float] = None,
        ping_s: Optional[float] = None,
        ring_cap: Optional[int] = None,
        on_link_down: Optional[Callable[[str, Optional[int]], None]] = None,
        throttle=None,
    ):
        self._store = store
        self.my_id = my_id
        self._addr_key = addr_key
        self.rank = rank
        self.io_s = (
            cfg.transport_io_timeout_ms() / 1000.0
            if io_timeout_s is None else io_timeout_s
        )
        self.retries = (
            cfg.transport_retries() if retries is None else retries
        )
        self.backoff_ms = (
            cfg.transport_backoff_ms() if backoff_ms is None else backoff_ms
        )
        self.ping_s = (
            cfg.transport_ping_ms() / 1000.0 if ping_s is None else ping_s
        )
        self.ring_cap = cfg.transport_ring() if ring_cap is None else ring_cap
        # Stale-ack horizon and the producer's ring-full cap: both a
        # small multiple of the io deadline so detection stays well
        # ahead of CGX_BRIDGE_TIMEOUT_MS.
        self.stale_s = 2.0 * self.io_s + self.ping_s
        self.post_cap_s = self.io_s * (self.retries + 2)
        self.throttle = throttle
        self.injector = faults_mod.get_injector(rank)
        self._on_link_down = on_link_down
        self._stop = threading.Event()
        self._links: Dict[str, _PeerLink] = {}
        self._links_lock = threading.Lock()
        self._mailbox: Dict[str, bytes] = {}
        self._rx_cond = threading.Condition()
        self._rx_seq: Dict[str, int] = {}
        self._addr_cache: Dict[str, Tuple[str, int]] = {}
        host = cfg.transport_host()
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        try:
            srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            srv.bind((host, 0))
            srv.listen(128)
            srv.settimeout(_ACCEPT_TICK_S)
            port = srv.getsockname()[1]
            store.set(addr_key(my_id), f"{host}:{port}".encode(_KEY_ENC))
        except OSError:
            srv.close()
            raise
        self._srv = srv
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="cgx-tp-accept", daemon=True
        )
        self._accept_thread.start()
        self.supervisor = ConnectionSupervisor(self)
        flightrec.record(
            "transport_up", my_id=my_id, port=port, rank=rank,
        )

    # -- plumbing --------------------------------------------------------

    @property
    def stopped(self) -> bool:
        return self._stop.is_set()

    def links(self) -> List[_PeerLink]:
        with self._links_lock:
            return list(self._links.values())

    def link(self, peer_id: str) -> _PeerLink:
        with self._links_lock:
            lk = self._links.get(peer_id)
            if lk is None:
                lk = _PeerLink(self, peer_id)
                self._links[peer_id] = lk
            return lk

    def degraded_count(self) -> int:
        return sum(
            1 for lk in self.links() if lk.state == _ST_DEGRADED
        )

    def down_peers(self) -> List[str]:
        """Peers whose edge degraded — suspect hints for the bounded
        waits' error naming."""
        return sorted(
            lk.peer for lk in self.links() if lk.state == _ST_DEGRADED
        )

    def status(self) -> List[Dict[str, object]]:
        return [lk.snapshot() for lk in self.links()]

    def _notify_link_down(self, link: _PeerLink) -> None:
        if self._on_link_down is not None:
            try:
                self._on_link_down(link.peer, link.peer_rank)
            except Exception:
                log.warning(
                    "transport link_down callback failed for peer %s",
                    link.peer, exc_info=True,
                )

    def _store_post(self, key: str, payload: bytes) -> None:
        """The degrade path: the same key, the same bytes, on the plane
        every peer can always read."""
        self._store.set(key, payload)
        metrics.add("cgx.transport.degraded_posts")

    def _store_check(self, key: str) -> bool:
        try:
            return bool(self._store.check([key]))
        except Exception:
            return False

    def _resolve_addr(self, peer_id: str) -> Tuple[str, int]:
        addr = self._addr_cache.get(peer_id)
        if addr is not None:
            return addr
        key = self._addr_key(peer_id)
        deadline = time.monotonic() + self.io_s
        while time.monotonic() < deadline:
            if self._store_check(key):
                raw = bytes(self._store.get(key)).decode(_KEY_ENC)
                host, _, port = raw.rpartition(":")
                addr = (host, int(port))
                self._addr_cache[peer_id] = addr
                return addr
            time.sleep(_ADDR_POLL_S)
        raise OSError(
            f"transport address for peer {peer_id!r} not published "
            f"({key})"
        )

    # -- Transport interface --------------------------------------------

    def post(
        self, key: str, payload: bytes, to: Sequence[str] = ()
    ) -> None:
        payload = bytes(payload)
        for peer_id in to:
            self.link(peer_id).post(key, payload)

    def poll(self, key: str) -> bool:
        with self._rx_cond:
            if key in self._mailbox:
                return True
        return self._store_check(key)

    def fetch(
        self,
        key: str,
        timeout_s: float,
        abort_check: Optional[Callable[[], None]] = None,
        peer: Optional[str] = None,
    ) -> bytes:
        """Bounded dual-probe read: the socket mailbox every slice, the
        store fallback every ``_STORE_PROBE_S`` — correctness never
        depends on both ends agreeing whether the edge is degraded."""
        metrics.add("cgx.transport.fetches")
        deadline = time.monotonic() + timeout_s
        next_probe = 0.0
        while True:
            with self._rx_cond:
                data = self._mailbox.pop(key, None)
                if data is None:
                    self._rx_cond.wait(_FETCH_TICK_S)
                    data = self._mailbox.pop(key, None)
            if data is not None:
                return data
            if abort_check is not None:
                abort_check()
            now = time.monotonic()
            if now >= next_probe:
                next_probe = now + _STORE_PROBE_S
                if self._store_check(key):
                    metrics.add("cgx.transport.store_fetches")
                    return bytes(self._store.get(key))
            if now >= deadline:
                raise TransportTimeout(key, timeout_s)

    def close(self) -> None:
        self._stop.set()
        self.supervisor.stop()
        try:
            self._srv.close()
        finally:
            for lk in self.links():
                lk.close()
        flightrec.record("transport_down", my_id=self.my_id)

    # -- inbound side ----------------------------------------------------

    def _accept_loop(self) -> None:
        srv = self._srv
        while not self._stop.is_set():
            try:
                srv.settimeout(_ACCEPT_TICK_S)
                conn, _ = srv.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(
                target=self._rx_loop, args=(conn,),
                name="cgx-tp-rx", daemon=True,
            ).start()

    def _recv_frame(
        self, conn: socket.socket
    ) -> Optional[Tuple[int, int, str, bytes]]:
        hdr = _recv_exact(conn, _FRAME.size, self.io_s, idle_ok=True)
        if hdr is None:
            return None
        magic, kind, _, klen, seq, plen, crc = _FRAME.unpack(hdr)
        if magic != _MAGIC:
            raise OSError("bad frame magic")
        body = _recv_exact(conn, klen + plen, self.io_s) if klen + plen else b""
        key = body[:klen].decode(_KEY_ENC)
        payload = body[klen:]
        if kind == _K_DATA and crc != _NO_CRC:
            if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                metrics.add("cgx.transport.crc_drops")
                raise OSError(f"crc mismatch on frame {key!r}")
        return kind, seq, key, payload

    def _send_ack(self, conn: socket.socket, seq: int) -> None:
        conn.settimeout(self.io_s)
        conn.sendall(_FRAME.pack(_MAGIC, _K_ACK, 0, 0, seq, 0, _NO_CRC))

    def _rx_loop(self, conn: socket.socket) -> None:
        """Per-inbound-connection reader: HELLO names the peer, DATA
        frames dedup against the peer's delivered watermark (replays
        resend in seq order on one ordered stream, so a cumulative
        watermark is exact), every DATA/PING is answered with a
        cumulative ACK."""
        peer: Optional[str] = None
        try:
            while not self._stop.is_set():
                frame = self._recv_frame(conn)
                if frame is None:
                    continue
                kind, seq, key, payload = frame
                if kind == _K_HELLO:
                    peer = key
                    continue
                if peer is None:
                    raise OSError("frame before HELLO")
                if kind == _K_PING:
                    with self._rx_cond:
                        hw = self._rx_seq.get(peer, 0)
                    self._send_ack(conn, hw)
                    continue
                if kind != _K_DATA:
                    continue
                with self._rx_cond:
                    hw = self._rx_seq.get(peer, 0)
                    if seq > hw:
                        self._rx_seq[peer] = hw = seq
                        self._mailbox[key] = payload
                        self._rx_cond.notify_all()
                        fresh = True
                    else:
                        fresh = False
                if fresh:
                    metrics.add("cgx.transport.frames_rx")
                    metrics.add(
                        "cgx.transport.bytes_rx",
                        _FRAME.size + len(key) + len(payload),
                    )
                else:
                    metrics.add("cgx.transport.dedup_drops")
                self._send_ack(conn, hw)
        except OSError:
            pass
        finally:
            conn.close()


# ---------------------------------------------------------------------------
# The store shim: existing senders/receivers ride the plane unchanged.
# ---------------------------------------------------------------------------


class TransportStore:
    """A c10d-store lookalike that routes *payload-prefix* keys through
    a :class:`SocketTransport` and passes everything else (counters,
    flags, waits) to the real store untouched. Handed to
    ``AsyncBridgeSender`` / ``KvPageSender`` / ``KvPageReceiver``
    construction sites, the publish-after-write protocol they already
    speak rides the socket plane with zero changes: ``set`` becomes a
    framed post toward the construction-time peer set, ``get`` becomes
    a mailbox fetch with the store as fallback."""

    def __init__(
        self,
        store,
        plane: SocketTransport,
        peers: Sequence[str],
        prefixes: Sequence[str],
        fetch_timeout_s: Optional[float] = None,
        exclude: Sequence[str] = (),
    ):
        self._store = store
        self._plane = plane
        self._peers = tuple(peers)
        self._prefixes = tuple(prefixes)
        # Substring opt-outs under a routed prefix: control keys (elastic
        # re-requests) whose reader set differs from the page stream's
        # construction-time peers stay on the plain store.
        self._exclude = tuple(exclude)
        bt = cfg.bridge_timeout_ms()
        self._fetch_s = (
            fetch_timeout_s if fetch_timeout_s is not None
            else (bt / 1000.0 if bt else 60.0)
        )

    @property
    def transport_plane(self) -> SocketTransport:
        return self._plane

    def _routed(self, key: str) -> bool:
        if not any(key.startswith(p) for p in self._prefixes):
            return False
        return not any(x in key for x in self._exclude)

    def set(self, key: str, value) -> None:
        if self._routed(key):
            self._plane.post(key, bytes(value), to=self._peers)
        else:
            self._store.set(key, value)

    def get(self, key: str):
        if self._routed(key):
            return self._plane.fetch(key, self._fetch_s)
        return self._store.get(key)

    def add(self, key: str, n: int):
        return self._store.add(key, n)

    def check(self, keys) -> bool:
        routed = [k for k in keys if self._routed(k)]
        if routed and all(self._plane.poll(k) for k in routed):
            rest = [k for k in keys if not self._routed(k)]
            return bool(self._store.check(rest)) if rest else True
        return self._store.check(keys)

    def wait(self, keys, *a):
        return self._store.wait(keys, *a)

    def delete_key(self, key: str):
        if self._routed(key):
            # Socket payloads are popped on fetch — nothing to refcount.
            return True
        return self._store.delete_key(key)

    def __getattr__(self, name: str):
        return getattr(self._store, name)


def _serving_addr_key(peer_id: str) -> str:
    return f"cgxtp/addr/{peer_id}"


def maybe_wrap_store(
    store,
    endpoint: str,
    peers: Sequence[str],
    prefixes: Sequence[str],
    rank: Optional[int] = None,
    fetch_timeout_s: Optional[float] = None,
    exclude: Sequence[str] = (),
):
    """Engage the socket plane for a serving/elastic page stream iff
    ``CGX_TRANSPORT=socket`` — otherwise return ``store`` UNCHANGED
    (the identity is the byte-compatibility pin: with the knob unset no
    store key, wire byte or code path differs from HEAD). The returned
    wrapper owns a private plane registered under ``endpoint`` in the
    store's address book."""
    if cfg.transport_mode() != "socket":
        return store
    plane = SocketTransport(
        store, my_id=endpoint, addr_key=_serving_addr_key, rank=rank,
    )
    return TransportStore(
        store, plane, peers=peers, prefixes=prefixes,
        fetch_timeout_s=fetch_timeout_s, exclude=exclude,
    )
