"""Pure-Python ``torch.distributed`` backend ``"cgx"``.

Re-expression of the reference's c10d ProcessGroup extension
(/root/reference/src/ProcessGroupCGX.{h,cc} — SURVEY.md §2.1, §3.2) without
MPI/CUDA: the *architecture* is preserved —

* a c10d ``ProcessGroup`` registered under the backend name ``"cgx"``
  (reference registers at import via ``__attribute__((constructor))``,
  ProcessGroupCGX.h:258-263; here :func:`register_backend` at module import),
* a single background **worker thread** consuming a FIFO queue of work
  entries and completing futures (the ``runLoop`` model,
  ProcessGroupCGX.cc:300-339),
* ``allreduce`` with a **quantized SRA/Ring path** for eligible float SUM
  buffers and a plain fallback otherwise (ProcessGroupCGX.cc:369-420),
* per-layer compression configs resolved from the registry filled by
  ``register_layer`` (ProcessGroupCGX.cc:837-857), applied with
  fusion-aware **per-layer framing** of each wire chunk
  (compressor.cc:62-179),
* the requantize + self-dequantize **error-symmetry step** on the reduced
  chunk (scatter_reduce_allgather.cc:157-160) so exactness oracles hold,
* thin uncompressed wrappers for broadcast / allgather / gather / scatter /
  alltoall / send / recv / barrier (ProcessGroupCGX.cc:341-833), plus
  ``alltoall_base`` with even (MPI_Alltoall) and uneven (MPI_Alltoallv)
  splits — the ``dist.all_to_all_single`` entry point
  (ProcessGroupCGX.cc:638-705),
* ``all_gather_into_tensor`` / ``reduce_scatter_tensor`` — the collectives
  FSDP/ZeRO sharding is built from; the reference throws on both
  (ProcessGroupCGX.cc:631-636,827-833), which is why FSDP can never run on
  it. ``reduce_scatter_tensor`` compresses eligible float chunks (it is the
  scatter-reduce half of SRA); ``all_gather_into_tensor`` compresses the
  parameter gather when ``CGX_FSDP_ALLGATHER_BITS`` is set (both halves of
  ZeRO-3's per-step traffic ride the wire format), and
* NotImplementedError on ``allreduce_coalesced`` like the reference
  (ProcessGroupCGX.cc:422-428).

The transport re-expresses the reference's two-plane split (SURVEY.md §7):
the c10d **Store** the group is constructed with is the portable control
plane (ordering, rendezvous, refcounted key GC, cross-host payloads), and
same-host ranks additionally carry payload bytes over an mmap'd **/dev/shm
data plane** (``shm.py`` — the shm_communicator.cc role; headers + acks
stay in the store). Groups spanning hosts run the reference's two-level
leader reduction (intra SHM reduce → leader cross-reduce → intra
broadcast, mpi_allreduce_operations.cc:139-185). ``abort()`` poisons the
group through the store so peers fail fast (ProcessGroupCGX.cc:295-298),
and every blocking wait is bounded by the group timeout. The codec — the
actual CPU work — runs in the native C++ core when built.

The codec math and wire format are byte-identical to the JAX/Pallas codec
(``ops/codec_host.py``), so a payload compressed here decodes on the TPU
path and vice versa.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import threading
import time
import queue as _queue
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np
import torch
import torch.distributed as dist
from torch.futures import Future

from .. import config as cfg
from ..observability import exporter as obs_exporter
from ..observability import flightrec
from ..observability import health as health_mod
from ..observability import memledger as memledger_mod
from ..observability import timeline
from ..observability import watch as watch_mod
from ..ops import codec_host as hcodec
from ..robustness import faults as faults_mod
from ..robustness import heartbeat as hb_mod
from ..robustness import retry as retry_mod
from ..robustness.errors import (
    BridgeTimeoutError,
    EvictedError,
    StaleGenerationError,
    WireCorruptionError,
)
from ..utils.logging import get_logger, metrics

log = get_logger()

BACKEND_NAME = "cgx"
_ALIGN = 8  # element alignment of chunk splits (reference utils.h ALIGNMENT_UNIT)

_TORCH_FLOATS = (torch.float32, torch.float16, torch.bfloat16)

# torch dtype <-> numpy dtype for the uncompressed wire (bf16 goes through
# its raw uint16 bit pattern; numpy has no native bfloat16).
_NP_OF_TORCH = {
    torch.float32: np.float32,
    torch.float64: np.float64,
    torch.float16: np.float16,
    torch.int32: np.int32,
    torch.int64: np.int64,
    torch.int16: np.int16,
    torch.int8: np.int8,
    torch.uint8: np.uint8,
    torch.bool: np.bool_,
}


def _wire_dtype(torch_dtype) -> np.dtype:
    """Wire dtype for meta/residual framing of a compressed tensor.

    bf16 tensors frame with bf16 meta — half the meta bytes on the wire,
    the reference's store-meta-in-input-dtype economics
    (compressor.cc:401-419); bf16 via ml_dtypes (numpy has none). fp16
    deliberately stays f32-framed: the fused accumulator holds f32 partial
    sums whose magnitude (and thus bucket unit/min) can exceed the fp16
    range mid-reduction, so fp16 meta would go inf; bf16 shares the f32
    exponent range and cannot overflow.
    """
    if torch_dtype == torch.bfloat16:
        import ml_dtypes

        return np.dtype(ml_dtypes.bfloat16)
    return np.dtype(np.float32)


def _to_np(t: torch.Tensor) -> np.ndarray:
    """Host copy of a tensor as a flat numpy array (bf16 -> f32, exact)."""
    t = t.detach()
    if t.dtype == torch.bfloat16:
        return t.to(torch.float32).numpy().reshape(-1)
    return t.numpy().reshape(-1).copy()


def _from_np(t: torch.Tensor, a: np.ndarray) -> None:
    """Write a flat numpy array back into tensor t (any float narrowing is
    done by torch, matching how the reference writes reduced fp16).
    ``copy_`` on the original tensor is stride-aware, so non-contiguous
    targets receive the data too (a reshape(-1) view would silently write
    into a detached copy)."""
    with torch.no_grad():
        src = torch.from_numpy(np.ascontiguousarray(a))
        t.detach().copy_(src.to(t.dtype).reshape(t.shape))


# ---------------------------------------------------------------------------
# Per-layer framed codec: one wire chunk carries multiple independently
# configured layer segments (reference compressor.cc:62-179).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Segment:
    """A [start, start+numel) slice of the fused buffer with its resolved
    compression config (the reference's per-layer slice of a chunk)."""

    start: int
    numel: int
    bits: int
    bucket_size: int


def _segments_in(
    layers: Sequence[Tuple[int, int, cfg.CompressionConfig]],
    lo: int,
    hi: int,
) -> List[_Segment]:
    """Intersect fused-coordinate layers with the chunk [lo, hi)."""
    out = []
    for start, numel, c in layers:
        s, e = max(start, lo), min(start + numel, hi)
        if s < e:
            out.append(_Segment(s, e - s, c.bits, c.bucket_size))
    return out


def _compress_frames(
    fused: np.ndarray, segs: Sequence[_Segment], dummy: bool,
    rng: Optional[np.random.Generator], wire_dtype=np.float32,
) -> bytes:
    """Concatenated per-segment wire frames. Frame sizes are a pure function
    of (numel, bits, bucket, wire dtype) so the receiver needs no header.

    ``wire_dtype`` is the tensor's own dtype for 16-bit floats: meta (and any
    residual) travel at half the bytes, matching the reference's
    store-meta-in-input-dtype wire economics (compressor.cc:401-419).
    Quantization math stays float32 regardless (the host codec upcasts)."""
    from . import device_codec

    t0 = time.perf_counter()
    parts: List[np.ndarray] = []
    for s in segs:
        x = fused[s.start : s.start + s.numel]
        if dummy:
            parts.append(np.ascontiguousarray(x, np.float32).view(np.uint8))
        elif device_codec.enabled(s.numel):
            # Accelerator-resident codec (reference: compression lives where
            # the gradients live, ProcessGroupCGX.cc:374-407).
            wire = device_codec.quantize(
                np.ascontiguousarray(x, np.float32),
                s.bits,
                s.bucket_size,
                stochastic_seed=(
                    int(rng.integers(2**31 - 1)) if rng is not None else None
                ),
                meta_dtype=wire_dtype,
            )
            parts.append(np.frombuffer(wire, np.uint8))
        else:
            q = hcodec.quantize(
                np.ascontiguousarray(x, np.float32),
                s.bits,
                s.bucket_size,
                stochastic=rng is not None,
                rng=rng,
                meta_dtype=wire_dtype,
            )
            parts.append(q.to_bytes())
    if not parts:
        return b""
    out = np.concatenate(parts).tobytes()
    timeline.record(
        "codec.compress", timeline.CAT_QUANTIZE, t0,
        time.perf_counter() - t0,
        elems=sum(s.numel for s in segs), bytes=len(out),
    )
    return out


def _decompress_frames(
    buf: np.ndarray, segs: Sequence[_Segment], fused: np.ndarray,
    dummy: bool, add: bool, wire_dtype=np.float32,
) -> None:
    """Decode frames into the fused buffer at their segment positions,
    accumulating (round 1) or assigning (allgather round)."""
    from . import device_codec

    t0 = time.perf_counter()
    off = 0
    for s in segs:
        sl = slice(s.start, s.start + s.numel)
        if dummy:
            nb = s.numel * 4
            vals = buf[off : off + nb].view(np.float32)
            off += nb
        elif device_codec.enabled(s.numel):
            nb = hcodec.wire_layout(s.numel, s.bits, s.bucket_size, wire_dtype)[3]
            vals = device_codec.dequantize(
                buf[off : off + nb], s.numel, s.bits, s.bucket_size,
                meta_dtype=wire_dtype,
            )
            off += nb
        else:
            nb = hcodec.wire_layout(s.numel, s.bits, s.bucket_size, wire_dtype)[3]
            q = hcodec.from_bytes(
                buf[off : off + nb], s.numel, s.bits, s.bucket_size, wire_dtype
            )
            vals = hcodec.dequantize(q, out_dtype=np.float32)
            off += nb
        if add:
            fused[sl] += vals
        else:
            fused[sl] = vals
    if segs:
        timeline.record(
            "codec.decompress", timeline.CAT_QUANTIZE, t0,
            time.perf_counter() - t0,
            elems=sum(s.numel for s in segs), bytes=int(off),
        )


def _requantize_frames(
    fused: np.ndarray, segs: Sequence[_Segment], dummy: bool,
    rng: Optional[np.random.Generator], wire_dtype=np.float32,
) -> bytes:
    """The SRA/Ring epilogue in one pass: requantize the reduced chunk and
    self-dequantize it back into ``fused`` (the error-symmetry rule —
    every replica must carry the identical quantization error,
    scatter_reduce_allgather.cc:157-160). Host mirror of the jax-side
    fused ``sra_epilogue`` kernel: wire bytes and written-back values are
    identical to the staged ``_compress_frames`` + ``_decompress_frames``
    pair it replaces, but the host codec decodes straight from the
    in-memory QTensor (no wire re-parse) and the timeline carries ONE
    ``codec.sra_epilogue`` span where the staged pair emitted two
    ``codec.compress``/``codec.decompress`` spans."""
    from . import device_codec

    t0 = time.perf_counter()
    parts: List[np.ndarray] = []
    for s in segs:
        sl = slice(s.start, s.start + s.numel)
        x = np.ascontiguousarray(fused[sl], np.float32)
        if dummy:
            parts.append(x.view(np.uint8))
            fused[sl] = x  # raw-bytes self-decode is the identity
            continue
        if device_codec.enabled(s.numel):
            wire = device_codec.quantize(
                x,
                s.bits,
                s.bucket_size,
                stochastic_seed=(
                    int(rng.integers(2**31 - 1)) if rng is not None else None
                ),
                meta_dtype=wire_dtype,
            )
            buf = np.frombuffer(wire, np.uint8)
            parts.append(buf)
            fused[sl] = device_codec.dequantize(
                buf, s.numel, s.bits, s.bucket_size, meta_dtype=wire_dtype
            )
            continue
        q = hcodec.quantize(
            x,
            s.bits,
            s.bucket_size,
            stochastic=rng is not None,
            rng=rng,
            meta_dtype=wire_dtype,
        )
        parts.append(q.to_bytes())
        fused[sl] = hcodec.dequantize(q, out_dtype=np.float32)
    out = np.concatenate(parts).tobytes() if parts else b""
    if segs:
        timeline.record(
            "codec.sra_epilogue", timeline.CAT_QUANTIZE, t0,
            time.perf_counter() - t0,
            elems=sum(s.numel for s in segs), bytes=len(out),
        )
    return out


# ---------------------------------------------------------------------------
def _planner_mod():
    """The step planner (``parallel/planner.py``) — but ONLY when some
    JAX-side caller already imported it: the bridge must never import the
    parallel package itself (the dependency-light contract of
    ``_sched_chunk_table`` below), so a pure bridge process sees None
    and uses the dependency-light default-model mirror below."""
    return sys.modules.get("torch_cgx_tpu.parallel.planner")


# Dependency-light duplicate of planner.bridge_chunks under the DEFAULT
# cost model (planner.CostModel.default()'s constants) — the same
# discipline as _sched_chunk_table: engagement is decided by ENV alone
# (cfg.planner_mode() == "on", identical on every launcher-configured
# rank), and a rank that never imported the parallel package derives the
# SAME depth as one that did, so mixed JAX/pure-bridge groups can never
# frame the collective differently. Ranks that install a CALIBRATED
# model must install the same bytes group-wide (bench.py --planner
# builds it from the shared span files) — the same group-consistency
# contract every CGX_* env knob already carries.
# tests/test_planner.py pins this mirror against planner.bridge_chunks.
_PLAN_CHUNK_CANDIDATES = (1, 2, 4, 8, 16)
_PLAN_DEFAULT_RATES = (8.0, 16.0, 1.0, 100e-6)  # q GB/s, d GB/s, wire GB/s, overhead s

# CGX_PLANNER_MODEL mirror cache: (path, mtime_ns) -> rate tuple.
# cgx-analysis: allow(orphan-memo) — (path, mtime_ns)-keyed mirror of the planner's file cache: self-invalidating on any rewrite, generation-independent
_PLAN_MODEL_CACHE: dict = {}


def _plan_model_rates() -> Tuple[float, float, float, float]:
    """The mirror's rate source: the CGX_PLANNER_MODEL file when set —
    the SAME bytes the JAX-side planner loads, so calibrated decisions
    stay group-consistent — else the built-in default constants. A
    bad/missing file falls back to defaults (never crashes the loop)."""
    path = cfg.planner_model_path()
    if not path:
        return _PLAN_DEFAULT_RATES
    try:
        mtime = os.stat(path).st_mtime_ns
    except OSError:
        return _PLAN_DEFAULT_RATES
    key = (path, mtime)
    hit = _PLAN_MODEL_CACHE.get(key)
    if hit is not None:
        return hit
    try:
        import json as _json

        with open(path) as f:
            d = _json.load(f)
        rates = (
            float(d.get("quantize_gbps", _PLAN_DEFAULT_RATES[0])),
            float(d.get("dequantize_gbps", _PLAN_DEFAULT_RATES[1])),
            float(d.get("wire_gbps", _PLAN_DEFAULT_RATES[2])),
            float(d.get("chunk_overhead_s", _PLAN_DEFAULT_RATES[3])),
        )
    except (OSError, ValueError, TypeError):
        return _PLAN_DEFAULT_RATES
    _PLAN_MODEL_CACHE.clear()
    _PLAN_MODEL_CACHE[key] = rates
    return rates


def _plan_bridge_chunks(width: int, bucket: int, ws: int, bits: int) -> int:
    """Model argmin over feasible depths of one rank-chunk (mirrors
    ``planner.CostModel.predict_slice`` + ``bridge_chunks``), rates from
    :func:`_plan_model_rates`."""
    if width <= 0 or ws <= 1:
        return 1
    q, d, w, over = _plan_model_rates()
    n = width * ws
    compressed = 1 <= bits <= 8
    t_codec = (
        4.0 * n * (1 + 1 / ws) / (q * 1e9)
        + 4.0 * n * (2 - 1 / ws) / (d * 1e9)
    ) if compressed else 0.0
    if compressed:
        # codec.wire_bytes duplicated dependency-light: per-bucket meta
        # (2 x 4-byte elems) + bit-plane words at the 32-lane grid.
        nb = -(-n // max(1, bucket))
        wire_bytes = 8.0 * nb + 4.0 * (-(-n // 32)) * bits
    else:
        wire_bytes = 4.0 * n
    t_wire = 2.0 * (ws - 1) / ws * wire_bytes / (w * 1e9)
    bottleneck = max(t_codec, t_wire)
    exposed_full = t_codec + t_wire - bottleneck
    units = width // max(1, bucket)
    best_c, best_t = 1, float("inf")
    for c in _PLAN_CHUNK_CANDIDATES:
        if c > max(1, units):
            continue
        t = bottleneck + exposed_full / c + c * over
        if t < best_t - 1e-15:
            best_c, best_t = c, t
    return best_c


# Compiled-schedule chunk plan (parallel/schedule.py), duplicated here in
# dependency-light form — same reason as the topology taxonomy below: the
# bridge must not import the parallel package into every rank process.
# tests/test_schedule.py cross-checks this against schedule.chunk_table.
# ---------------------------------------------------------------------------

_SCHED_LANE_GROUP = 32  # codec packing group (ops/codec.LANE_GROUP)
# Double-buffered in-flight window of the pipelined bridge: how many chunks
# the encoder thread may run ahead of the worker thread's take/epilogue
# (schedule._BRIDGE_WINDOW's bridge-side value — 2 = classic double
# buffering; deeper only grows arena residency without adding overlap).
_SCHED_WINDOW = 2


def _sched_chunk_table(
    width: int, chunks: int, bucket_size: int
) -> List[Tuple[int, int]]:
    """(offset, width) sub-chunk plan over one rank's chunk of ``width``
    elements — boundaries at multiples of ``lcm(bucket, 32)`` so the
    quantization bucket grid within the chunk is unchanged (the
    bit-equality contract of parallel/schedule.py). Degrades to a single
    chunk when the width is too small for the requested depth."""
    import math as _math

    if width <= 0:
        return [(0, max(width, 0))] if width else []
    align = _math.lcm(max(1, bucket_size), _SCHED_LANE_GROUP)
    chunks = max(1, int(chunks))
    units = width // align
    depth = min(chunks, units) if units else 1
    if depth <= 1:
        return [(0, width)]
    per = (units // depth) * align
    out = []
    off = 0
    for _ in range(depth - 1):
        out.append((off, per))
        off += per
    out.append((off, width - off))
    return out


# The topology router's group taxonomy (parallel/topology.py), duplicated
# here in dependency-light form: the bridge must not import the parallel
# package (it pulls flax/models) into every rank process. The duplication
# is pinned by tests/test_xla_allreduce.py, which cross-checks this
# classifier against topology.classify_hosts on the same host maps.
TOPO_SINGLE = "single"
TOPO_INTRA = "intra_slice"
TOPO_CROSS = "cross_slice"
TOPO_MIXED = "mixed"


def _host_topology(hosts: Sequence) -> str:
    """Classify a bridge group from its per-rank host fingerprints: one
    host = intra_slice (the traffic the staged in-XLA program is taking
    over — the bridge's end-state is to carry only the other classes),
    all-distinct = cross_slice (the bridge's home turf), otherwise mixed
    (the two-level leader scheme)."""
    ws = len(hosts)
    n_hosts = len(set(hosts))
    if ws <= 1:
        return TOPO_SINGLE
    if n_hosts == 1:
        return TOPO_INTRA
    if n_hosts == ws:
        return TOPO_CROSS
    return TOPO_MIXED


def _slice_leaders(hosts: Sequence) -> List[int]:
    """Group-local leader ranks, one per slice, first-seen host order —
    the dependency-light duplicate of ``topology.slice_leaders`` (pinned
    equal by tests/test_async_plane.py). Always fed the CURRENT host map
    (after a reconfigure: the survivor-filtered one), so an evicted rank
    can never be named leader."""
    seen: dict = {}
    for i, h in enumerate(hosts):
        if h not in seen:
            seen[h] = i
    return list(seen.values())


def _sra_fold_chunk(
    fused: np.ndarray,
    lo: int,
    hi: int,
    segs_me: Sequence[_Segment],
    frames,
    me: int,
    ws: int,
    dummy: bool,
    wdt=np.float32,
) -> None:
    """Decompress-accumulate the SRA stage-1 frames into the own chunk
    ``fused[lo:hi]`` with the accumulate association PINNED to the
    dispatcher's ``ordered_rowsum`` fold: ``v0 + v1 + ...`` ascending by
    peer rank, the raw own chunk at position ``me``. All three lowerings
    of the SRA epilogue — the staged XLA ops, the fused Pallas kernel and
    this host bridge — now share ONE association, which is what makes the
    staged program's stage-2 wire bytes bit-identical to the bridge's on
    the same inputs (the staged<->bridge wire contract,
    docs/COMPRESSION_GUIDE.md). The previous in-place add (own chunk
    first, then arrivals ascending) differed from this fold by a last ulp
    whenever ``me >= 2`` — and a last-ulp-different accumulate is a
    different requantized wire byte.

    ``frames``: peer rank -> wire buffer (uint8 ndarray); own rank absent.
    """
    if hi <= lo:
        return
    # Chunk-local scratch reused across peers (segments shifted to chunk
    # offsets) and in-place accumulate: the fold association is unchanged,
    # but the hot path no longer allocates a full-fused-size buffer per
    # collective plus a fresh accumulator per peer.
    segs_local = [dataclasses.replace(s, start=s.start - lo) for s in segs_me]
    scratch = np.empty(hi - lo, dtype=fused.dtype)
    acc: Optional[np.ndarray] = None
    for j in range(ws):
        if j == me:
            vals = fused[lo:hi]
        else:
            _decompress_frames(
                frames[j], segs_local, scratch, dummy, add=False,
                wire_dtype=wdt,
            )
            vals = scratch
        if acc is None:
            acc = vals.astype(np.float32, copy=True)
        else:
            acc += vals
    fused[lo:hi] = acc


def _chunk_split(
    n: int, ws: int, layers=None
) -> Tuple[List[int], List[int]]:
    """Split n fused elements into ws chunks.

    Default: equal split rounded up to 8 elements — every chunk but the
    last is a multiple of 8; trailing chunks may be empty.

    With ``CGX_LAYER_ALIGNED_SPLIT=1`` (and ``layers`` given), the
    reference's greedy layer-aligned walk instead
    (Quantizer::GetSizesAndOffsets, compressor.cc:265-299):
    :func:`_chunk_split_layer_aligned`.
    """
    if layers is not None and cfg.layer_aligned_split():
        return _chunk_split_layer_aligned(
            n, ws, [numel for (_o, numel, _c) in layers]
        )
    per = -(-n // ws)
    per = -(-per // _ALIGN) * _ALIGN
    sizes, offs, used = [], [], 0
    for _ in range(ws):
        offs.append(used)
        take = min(per, n - used)
        sizes.append(take)
        used += take
    return sizes, offs


def _chunk_split_layer_aligned(
    n: int, ws: int, layer_sizes: List[int], align: int = 32
) -> Tuple[List[int], List[int]]:
    """The reference's greedy layer-aligned split
    (Quantizer::GetSizesAndOffsets, compressor.cc:265-299): rank r's chunk
    targets ``remaining / (ws - r)`` elements, preferring WHOLE layers; a
    layer is cut only when it exceeds the rank's remaining budget, and then
    at an alignment-rounded offset. Small layers therefore never straddle a
    chunk boundary, so their quantization buckets are never split between
    two ranks' requantize stages (the wire-layout behavior delta VERDICT r4
    missing #5 called out).

    ``align`` is 32 — our packing group (LANE_GROUP) — where the reference
    uses 4/8 elements (fp32/fp16 ALIGNMENT_UNIT): the bit-plane wire packs
    32-value groups, so a 4-element alignment would only re-introduce
    straddling at the packing layer.
    """
    sizes_out: List[int] = []
    offs_out: List[int] = []
    li = 0
    remaining = n
    n_elem = min(layer_sizes[0], remaining) if layer_sizes else 0
    offset = 0
    for rank in range(ws):
        per_node = remaining // (ws - rank)
        cur = 0
        while cur < per_node:
            if n_elem <= per_node - cur:
                cur += n_elem
                li += 1
                if li == len(layer_sizes):
                    break
                n_elem = min(layer_sizes[li], remaining)
            else:
                aligned = min(
                    -(-(per_node - cur) // align) * align, n_elem
                )
                cur += aligned
                n_elem -= aligned
        remaining -= cur
        sizes_out.append(cur)
        offs_out.append(offset)
        offset += cur
    return sizes_out, offs_out


def _record_qreduce_phases(
    kind: str, pfx: str, ws: int, fused: np.ndarray, wire_out: int,
    t0: float, t1: float,
) -> None:
    """Shared phase-timing epilogue of the quantized SRA/Ring allreduce:
    scatter-reduce [t0, t1) vs allgather [t1, now) durations, wire bytes
    and the measured compression ratio — into the metrics registry
    (``cgx.<kind>.*``) and the flight recorder."""
    t2 = time.perf_counter()
    bytes_in = int(fused.nbytes)
    metrics.observe(f"cgx.{kind}.scatter_reduce_s", t1 - t0)
    metrics.observe(f"cgx.{kind}.allgather_s", t2 - t1)
    metrics.add(f"cgx.{kind}.wire_bytes_out", float(wire_out))
    # Raw-bytes sibling of wire_bytes_out: their ratio is the live wire
    # compression ratio cgx_top and the Prometheus endpoint render.
    metrics.add(f"cgx.{kind}.bytes_in", float(bytes_in))
    # Timeline: the two algorithm phases as spans keyed by the collective
    # prefix (the same key the wire messages carry — cross-rank linkable).
    timeline.record(
        f"{kind}.scatter_reduce", timeline.CAT_PHASE, t0, t1 - t0,
        key=pfx, ws=ws,
    )
    timeline.record(
        f"{kind}.allgather", timeline.CAT_PHASE, t1, t2 - t1,
        key=pfx, ws=ws, wire_bytes=wire_out,
    )
    flightrec.record(
        kind, key=pfx, ws=ws, elems=int(fused.shape[0]),
        bytes_in=bytes_in, wire_bytes_out=wire_out,
        ratio=round(bytes_in / wire_out, 3) if wire_out else None,
        scatter_reduce_s=round(t1 - t0, 6),
        allgather_s=round(t2 - t1, 6),
    )


# ---------------------------------------------------------------------------
# The process group.
# ---------------------------------------------------------------------------


class _CGXWork(dist.Work):
    """Work future completed by the worker thread.

    NOT ``_create_work_from_future``: that wrapper's ``wait()`` swallows
    future exceptions (returns success on a failed op — silent corruption);
    this subclass re-raises them, matching the reference's failed-future
    semantics (finishWorkMPIError, ProcessGroupCGX.cc:120-123)."""

    def __init__(self, fut: Future):
        super().__init__()
        self._fut = fut

    def wait(self, timeout=None):
        # c10d contract: raise on expiry. timeout None/<=0 means block
        # forever; torch passes a datetime.timedelta.
        seconds = timeout.total_seconds() if timeout is not None else 0.0
        if seconds > 0 and not self._fut.done():
            done = threading.Event()
            self._fut.add_done_callback(lambda _f: done.set())
            if not done.wait(seconds):
                raise RuntimeError(f"cgx: work timed out after {seconds}s")
        self._fut.wait()  # re-raises the worker's exception
        return True

    def is_completed(self):
        return self._fut.done()

    def is_success(self):
        if not self._fut.done():
            return False
        try:
            self._fut.value()
            return True
        except Exception:
            return False

    def get_future(self):
        return self._fut


class _CompletionPool:
    """Cached thread pool for Work-future completions.

    Semantics of Java's cachedThreadPool: an idle thread is reused when
    one exists, a new daemon thread is spawned when none is (a completion
    can block indefinitely inside a chained ``.then`` hook waiting on the
    NEXT collective, so a bounded pool that queues behind busy threads
    can deadlock), and idle threads exit after ``_IDLE_TIMEOUT`` seconds.
    Under steady DDP load each bucket's completion reuses the same one or
    two threads instead of spawning thousands per second.

    Invariant: ``_idle`` counts threads blocked in (or committed to)
    ``_jobs.get``.  ``submit`` reserves one under the lock *and enqueues
    under the same lock*, so a thread observing an empty queue under the
    lock after a get-timeout can safely exit.
    """

    _IDLE_TIMEOUT = 5.0

    def __init__(self):
        self._jobs: _queue.Queue = _queue.Queue()
        self._lock = threading.Lock()
        self._idle = 0

    def submit(self, fn, args) -> None:
        with self._lock:
            if self._idle > 0:
                self._idle -= 1  # reserve a parked thread...
                self._jobs.put((fn, args))  # ...and wake it, atomically
                return
        threading.Thread(
            target=self._worker, args=(fn, args),
            name="cgx-complete", daemon=True,
        ).start()

    def _worker(self, fn, args) -> None:
        while True:
            try:
                fn(*args)
            except Exception as e:  # _finish logs its own; belt+braces
                log.error("completion raised: %s", e)
            with self._lock:
                self._idle += 1
            while True:
                try:
                    fn, args = self._jobs.get(timeout=self._IDLE_TIMEOUT)
                    break
                except _queue.Empty:
                    with self._lock:
                        if self._jobs.empty():
                            self._idle -= 1
                            return
                    # a reservation landed between the timeout and the
                    # lock: loop and collect it (some parked thread must).


# Per-process group ordinal: c10d requires every rank to construct
# process groups in the same order, so this counter is cross-rank
# consistent — the timeline uses it to namespace collective seqs (a
# dist.new_group subgroup's ("allreduce", 5) must not correlate with
# the default group's in the merged trace).
_group_counter = 0
_group_counter_lock = threading.Lock()


class ProcessGroupCGX(dist.ProcessGroup):
    """Store-transport c10d process group with quantized allreduce.

    Single-tensor ops only, like the reference (ProcessGroupCGX.cc:91-97).
    """

    def __init__(
        self,
        store,
        rank: int,
        size: int,
        timeout=None,
        *,
        generation: int = 0,
        global_ranks: Optional[Sequence[int]] = None,
        peer_info: Optional[Sequence[str]] = None,
    ):
        super().__init__(rank, size)
        self._store = store
        self._rank = rank
        self._size = size
        # Recovery generation (epoch): every store key this group touches
        # is namespaced by it (``_ns``), so traffic from a pre-recovery
        # generation can never alias into the reconfigured group's
        # matching collective. 0 (the default, and the only value with
        # recovery off) leaves every key byte-identical to the legacy
        # format. ``_global_ranks[i]`` is group-local rank i's identity in
        # the ORIGINAL world — stable across reconfigurations, which is
        # what eviction votes and per-rank RNG streams key off.
        self._generation = int(generation)
        self._global_ranks: List[int] = (
            list(global_ranks) if global_ranks is not None
            else list(range(size))
        )
        if len(self._global_ranks) != size:
            raise ValueError(
                f"global_ranks has {len(self._global_ranks)} entries for "
                f"group size {size}"
            )
        global _group_counter
        with _group_counter_lock:
            self._gid = _group_counter
            _group_counter += 1
        # Collective wait deadline: the c10d group timeout when given, else
        # the classic store-get bound. A peer that dies WITHOUT reaching
        # abort() must surface as a timeout error, not an infinite park.
        try:
            self._timeout_s = float(timeout.total_seconds())
        except AttributeError:
            self._timeout_s = 300.0
        if self._timeout_s <= 0:
            self._timeout_s = 300.0
        # CGX_BRIDGE_TIMEOUT_MS wins over the group timeout when set: one
        # knob bounds every bridge wait (docs/ROBUSTNESS.md).
        bt = cfg.bridge_timeout_ms()
        if bt:
            self._timeout_s = bt / 1000.0
        self._injector = faults_mod.get_injector(rank)
        # Observability: bind the process flight recorder to this rank and
        # start the periodic metrics exporter (both no-ops on the clean
        # path — the exporter only runs with CGX_METRICS_DIR set).
        flightrec.bind_rank(rank)
        timeline.bind_rank(rank)
        obs_exporter.start_exporter(rank)
        # Live health plane (PR 6): the streaming evaluator (CGX_HEALTH),
        # the memory ledger (CGX_MEMLEDGER) and the Prometheus endpoint
        # (CGX_PROM_PORT) — all no-ops with their knobs unset, like the
        # exporter above.
        health_mod.maybe_start(rank)
        memledger_mod.maybe_start(rank)
        watch_mod.maybe_start_prom(rank)
        metrics.set("cgx.recovery.generation", float(generation))
        metrics.set("cgx.recovery.ws", float(size))
        self._pid_by_rank: List[int] = []
        self._seq = 0  # collective sequence number (issued on calling thread)
        self._p2p_send = {}  # (dst, tag) -> count
        self._p2p_recv = {}  # (src, tag) -> count
        self._p2p_ann = {}  # tag -> announce tickets read (any-source)
        self._p2p_ann_used = {}  # (src, tag) -> tickets reconciled
        self._p2p_claim = threading.Lock()  # guards the counter maps
        # p2p ops run here, independent of the collective worker FIFO, so a
        # blocked recv never stalls allreduces (AsyncWork analogue).
        self._p2p_pool = ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="cgx-p2p"
        )
        self._rng: Optional[np.random.Generator] = None
        self._store_can_delete: Optional[bool] = None  # probed on first use
        # runLoop analogue (ProcessGroupCGX.cc:300-339): one worker thread
        # drains a FIFO of work entries and completes their futures.
        self._jobs: _queue.Queue = _queue.Queue()
        self._completions = _CompletionPool()
        self._shutdown = threading.Event()
        # Abort machinery (ProcessGroupCGX.cc:295-298): a poison key in the
        # store lets a failing rank unblock peers parked in collectives.
        # Generation-namespaced: a pre-recovery abort must not poison the
        # reconfigured group.
        self._abort_key = self._ns("cgxctl/abort")
        # An Event, not a bare bool: set from the worker/observer threads'
        # failure paths and read from user threads parked in _wait_key —
        # the one cross-thread flag here that must publish without a lock
        # (ISSUE 14's thread-shared-write pass).
        self._aborted = threading.Event()
        self._store_can_check: Optional[bool] = None
        # Same-host SHM data plane + host topology map (the reference's
        # shm_communicator/mpi_context roles — see shm.py). Rendezvous over
        # the store; any failure degrades to store-only transport.
        self._shm = None
        self._host_by_rank: List[str] = []
        self._local_ranks: List[int] = [rank]
        self._all_local = False
        # Async cross-slice plane (PR 13): the outer-exchange sender
        # thread, created lazily by async_sender() and rebuilt per
        # generation.
        self._async_sender = None
        # Socket data plane (PR 20, CGX_TRANSPORT) + cross-host liveness
        # judge — both engage only when their gates say so; None keeps
        # every legacy path byte-identical.
        self._transport = None
        self._remote_live = None
        if size > 1:
            try:
                self._init_shm(peer_info)
            except Exception as e:
                log.warning(
                    "cgx shm rendezvous failed (%s); store transport only", e
                )
                self._shm = None
            try:
                self._init_transport()
            except Exception as e:
                log.warning(
                    "cgx socket transport init failed (%s); store path", e
                )
                self._transport = None
        self._worker = threading.Thread(
            target=self._run_loop, name="cgx-worker", daemon=True
        )
        self._worker.start()

    def _init_shm(self, peer_info: Optional[Sequence[str]] = None) -> None:
        """Host rendezvous (always, when ws > 1 — the hierarchy gate needs
        the host map) + SHM channel creation (gated by CGX_SHM and >1
        same-host rank).

        ``peer_info`` (one ``"<host_fp>|<pid>"`` per group-local rank)
        replaces the blocking store exchange AND the two-phase ok
        negotiation: an elastic joiner boots with the hosts map its
        admit record carried (robustness/elastic.py), because a blocking
        ``get`` against peers mid-step would park for the store timeout —
        past the join bound — and the ok handshake's consensus is owned
        by the join protocol's shmok flags instead.
        """
        from . import shm as shm_mod

        fp = shm_mod.host_fingerprint()
        if peer_info is not None:
            if len(peer_info) != self._size:
                raise ValueError(
                    f"peer_info has {len(peer_info)} entries for group "
                    f"size {self._size}"
                )
            raw = [str(v) for v in peer_info]
        else:
            # Piggyback this rank's pid on the host-key exchange: peers
            # need it to resolve the per-process liveness heartbeat file
            # — no extra store round-trips (an init-time rendezvous here
            # proved destabilizing under rapid group churn).
            # Generation-namespaced: a post-recovery group's exchange
            # (shrunk world, re-indexed ranks) must never read the dead
            # world's stale values.
            self._store.set(
                self._ns(f"cgxshm/h{self._rank}"),
                f"{fp}|{os.getpid()}".encode(),
            )
            raw = [
                bytes(self._store.get(self._ns(f"cgxshm/h{j}"))).decode()
                for j in range(self._size)
            ]
        hosts, pids = [], []
        for v in raw:
            h, _, p = v.rpartition("|")
            hosts.append(h)
            pids.append(int(p) if p.isdigit() else -1)
        self._host_by_rank = hosts
        self._pid_by_rank = pids
        self._local_ranks = [j for j, h in enumerate(hosts) if h == fp]
        if len(set(hosts)) > 1:
            # Cross-host liveness (PR 20): the heartbeat file's mtime is
            # invisible to remote peers, so the same daemon tick also
            # bumps a per-pid store counter; RemoteLiveness convicts on
            # counter ADVANCE against local monotonic time only — never
            # by comparing wall clocks across hosts. Best-effort, like
            # the file heartbeat below.
            try:
                hb_mod.attach_store(shm_mod.default_dir(), self._store)
                self._remote_live = hb_mod.RemoteLiveness(self._store)
            except Exception as e:
                log.warning(
                    "cgx store heartbeat setup failed (%s); timeouts "
                    "will not name dead cross-host peers", e,
                )
        if len(self._local_ranks) > 1:
            # Per-process liveness file (robustness/heartbeat.py): lets a
            # bounded wait NAME a SIGKILL'd same-host peer instead of only
            # suspecting one. Process-wide singleton — survives group
            # churn, dies with the process.
            try:
                hb_mod.ensure_heartbeat(shm_mod.default_dir())
            except Exception as e:
                log.warning("cgx heartbeat setup failed (%s); timeout "
                            "errors will not name dead peers", e)
            # Channel creation must be GROUP-COORDINATED within the local
            # group: routing is computed independently on each rank, so one
            # rank degrading to the store while a local peer keeps SHM
            # deadlocks the first collective (writer posts to one channel,
            # reader waits on the other). Two-phase: everyone publishes its
            # own create outcome — INCLUDING a rank whose CGX_SHM=0 gate
            # says no (peers still block on its flag) — then everyone reads
            # every local peer's; shm engages only if the whole local group
            # succeeded.
            mine = b"0"
            if cfg.shm_enabled():
                try:
                    self._shm = shm_mod.ShmChannel(
                        self._store, self._rank, wait_key=self._wait_key
                    )
                    if self._generation:
                        self._shm.bump_epoch(self._generation)
                    mine = b"1"
                except Exception as e:
                    log.warning(
                        "cgx shm channel creation failed (%s); "
                        "negotiating store fallback", e
                    )
                    self._shm = None
            if peer_info is not None:
                # Elastic boot: no blocking ok handshake against peers
                # that are mid-step — the join protocol's shmok flags
                # carry the consensus (any local-group member without a
                # channel degrades EVERYONE to the store at the ready
                # barrier).
                self._all_local = (
                    self._shm is not None
                    and len(self._local_ranks) == self._size
                )
                return
            self._store.set(self._ns(f"cgxshm/ok{self._rank}"), mine)
            peers_ok = all(
                bytes(self._store.get(self._ns(f"cgxshm/ok{j}"))) == b"1"
                for j in self._local_ranks
            )
            if not peers_ok and self._shm is not None:
                log.warning(
                    "cgx shm disabled: a same-host peer could not create "
                    "its channel; whole local group uses the store"
                )
                self._shm.close()
                self._shm = None
            self._all_local = (
                self._shm is not None
                and len(self._local_ranks) == self._size
            )

    def _init_transport(self) -> None:
        """Socket data plane (PR 20): engage the supervised TCP transport
        when ``CGX_TRANSPORT`` asks for it. ``socket`` forces it on;
        ``auto`` engages only for groups that actually span hosts (a
        same-host group already has the shm arena and a local store —
        TCP buys nothing). Unset/""/``store``/``shm`` leave
        ``self._transport`` None and every legacy path byte-identical.
        Address keys are generation-namespaced, so a reconfigured group
        re-exchanges endpoints under ``g<N>/`` automatically."""
        mode = cfg.transport_mode()
        if mode not in ("socket", "auto") or self._size < 2:
            return
        if mode == "auto" and len(set(self._host_by_rank)) < 2:
            return
        from . import transport as transport_mod

        self._transport = transport_mod.SocketTransport(
            self._store,
            my_id=str(self._rank),
            addr_key=lambda pid: self._ns(f"cgxtp/a{pid}"),
            rank=self._rank,
            on_link_down=self._on_link_down,
        )

    def _on_link_down(self, peer_id: str, peer_rank) -> None:
        """Transport supervisor callback (runs on a transport thread): an
        edge exhausted its reconnect ladder and degraded to the store.
        Surface it as a PR 6 HealthEvent attributed by GLOBAL rank, like
        every other health verdict."""
        r = peer_rank
        if r is None:
            try:
                r = int(peer_id)
            except ValueError:
                r = None
        gpeer = (
            self._global_ranks[r]
            if r is not None and 0 <= r < len(self._global_ranks)
            else None
        )
        health_mod.note_link_down(
            gpeer,
            failures=cfg.transport_retries(),
            threshold=cfg.transport_retries(),
            peer_id=peer_id,
            generation=self._generation,
        )

    # -- worker loop ------------------------------------------------------

    @staticmethod
    def _finish(fut, result, exc) -> None:
        try:
            if exc is None:
                fut.set_result(result)
            else:  # failed future, like finishWorkMPIError
                fut.set_exception(exc)
        except Exception as e:
            log.error("work completion failed after future done: %s", e)

    def _run_loop(self) -> None:
        # Futures complete OFF the collective worker, never serialized
        # behind other completions: torch comm hooks chain `.then()`
        # callbacks that execute inside set_result, and a callback may
        # enqueue AND WAIT on the next collective (torch's built-in
        # powerSGD_hook does, between its P and Q allreduces). Completing
        # on the worker deadlocks the worker against itself; completing on
        # one shared thread deadlocks that thread against the NEXT
        # completion it is itself waiting for. A cached pool reuses idle
        # completion threads under steady-state DDP load (no
        # thread-per-collective churn) while still growing when every
        # thread is blocked inside a nested hook, so no fixed bound can
        # deadlock. Consequence, unlike the reference's serialized runLoop
        # (ProcessGroupCGX.cc:300-339): completions may run OUT of issue
        # order — correct for torch futures (each Work's wait/then is
        # self-contained) but observable to code timing callbacks.
        while not self._shutdown.is_set():
            try:
                item = self._jobs.get(timeout=0.1)
            except _queue.Empty:
                continue
            fn, fut, result, op, seq, gen = item
            if gen != self._generation:
                # Work enqueued under a pre-recovery generation: its keys,
                # chunking and peer set describe a group that no longer
                # exists. Fail the future instead of running it — the
                # supervisor's rollback-replay re-issues the step against
                # the new generation.
                metrics.add("cgx.recovery.stale_jobs")
                self._completions.submit(
                    self._finish,
                    (fut, None, StaleGenerationError(
                        f"cgx: {op or 'work'} (seq {seq}) was enqueued at "
                        f"generation {gen} but the group is now at "
                        f"generation {self._generation}",
                        found=gen,
                        current=self._generation,
                    )),
                )
                continue
            t0 = time.perf_counter()
            try:
                if self._injector is not None:
                    # kill_rank fault: die mid-collective the way SIGKILL
                    # does (no abort poison, no atexit) — each dequeued
                    # work entry is one step of the injector's counter.
                    self._injector.maybe_kill()
                    # preempt fault: same SIGKILL-grade death, but the
                    # platform gave notice — the comeback record lets the
                    # supervisor ladder prefer the rejoin rung over a
                    # permanent evict (robustness/elastic.py).
                    self._injector.maybe_preempt(notify=self._preempt_notify)
                    # slow_rank fault: a straggler, not a corpse — the
                    # heartbeat keeps beating while peers' bounded waits
                    # expire, which is exactly what the recovery retry
                    # rung (not eviction) must absorb.
                    self._injector.delay("slow_rank")
                if self._aborted.is_set():
                    self._raise_abort()
                fn()
            except Exception as e:
                args = (fut, None, e)
            else:
                args = (fut, result, None)
            if op:
                dt = time.perf_counter() - t0
                metrics.observe(f"cgx.collective.{op}_s", dt)
                flightrec.record(
                    "collective", op=op, seq=seq,
                    seconds=round(dt, 6), ok=args[2] is None,
                )
                # Cross-rank correlation anchor: every rank issues the
                # same seq for the same collective (SPMD program order),
                # so (op, seq) links this span to its peers in the
                # merged timeline (tools/cgx_trace.py flow arrows).
                timeline.record(
                    op, timeline.CAT_COLLECTIVE, t0, dt,
                    seq=seq, group=self._gid, ok=args[2] is None,
                )
            if isinstance(args[2], (BridgeTimeoutError, WireCorruptionError)):
                # Name the failing collective in the black box — the deeper
                # raise site recorded the key/suspects but not which op was
                # running. Ordered after the collective event so the
                # re-dump (an idempotent rewrite of the ring) includes it.
                flightrec.record_failure(args[2], op=op, seq=seq)
            try:
                self._completions.submit(self._finish, args)
            except Exception as e:  # thread exhaustion: complete inline
                # rather than killing the worker loop (a `.then` hook
                # waiting on a nested collective may then deadlock, but
                # plain Work.wait callers — the common case — survive).
                log.warning("completion thread spawn failed (%s); "
                            "completing inline", e)
                self._finish(*args)

    def _submit(self, fn, result, op: str = "", seq: int = 0) -> dist.Work:
        fut = Future()
        self._jobs.put((fn, fut, result, op, seq, self._generation))
        return _CGXWork(fut)

    def _done(self, result) -> dist.Work:
        fut = Future()
        fut.set_result(result)
        return _CGXWork(fut)

    # -- store transport --------------------------------------------------

    def _ns(self, key: str) -> str:
        """Generation-namespace a store key. Generation 0 (recovery never
        engaged) returns the key unchanged — the legacy wire contract,
        byte for byte. Any later generation prefixes ``g<N>/`` so traffic
        from a pre-recovery group can never alias into this one."""
        return key if self._generation == 0 else f"g{self._generation}/{key}"

    def _next_seq(self) -> int:
        self._seq += 1
        return self._seq

    # -- abort (ProcessGroupCGX.cc:295-298) --------------------------------

    def _check_store(self, keys) -> Optional[bool]:
        """store.check with one-time capability probe (None = unsupported)."""
        if self._store_can_check is False:
            return None
        try:
            r = bool(self._store.check(keys))
            self._store_can_check = True
            return r
        except (NotImplementedError, AttributeError):
            self._store_can_check = False
            return None

    def _raise_abort(self) -> None:
        self._aborted.set()
        try:
            msg = bytes(self._store.get(self._abort_key)).decode()
        except Exception:
            msg = "unknown"
        raise RuntimeError(f"cgx: process group aborted ({msg})")

    def _preempt_notify(self, delay_s: float) -> None:
        """Preempt-fault notice hook: publish this rank's comeback record
        so the survivors' recovery ladder can take the rejoin rung instead
        of a permanent evict (robustness/elastic.py). Best-effort — the
        process is about to die either way."""
        from ..robustness import elastic as elastic_mod

        elastic_mod.publish_comeback(
            self._store, self.global_rank, delay_s
        )

    def _wait_key(self, key: str, bounded: bool = True) -> None:
        """Block until ``key`` exists OR the group is aborted.

        The reference's runLoop drains the queue and calls MPI_Abort on
        failure (ProcessGroupCGX.cc:295-298) — peers blocked in a matching
        collective die with the MPI job. A store get has no such poison, so
        every blocking wait polls the abort key alongside its payload key:
        a rank that failed mid-collective unblocks its peers in ~200 ms
        instead of leaving them parked until the store timeout."""
        if self._aborted.is_set():
            self._raise_abort()
        # Park in the store's own blocking wait in 200 ms slices: TCPStore
        # waiters get push-notified (sub-ms arrival latency, ~5 RPCs/s per
        # stalled rank — no check() storm against the single-threaded
        # server during a straggler stall); FileStore's wait polls its file
        # internally at a fixed short interval. The abort key is polled
        # between slices, and the whole wait is bounded by the group
        # timeout — a peer that died WITHOUT reaching abort() (SIGKILL,
        # OOM) surfaces as a timeout error, like the plain store get did.
        import datetime as _dt
        import time as _time

        slice_ = _dt.timedelta(milliseconds=200)
        deadline = _time.monotonic() + self._timeout_s
        # Recovery retry rung (CGX_RECOVERY_RETRIES, off by default): an
        # expired deadline with NO heartbeat-named suspect is re-armed
        # with exponential backoff + jitter before raising — transient
        # stalls (flap faults, slow peers, store hiccups) heal locally.
        # Constructed lazily: the env-derived policy is only read on an
        # expired deadline, never on the per-collective fast path.
        retry: Optional[retry_mod.WaitRetry] = None
        fast_fails = 0
        while True:
            t0 = _time.monotonic()
            try:
                self._store.wait([key], slice_)
                return
            except Exception as e:
                # A wait that fails in well under its slice did not time
                # out — it's a store error. Tolerate transients, but a
                # BROKEN store (deleted backing file, dead server) must
                # surface instead of hot-spinning, especially for
                # bounded=False waiters that have no deadline.
                if _time.monotonic() - t0 < 0.1:
                    fast_fails += 1
                    if fast_fails >= 5:
                        raise RuntimeError(
                            f"cgx: store wait failing fast for {key!r} "
                            f"({e}) — broken store?"
                        ) from e
                    _time.sleep(0.05)
                else:
                    fast_fails = 0  # a full slice elapsed: normal timeout
            if self._aborted.is_set() or self._check_store([self._abort_key]):
                self._raise_abort()
            if self._shutdown.is_set():
                raise RuntimeError("cgx: process group is shut down")
            # bounded=False: an any-source receiver may legitimately idle
            # forever (MPI ANY_SOURCE semantics) — only abort/shutdown
            # break it out.
            if bounded and _time.monotonic() > deadline:
                suspects = self._suspect_dead_peers()
                if retry is None:
                    retry = retry_mod.WaitRetry("wait_key")
                if retry.attempt(key, suspects):
                    deadline = _time.monotonic() + self._timeout_s
                    continue
                extra = (
                    f"; suspected dead peer rank(s): {suspects}"
                    if suspects
                    else ""
                )
                metrics.add("cgx.bridge_timeout")
                err = BridgeTimeoutError(
                    f"cgx: timed out after {self._timeout_s:.0f}s waiting "
                    f"for {key!r} (peer dead or stalled?){extra}",
                    key=key,
                    suspects=suspects,
                )
                flightrec.record_failure(
                    err, key=key, suspects=list(suspects),
                    rank=self._rank, timeout_s=self._timeout_s,
                )
                raise err

    def _suspect_dead_peers(self) -> List[int]:
        """Best-effort attribution for a timeout, merged from every
        liveness signal this rank has: same-host peers by heartbeat-file
        mtime, cross-host peers by store-counter advance (PR 20 —
        previously un-nameable), and peers whose socket-transport edge
        already degraded."""
        suspects: set = set()
        try:
            from . import shm as shm_mod

            if self._pid_by_rank and len(self._local_ranks) >= 2:
                peers = [r for r in self._local_ranks if r != self._rank]
                dead = set(
                    hb_mod.suspect_dead_pids(
                        shm_mod.default_dir(),
                        [self._pid_by_rank[r] for r in peers],
                    )
                )
                local = [r for r in peers if self._pid_by_rank[r] in dead]
                if local:
                    metrics.add("cgx.heartbeat_stale", float(len(local)))
                suspects.update(local)
            if self._remote_live is not None and self._pid_by_rank:
                remote = [
                    r for r in range(self._size)
                    if r != self._rank
                    and r not in self._local_ranks
                    and 0 <= r < len(self._pid_by_rank)
                    and self._pid_by_rank[r] > 0
                ]
                dead_pids = set(
                    self._remote_live.suspects(
                        [self._pid_by_rank[r] for r in remote]
                    )
                )
                suspects.update(
                    r for r in remote if self._pid_by_rank[r] in dead_pids
                )
            if self._transport is not None:
                for p in self._transport.down_peers():
                    try:
                        suspects.add(int(p))
                    except ValueError:
                        pass
        except Exception as e:
            # Attribution is best-effort garnish on a timeout that is
            # raising anyway — but a broken judge is worth one line.
            log.warning("cgx: dead-peer suspect scan failed: %s", e)
        return sorted(suspects)

    def abort(self, reason: str = "") -> None:
        """Poison the group: peers blocked in any collective fail fast, and
        every queued-but-unstarted work entry on this rank is drained into
        a failed future (the reference's queue-drain + MPI_Abort)."""
        msg = f"rank {self._rank}: {reason or 'abort() called'}"
        try:
            self._store.set(self._abort_key, msg.encode())
        except Exception as e:
            log.warning("abort: poison key write failed: %s", e)
        self._aborted.set()
        err = RuntimeError(f"cgx: process group aborted ({msg})")
        while True:
            try:
                _fn, fut, _result, _op, _seq, _gen = self._jobs.get_nowait()
            except _queue.Empty:
                break
            self._completions.submit(self._finish, (fut, None, err))

    # -- transport routing -------------------------------------------------

    def _route_shm(self, local: Optional[bool]) -> bool:
        """Channel choice for one message: explicit ``local`` wins (the
        hierarchical path's intra stages); default = whole-group locality."""
        if self._shm is None:
            return False
        return self._all_local if local is None else local

    def _put(
        self, key: str, data, readers: int = 1,
        local: Optional[bool] = None,
        to: Optional[Sequence[int]] = None,
    ) -> None:
        """Post ``data`` for ``readers`` consumers. Same-host readers get
        the SHM byte plane (store carries only a header); with the socket
        plane up the bytes ride framed TCP toward ``to`` (the GROUP-LOCAL
        reader ranks — None means every other rank); otherwise the bytes
        ride the store itself."""
        if self._route_shm(local):
            self._shm.put(key, data, readers=readers)
            return
        if self._transport is not None:
            payload = bytes(data) if not isinstance(data, bytes) else data
            dests = (
                [j for j in range(self._size) if j != self._rank]
                if to is None
                else [j for j in to if j != self._rank]
            )
            t0 = time.perf_counter()
            self._transport.post(key, payload, to=[str(j) for j in dests])
            timeline.record(
                "transport.post", timeline.CAT_WIRE, t0,
                time.perf_counter() - t0, key=key, bytes=len(payload),
            )
            return
        if self._injector is not None and self._injector.fire("drop_put"):
            return  # store-path drop: the matching take's wait expires
        payload = bytes(data) if not isinstance(data, bytes) else data
        if self._injector is not None:
            flap_s = self._injector.flap_delay()
            if flap_s is not None:
                # Transient drop-then-recover: the payload lands LATE — the
                # peer's first bounded wait may expire, a recovery retry
                # succeeds (robustness/faults.py ``flap``).
                threading.Timer(
                    flap_s, self._store.set, (key, payload)
                ).start()
                return
        t0 = time.perf_counter()
        self._store.set(key, payload)
        timeline.record(
            "store.put", timeline.CAT_WIRE, t0, time.perf_counter() - t0,
            key=key, bytes=len(payload),
        )

    def _delete_key(self, key: str) -> None:
        """Delete with one-time capability probe: stores without delete
        support are detected once (keys then persist, by design); any other
        failure is logged instead of silently swallowed."""
        if self._store_can_delete is False:
            return
        try:
            self._store.delete_key(key)
            self._store_can_delete = True
        except (NotImplementedError, AttributeError):
            self._store_can_delete = False
            log.debug("store %r has no delete support; keys will persist",
                      type(self._store).__name__)
        except Exception as e:
            if self._store_can_delete is None:
                self._store_can_delete = False
                log.debug("store delete probe failed (%s); keys will persist", e)
            else:
                log.warning("store delete_key(%r) failed: %s", key, e)

    def _take(
        self, key: str, readers: int = 1, local: Optional[bool] = None,
        peer: Optional[int] = None,
    ) -> np.ndarray:
        """Blocking get + refcounted delete once all readers have read.
        Abort-aware (waits poll the poison key) on both channels.

        ``peer`` is the GROUP-LOCAL rank this take waits on, when the
        caller knows it (the SRA/Ring/alltoall exchanges always do): it
        feeds the health engine's per-peer straggler scoring — attributed
        by GLOBAL rank so scores survive reconfigurations. The hook is an
        attribute check when CGX_HEALTH is off."""
        if peer is not None and health_mod.active():
            gpeer = (
                self._global_ranks[peer]
                if 0 <= peer < len(self._global_ranks) else None
            )
            tok = health_mod.wait_begin(gpeer, key)
            try:
                return self._take_inner(key, readers, local)
            finally:
                health_mod.wait_end(tok)
        return self._take_inner(key, readers, local)

    def _take_inner(
        self, key: str, readers: int = 1, local: Optional[bool] = None
    ) -> np.ndarray:
        if self._route_shm(local):
            return self._shm.take(key)
        if self._transport is not None:
            return self._take_socket(key)
        t0 = time.perf_counter()
        try:
            self._wait_key(key)
        except BaseException:
            # A timed-out wait is the span the trace is for: record it
            # as a failed wait before propagating.
            timeline.record(
                "store.take.wait", timeline.CAT_WAIT, t0,
                time.perf_counter() - t0, key=key, ok=False,
            )
            raise
        t_hdr = time.perf_counter()
        timeline.record(
            "store.take.wait", timeline.CAT_WAIT, t0, t_hdr - t0, key=key
        )
        if self._injector is not None:
            self._injector.delay("delay_take")
        data = self._store.get(key)
        timeline.record(
            "store.take.copy", timeline.CAT_WIRE, t_hdr,
            time.perf_counter() - t_hdr, key=key, bytes=len(data),
        )
        if readers <= 1:
            self._delete_key(key)
        elif int(self._store.add(key + "/ack", 1)) >= readers:
            self._delete_key(key + "/ack")
            self._delete_key(key)
        return np.frombuffer(data, np.uint8)

    def _take_socket(self, key: str) -> np.ndarray:
        """Socket-plane take: a bounded dual-probe fetch (mailbox every
        slice, store fallback — a degraded WRITER still delivers) with
        the same abort/shutdown/retry/timeout semantics as ``_wait_key``.
        No reader refcount: each target got its own framed copy, popped
        on delivery. A degraded multi-reader post lands as one store key
        that is never refcount-deleted — a bounded leak, at most the
        collectives in flight during a degrade incident."""
        from . import transport as transport_mod

        last_poll = [0.0]

        def _abort_probe() -> None:
            if self._aborted.is_set():
                self._raise_abort()
            if self._shutdown.is_set():
                raise RuntimeError("cgx: process group is shut down")
            now = time.monotonic()
            # The store-side poison poll keeps the _wait_key cadence
            # (one check per ~200 ms), not the fetch slice's.
            if now - last_poll[0] >= 0.2:
                last_poll[0] = now
                if self._check_store([self._abort_key]):
                    self._raise_abort()

        t0 = time.perf_counter()
        retry: Optional[retry_mod.WaitRetry] = None
        while True:
            try:
                data = self._transport.fetch(
                    key, timeout_s=self._timeout_s,
                    abort_check=_abort_probe,
                )
                break
            except transport_mod.TransportTimeout:
                suspects = self._suspect_dead_peers()
                if retry is None:
                    retry = retry_mod.WaitRetry("transport_fetch")
                if retry.attempt(key, suspects):
                    continue
                extra = (
                    f"; suspected dead peer rank(s): {suspects}"
                    if suspects
                    else ""
                )
                metrics.add("cgx.bridge_timeout")
                err = BridgeTimeoutError(
                    f"cgx: timed out after {self._timeout_s:.0f}s waiting "
                    f"for {key!r} on the socket transport (peer dead or "
                    f"stalled?){extra}",
                    key=key,
                    suspects=suspects,
                )
                flightrec.record_failure(
                    err, key=key, suspects=list(suspects),
                    rank=self._rank, timeout_s=self._timeout_s,
                )
                timeline.record(
                    "transport.take", timeline.CAT_WAIT, t0,
                    time.perf_counter() - t0, key=key, ok=False,
                )
                raise err
        timeline.record(
            "transport.take", timeline.CAT_WAIT, t0,
            time.perf_counter() - t0, key=key, bytes=len(data),
        )
        return np.frombuffer(data, np.uint8)

    # -- config -----------------------------------------------------------

    def _stochastic_rng(self) -> Optional[np.random.Generator]:
        if not cfg.stochastic_rounding():
            return None
        if self._rng is None:
            self._rng = np.random.default_rng(
                (cfg.global_seed() << 16) ^ (self._rank + 1)
            )
        return self._rng

    def _extract_layers(
        self, numel: int, bucket_key=None
    ) -> List[Tuple[int, int, cfg.CompressionConfig]]:
        """(offset, numel, resolved config) per layer of this bucket.

        The reference slices the DDP bucket by the per-layer sizes registered
        under an explicit bucket index and errors on mismatch
        (mpi_allreduce_operations.cc:257-285). Here the DDP hook tags each
        allreduce with its bucket key (config.set_current_bucket), so
        resolution is by identity. Untagged calls (plain user allreduces)
        fall back to matching by total element count — unique match uses that
        bucket's configs, no match is one env-default layer, and an ambiguous
        match (several registered buckets share the total) raises, like the
        reference's extractLayers error.
        """
        if bucket_key is not None:
            sizes = cfg.registered_layer_sizes(bucket_key)
            if sizes is not None:
                if sum(sizes) != numel:
                    raise RuntimeError(
                        f"bucket {bucket_key!r}: registered layer sizes sum to "
                        f"{sum(sizes)} but the buffer has {numel} elements "
                        "(stale registry? call clear_registry() after "
                        "changing the model)"
                    )
                return self._resolve_layers(bucket_key, sizes)
            return [(0, numel, cfg.default_compression_config())]
        matches = [
            (idx, sizes)
            for idx in cfg.registered_buckets()
            if (sizes := cfg.registered_layer_sizes(idx)) and sum(sizes) == numel
        ]
        if not matches:
            return [(0, numel, cfg.default_compression_config())]
        if len(matches) > 1:
            raise RuntimeError(
                f"untagged allreduce of {numel} elements matches "
                f"{len(matches)} registered buckets "
                f"({[m[0] for m in matches]!r}) — cannot resolve per-layer "
                "configs; use the cgx_hook (which tags buckets) or "
                "clear_registry()"
            )
        return self._resolve_layers(*matches[0])

    @staticmethod
    def _resolve_layers(bucket_key, sizes):
        out, off = [], 0
        for li, n in enumerate(sizes):
            out.append((off, n, cfg.get_layer_config((bucket_key, li))))
            off += n
        return out

    # -- allreduce --------------------------------------------------------

    def allreduce(self, tensors, opts=None):
        self._check_single(tensors)
        t = tensors[0]
        op = opts.reduceOp if opts is not None else dist.ReduceOp.SUM
        seq = self._next_seq()
        # Consume the hook's bucket tag on the calling thread (the hook sets
        # it immediately before dist.all_reduce).
        bucket_key = cfg.take_current_bucket()
        do_compress = (
            t.dtype in _TORCH_FLOATS
            and op == dist.ReduceOp.SUM
            and self._size > 1
        )

        def run():
            if self._size == 1:
                return
            if do_compress:
                self._allreduce_quantized(t, seq, bucket_key)
            else:
                self._allreduce_plain(t, op, seq)

        return self._submit(run, tensors, op="allreduce", seq=seq)

    def _allreduce_quantized(self, t: torch.Tensor, seq: int, bucket_key=None) -> None:
        # Per-layer partition into compress / no-compress, exactly the
        # orchestrator's split (mpi_allreduce_operations.cc:240-247):
        # enabled config AND numel above the minimal size.
        layers = self._extract_layers(t.numel(), bucket_key)
        minimal = cfg.minimal_size()
        arr = _to_np(t).astype(np.float32, copy=False)
        comp = [(o, n, c) for (o, n, c) in layers if c.enabled and n >= minimal]
        rest = [(o, n, c) for (o, n, c) in layers if not (c.enabled and n >= minimal)]

        if rest:
            # Layers are contiguous runs: gather/scatter by slices, not
            # index arrays (VERDICT r2 Weak #7 — O(n) arange per bucket).
            part = np.concatenate([arr[o : o + n] for (o, n, _) in rest])
            self._sum_alltoall(part, np.float32, self._ns(f"cgx{seq}u"))
            off = 0
            for (o, n, _) in rest:
                arr[o : o + n] = part[off : off + n]
                off += n
        if comp:
            spans = [(o, n) for (o, n, _) in comp]
            total = sum(n for _, n in spans)
            # Debug traffic shaping (mpi_allreduce_operations.cc:130-144):
            # with CGX_COMPRESSION_FAKE_RATIO set, only the leading fraction
            # of the compressed slice is reduced; the tail stays stale.
            ratio = cfg.fake_ratio()
            if ratio is not None and total > 1:
                budget = max(1, int(np.ceil(ratio * total)))
                cut, acc = [], 0
                for o, n in spans:
                    take = min(n, budget - acc)
                    if take <= 0:
                        break
                    cut.append((o, take))
                    acc += take
                spans = cut
            fused = np.concatenate([arr[o : o + n] for (o, n) in spans])
            # Re-base layer offsets into fused coordinates (clipped to the
            # possibly-shrunk fused length; _segments_in intersects).
            fl, off = [], 0
            for (_, n, c) in comp:
                if off >= fused.shape[0]:
                    break
                fl.append((off, min(n, fused.shape[0] - off), c))
                off += n
            wdt = _wire_dtype(t.dtype)
            topo = cfg.topology_from_env()
            flightrec.record(
                "allreduce_layers", seq=seq,
                elems=int(t.numel()),
                compressed_elems=sum(n for (_, n, _) in comp),
                raw_elems=sum(n for (_, n, _) in rest),
                bits=sorted({c.bits for (_, _, c) in comp}),
                buckets=sorted({c.bucket_size for (_, _, c) in comp}),
                algo=(
                    "hier" if self._use_hierarchy(topo)
                    else topo.intra_reduction
                ),
                # Which fabric this group's traffic crosses — the router
                # taxonomy (intra-slice bridge traffic is the class the
                # staged in-XLA program exists to absorb).
                topo=_host_topology(self._host_by_rank) if (
                    self._host_by_rank
                ) else "unknown",
            )
            if self._use_hierarchy(topo):
                self._qreduce_hier(fused, fl, self._ns(f"cgx{seq}q"), wdt, topo)
            else:
                # Flat (single-level) bridge: the "inner" reduction choice
                # applies, like a one-node reference run
                # (mpi_allreduce_operations.cc:70-94).
                self._qreduce_flat(
                    fused, fl, self._ns(f"cgx{seq}q"), wdt,
                    topo.intra_reduction,
                )
            off = 0
            for (o, n) in spans:
                arr[o : o + n] = fused[off : off + n]
                off += n
        _from_np(t, arr)

    def _group_ctx(self, ranks, force_raw):
        """(member ranks, my index, ws, dummy-codec flag) for a collective
        running over a subgroup (None = the whole group). ``force_raw``
        sends pass-through frames regardless of layer configs — the
        hierarchical path's CGX_INTRA_COMPRESS/cross_compress=off stages."""
        group = list(ranks) if ranks is not None else list(range(self._size))
        return (
            group,
            group.index(self._rank),
            len(group),
            cfg.dummy_compression() or force_raw,
        )

    def _sched_tables(
        self, sizes: List[int], layers
    ) -> Optional[List[List[Tuple[int, int]]]]:
        """Per-rank sub-chunk plans for a pipelined SRA (CGX_SCHEDULE=on),
        or None when the payload can't sustain a >= 2-deep pipeline.
        Group-global by construction — every rank derives every rank's
        table from (sizes, layer configs, env knobs), so writers and
        readers always agree on the framing of each sub-chunk. Tables
        are padded to a common depth with empty entries (empty frames
        travel, like empty monolithic chunks — no rank ever skips a
        matching put/take)."""
        import math as _math

        buckets = [c.bucket_size for (_o, _n, c) in layers] or [1]
        align = 1
        for b in buckets:
            align = _math.lcm(align, max(1, b))
        chunks = cfg.sched_chunks()
        # Step-planner depth decision (CGX_PLANNER=on): engagement is
        # ENV-ONLY (identical on every launcher-configured rank). A rank
        # with the planner loaded asks its cost model; one without uses
        # the dependency-light default-model mirror — pinned equal under
        # the default model, so mixed JAX/pure-bridge groups always
        # derive the same depth and the group-global framing invariant
        # holds. Calibrated models must be installed group-wide (the
        # _plan_bridge_chunks contract note).
        if cfg.planner_mode() == "on" and sizes:
            bits = next(
                (c.bits for (_o, _n, c) in layers if c.enabled), 32
            )
            pl = _planner_mod()
            if pl is not None:
                chunks = pl.bridge_chunks(
                    max(sizes), align, len(sizes), bits, chunks
                )
            else:
                chunks = _plan_bridge_chunks(
                    max(sizes), align, len(sizes), bits
                )
                metrics.add("cgx.plan.bridge_hints")
                metrics.set("cgx.plan.bridge_chunks", float(chunks))
        tables = [
            _sched_chunk_table(s, chunks, align) for s in sizes
        ]
        depth = max((len(t) for t in tables), default=1)
        if depth < 2:
            return None
        for t in tables:
            while len(t) < depth:
                end = t[-1][0] + t[-1][1] if t else 0
                t.append((end, 0))
        return tables

    def _qreduce_sra_pipelined(
        self, fused, layers, pfx, wdt, tables, *, ranks=None, local=None,
        force_raw=False,
    ) -> None:
        """Schedule-pipelined SRA (CGX_SCHEDULE=on — parallel/schedule.py's
        bridge plane): each rank's chunk is split into the sub-chunk plan
        ``tables[r]``, and the strict phase barriers of the monolithic
        path are replaced by a double-buffered in-flight window — an
        encoder thread runs chunk encode+put up to ``_SCHED_WINDOW``
        chunks ahead while this (worker) thread takes, folds, requantizes
        and decodes earlier chunks. Per-chunk store keys
        (``{pfx}/c<k>s…``/``…g…``) namespace the sub-collectives; wire
        framing per sub-chunk restarts quantization buckets at aligned
        boundaries, so the single-default-config case stays bit-equal to
        the monolithic path (bench.py --schedule asserts it).

        Overlap accounting: the encoder's per-chunk work is recorded as
        ``sched_encode`` CAT_SPAN timeline spans (compute concurrent with
        the in-flight collective — exactly what ``cgx_trace``'s
        ``overlap_frac`` measures) and summed into ``cgx.sched.overlap_s``
        against ``cgx.sched.wall_s`` for the live ratio ``cgx_top``
        renders."""
        _group, me, ws, dummy = self._group_ctx(ranks, force_raw)
        sizes, offs = _chunk_split(fused.shape[0], ws, layers)
        depth = len(tables[0])
        seed = cfg.global_seed()
        stoch = cfg.stochastic_rounding()

        def _rng(c: int, salt: str):
            # Per-(collective, chunk, stage) deterministic streams: the
            # monolithic path's sequential per-rank generator would make
            # draw order depend on pipeline timing. Stochastic bytes
            # therefore differ from the monolithic path — as they differ
            # between any two schedules (parallel/schedule.py contract).
            if not stoch:
                return None
            import zlib as _zlib

            mix = _zlib.crc32(f"{pfx}/c{c}/{salt}".encode())
            return np.random.default_rng(
                (seed << 16) ^ (self._rank + 1) ^ mix
            )

        def _segs(r: int, c: int):
            lo = offs[r] + tables[r][c][0]
            return _segments_in(layers, lo, lo + tables[r][c][1]), lo

        stop = threading.Event()
        enc_state = {"err": None, "busy_s": 0.0, "wire_out": 0}
        window = threading.Semaphore(_SCHED_WINDOW)

        def _encode_loop() -> None:
            try:
                for c in range(depth):
                    # Double-buffered window: run at most _SCHED_WINDOW
                    # chunks ahead of the worker thread's epilogues (the
                    # deadline bounds a worker stuck in a failed take —
                    # the stop event, checked after, breaks us out).
                    while not window.acquire(timeout=0.2):
                        if stop.is_set():
                            return
                    if stop.is_set():
                        return
                    t0 = time.perf_counter()
                    rng = _rng(c, "enc")
                    for j in range(ws):
                        if j == me:
                            continue
                        segs_j, _lo = _segs(j, c)
                        frame = _compress_frames(
                            fused, segs_j, dummy, rng, wdt
                        )
                        enc_state["wire_out"] += len(frame)
                        self._put(
                            f"{pfx}/c{c}s{me}>{j}", frame, local=local,
                            to=[_group[j]],
                        )
                    dur = time.perf_counter() - t0
                    enc_state["busy_s"] += dur
                    # CAT_SPAN: this is compute running CONCURRENTLY with
                    # the in-flight collective — the interval cgx_trace's
                    # overlap_frac intersects against the collective span.
                    timeline.record(
                        "sched_encode", timeline.CAT_SPAN, t0, dur,
                        key=f"{pfx}/c{c}",
                    )
            except Exception as e:  # surfaced by the worker thread below
                enc_state["err"] = e

        t0 = time.perf_counter()
        metrics.add("cgx.sched.bridge_collectives")
        metrics.add("cgx.sched.chunks_bridge", float(depth))
        enc = threading.Thread(
            target=_encode_loop, name="cgx-sched-enc", daemon=True
        )
        enc.start()
        wire_out = 0
        t1 = t0
        try:
            for c in range(depth):
                if enc_state["err"] is not None:
                    raise enc_state["err"]
                tc0 = time.perf_counter()
                frames = {}
                for j in range(ws):
                    if j != me:
                        frames[j] = self._take(
                            f"{pfx}/c{c}s{j}>{me}", local=local,
                            peer=_group[j],
                        )
                segs_me, lo = _segs(me, c)
                hi = lo + tables[me][c][1]
                _sra_fold_chunk(
                    fused, lo, hi, segs_me, frames, me, ws, dummy, wdt
                )
                wire = _requantize_frames(
                    fused, segs_me, dummy, _rng(c, "req"), wdt
                )
                wire_out += len(wire)
                t1 = time.perf_counter()
                self._put(
                    f"{pfx}/c{c}g{me}", wire, readers=ws - 1, local=local,
                    to=[_group[x] for x in range(ws) if x != me],
                )
                for j in range(ws):
                    if j != me:
                        buf = self._take(
                            f"{pfx}/c{c}g{j}", readers=ws - 1, local=local,
                            peer=_group[j],
                        )
                        segs_j, _lo_j = _segs(j, c)
                        _decompress_frames(
                            buf, segs_j, fused, dummy, add=False,
                            wire_dtype=wdt,
                        )
                window.release()
                timeline.record(
                    "sched.chunk", timeline.CAT_PHASE, tc0,
                    time.perf_counter() - tc0,
                    key=f"{pfx}/c{c}", ws=ws, chunk=c,
                )
        finally:
            stop.set()
            enc.join(timeout=self._timeout_s)
        if enc_state["err"] is not None:
            raise enc_state["err"]
        wire_out += enc_state["wire_out"]
        wall = time.perf_counter() - t0
        # Live overlap ratio: encoder-thread busy seconds over collective
        # wall seconds (the encoder runs strictly inside this collective's
        # window, so its busy time IS communication-hidden compute).
        metrics.add("cgx.sched.overlap_s", enc_state["busy_s"])
        metrics.add("cgx.sched.wall_s", wall)
        _record_qreduce_phases("sra", pfx, ws, fused, wire_out, t0, t1)

    def _qreduce_sra(
        self, fused, layers, pfx, wdt=np.float32, *, ranks=None, local=None,
        force_raw=False,
    ) -> None:
        """Quantized Scatter-Reduce-AllGather over the store — the flagship
        algorithm (scatter_reduce_allgather.cc:94-202). Empty chunks travel
        as empty payloads, so no rank ever skips a matching put/take.
        ``ranks``/``local`` scope it to a subgroup/channel (the hierarchical
        leaders' cross stage); keys use subgroup indices.

        With ``CGX_SCHEDULE=on`` and a payload that sustains a >= 2-deep
        chunk plan, the schedule-pipelined variant runs instead
        (:meth:`_qreduce_sra_pipelined` — double-buffered in-flight
        windows; per-chunk store keys). The knob unset keeps this
        monolithic body byte-identical, store keys included."""
        _group, me, ws, dummy = self._group_ctx(ranks, force_raw)
        # Pipelined engagement is ENV-ONLY (schedule knob or planner
        # mode), never process-local import state: every rank of a
        # launcher-configured group answers this gate identically.
        if ws > 1 and (
            cfg.schedule_mode() == "on" or cfg.planner_mode() == "on"
        ):
            sizes, _offs = _chunk_split(fused.shape[0], ws, layers)
            tables = self._sched_tables(sizes, layers)
            if tables is not None:
                self._qreduce_sra_pipelined(
                    fused, layers, pfx, wdt, tables,
                    ranks=ranks, local=local, force_raw=force_raw,
                )
                return
        rng = self._stochastic_rng()
        sizes, offs = _chunk_split(fused.shape[0], ws, layers)
        segs = [
            _segments_in(layers, offs[r], offs[r] + sizes[r]) for r in range(ws)
        ]
        t0 = time.perf_counter()
        wire_out = 0
        # Round 1: compress each peer's chunk and post it (ISend analogue).
        for j in range(ws):
            if j != me:
                frame = _compress_frames(fused, segs[j], dummy, rng, wdt)
                wire_out += len(frame)
                self._put(
                    f"{pfx}/s{me}>{j}", frame, local=local, to=[_group[j]]
                )
        # Accumulate peers into our own chunk (TestRecv + decompress) —
        # the fold association pinned to the dispatcher's ordered_rowsum
        # (see _sra_fold_chunk: the staged<->bridge wire contract).
        frames = {}
        for j in range(ws):
            if j != me:
                frames[j] = self._take(
                    f"{pfx}/s{j}>{me}", local=local, peer=_group[j]
                )
        _sra_fold_chunk(
            fused, offs[me], offs[me] + sizes[me], segs[me], frames, me, ws,
            dummy, wdt,
        )
        # Requantize the reduced chunk + self-dequantize in ONE fused pass
        # (error symmetry, scatter_reduce_allgather.cc:157-160 —
        # load-bearing for the bit-exactness oracle).
        t1 = time.perf_counter()
        wire = _requantize_frames(fused, segs[me], dummy, rng, wdt)
        wire_out += len(wire)
        self._put(
            f"{pfx}/g{me}", wire, readers=ws - 1, local=local,
            to=[_group[x] for x in range(ws) if x != me],
        )
        # Round 2: gather every reduced chunk (allgather).
        for j in range(ws):
            if j != me:
                buf = self._take(
                    f"{pfx}/g{j}", readers=ws - 1, local=local,
                    peer=_group[j],
                )
                _decompress_frames(buf, segs[j], fused, dummy, add=False, wire_dtype=wdt)
        _record_qreduce_phases("sra", pfx, ws, fused, wire_out, t0, t1)

    def _qreduce_ring(
        self, fused, layers, pfx, wdt=np.float32, *, ranks=None, local=None,
        force_raw=False,
    ) -> None:
        """Quantized ring: N-1 scatter-reduce steps then N-1 allgather steps
        (ring.cc:139-226). Scatter-reduce requantizes each outgoing segment;
        the allgather circulates reduced wire payloads unchanged (one
        quantization per reduced chunk, no per-hop drift)."""
        _group, me, ws, dummy = self._group_ctx(ranks, force_raw)
        rng = self._stochastic_rng()
        sizes, offs = _chunk_split(fused.shape[0], ws, layers)
        segs = [
            _segments_in(layers, offs[r], offs[r] + sizes[r]) for r in range(ws)
        ]
        right = (me + 1) % ws
        t0 = time.perf_counter()
        wire_out = 0
        for step in range(ws - 1):
            s_idx = (me - step) % ws  # chunk we send rightward
            r_idx = (me - step - 1) % ws  # chunk we receive + reduce
            frame = _compress_frames(fused, segs[s_idx], dummy, rng, wdt)
            wire_out += len(frame)
            self._put(
                f"{pfx}/r{step}>{right}", frame, local=local,
                to=[_group[right]],
            )
            buf = self._take(
                f"{pfx}/r{step}>{me}", local=local,
                peer=_group[(me - 1) % ws],
            )
            _decompress_frames(buf, segs[r_idx], fused, dummy, add=True, wire_dtype=wdt)
        # Our fully-reduced chunk is (me+1) % ws; requantize + self-dequantize
        # it once, in one fused pass (error symmetry, ring.cc:190-199),
        # then circulate.
        t1 = time.perf_counter()
        hold = _requantize_frames(fused, segs[(me + 1) % ws], dummy, rng, wdt)
        for step in range(ws - 1):
            r_idx = (me - step) % ws  # chunk arriving this step
            wire_out += len(hold)
            self._put(
                f"{pfx}/a{step}>{right}", hold, local=local,
                to=[_group[right]],
            )
            buf = self._take(
                f"{pfx}/a{step}>{me}", local=local,
                peer=_group[(me - 1) % ws],
            )
            _decompress_frames(buf, segs[r_idx], fused, dummy, add=False, wire_dtype=wdt)
            hold = buf.tobytes()  # forward verbatim next step
        _record_qreduce_phases("ring", pfx, ws, fused, wire_out, t0, t1)

    def _qreduce_alltoall(
        self, fused, layers, pfx, wdt=np.float32, *, ranks=None, local=None,
        force_raw=False,
    ) -> None:
        """Debug all-to-all: compress once, everyone sums everything
        (CGX_DEBUG_ALL_TO_ALL_REDUCTION, scatter_reduce_allgather.cc:269-306)."""
        _group, me, ws, dummy = self._group_ctx(ranks, force_raw)
        rng = self._stochastic_rng()
        segs = _segments_in(layers, 0, fused.shape[0])
        wire = _compress_frames(fused, segs, dummy, rng, wdt)
        self._put(
            f"{pfx}/x{me}", wire, readers=ws - 1, local=local,
            to=[_group[x] for x in range(ws) if x != me],
        )
        # Decode own wire too so every rank sums identical quantized terms.
        _decompress_frames(
            np.frombuffer(wire, np.uint8), segs, fused, dummy, add=False,
            wire_dtype=wdt,
        )
        for j in range(ws):
            if j == me:
                continue
            buf = self._take(
                f"{pfx}/x{j}", readers=ws - 1, local=local, peer=_group[j]
            )
            _decompress_frames(buf, segs, fused, dummy, add=True, wire_dtype=wdt)

    def _qreduce_flat(
        self, fused, layers, pfx, wdt, algo, *, ranks=None, local=None,
        force_raw=False,
    ) -> None:
        """Algorithm dispatch for one (sub)group-level quantized allreduce
        (mpi_allreduce_operations.cc:70-115)."""
        kw = dict(ranks=ranks, local=local, force_raw=force_raw)
        if algo == cfg.REDUCTION_ALLTOALL:
            self._qreduce_alltoall(fused, layers, pfx, wdt, **kw)
        elif algo == cfg.REDUCTION_RING:
            self._qreduce_ring(fused, layers, pfx, wdt, **kw)
        else:
            self._qreduce_sra(fused, layers, pfx, wdt, **kw)

    def _use_hierarchy(self, topo) -> bool:
        """Two-level reduction applies when the group spans hosts AND this
        host has >1 rank — the reference's communicator split
        (mpi_context.cc topology trio; mpi_allreduce_operations.cc:139-185
        builds inner/cross comms exactly when both levels are non-trivial).
        Requires the host map from the shm rendezvous; CGX_INTRA_BROADCAST=0
        falls back to the flat algorithm (the bridge analogue of the
        reference's non-leader mode is no hierarchy at all, since a full
        intra allreduce before a full cross allreduce saves nothing without
        a separate fast intra fabric)."""
        if not topo.intra_broadcast or not self._host_by_rank:
            return False
        # GROUP-GLOBAL predicate: every rank must take the same branch or
        # the collective deadlocks (a rank alone on its host still joins
        # the hierarchical path — as its own leader with no local peers).
        # The host map is the bridge's slice map, and "two-level applies"
        # is exactly the topology router's MIXED class: spanning hosts
        # with >1 rank on some host (parallel/topology.py taxonomy).
        return _host_topology(self._host_by_rank) == TOPO_MIXED

    def _qreduce_hier(self, fused, layers, pfx, wdt, topo) -> None:
        """Two-level leader reduction (mpi_allreduce_operations.cc:139-185):

        1. intra-node REDUCE to the node leader — non-leaders frame their
           whole fused buffer once (quantized iff CGX_INTRA_COMPRESS) and
           post it over the SHM plane; the leader decompress-accumulates
           into its raw buffer,
        2. node leaders run the flat cross algorithm
           (CGX_CROSS_REDUCTION_TYPE) among themselves over the store,
        3. leaders frame the result once, self-decode it (error symmetry:
           every rank must decode the same bytes,
           scatter_reduce_allgather.cc:157-160), and broadcast over SHM.

        Leaders hold bit-identical values after stage 2 (the flat
        algorithms' own symmetry invariant), and every non-leader decodes
        its leader's stage-3 frame — so all ``ws`` ranks agree bit-exactly,
        the same oracle the flat paths satisfy."""
        me = self._rank
        locals_ = self._local_ranks
        leader = locals_[0]
        li = locals_.index(me)
        intra_raw = not topo.intra_compress
        dummy = cfg.dummy_compression()
        rng = self._stochastic_rng()
        # Stage-3 stochastic noise must be IDENTICAL on every leader: each
        # leader requantizes the same post-cross values, and every rank
        # decodes its own leader's frame — per-rank noise would break
        # cross-host bit-identity. Seed from (global seed, collective key),
        # both group-wide constants.
        rng3 = None
        if cfg.stochastic_rounding():
            import zlib

            rng3 = np.random.default_rng(
                (cfg.global_seed() << 16) ^ (zlib.crc32(pfx.encode()) & 0x7FFF)
            )
        segs = _segments_in(layers, 0, fused.shape[0])
        if me != leader:
            self._put(
                f"{pfx}/h1.{leader}.{li}",
                _compress_frames(fused, segs, dummy or intra_raw, rng, wdt),
                local=True, to=[leader],
            )
            buf = self._take(
                f"{pfx}/h3.{leader}", readers=len(locals_) - 1, local=True,
                peer=leader,
            )
            _decompress_frames(
                buf, segs, fused, dummy or intra_raw, add=False,
                wire_dtype=wdt,
            )
            return
        for idx in range(1, len(locals_)):
            buf = self._take(
                f"{pfx}/h1.{leader}.{idx}", local=True, peer=locals_[idx]
            )
            _decompress_frames(
                buf, segs, fused, dummy or intra_raw, add=True,
                wire_dtype=wdt,
            )
        leaders = _slice_leaders(self._host_by_rank)
        if len(leaders) > 1 and not cfg.async_engaged():
            if self._injector is not None:
                # slow_rank@edge=dcn: the injected slow DCN link — on the
                # SYNC path it sits right on the critical path (every
                # rank stalls behind this leader's cross exchange); on
                # the async path the same fault fires inside the sender
                # thread instead (async_bridge._ship) and the step never
                # feels it. That contrast is bench.py --async-dcn.
                self._injector.delay_edge("slow_rank", "dcn")
            self._qreduce_flat(
                fused, layers, f"{pfx}/hx", wdt, topo.cross_reduction,
                ranks=leaders, local=False,
                force_raw=not topo.cross_compress,
            )
        elif len(leaders) > 1:
            # CGX_ASYNC=on (group-global, env-only — every rank takes
            # this branch together): the cross-slice stage leaves the
            # critical path entirely. Slices reduce intra and diverge;
            # the async plane reconciles them with compressed parameter
            # deltas every CGX_ASYNC_H steps through the dedicated
            # sender thread (outer_exchange_post/poll — PR 13). The
            # train step never blocks on DCN.
            metrics.add("cgx.async.cross_skipped")
        # Every leader requantizes + self-decodes (one fused pass), even one
        # with no local peers: non-leaders on OTHER hosts hold
        # decode(frame(stage-2)), so a leader keeping raw stage-2 values
        # would break global symmetry.
        wire = _requantize_frames(fused, segs, dummy or intra_raw, rng3, wdt)
        if len(locals_) > 1:
            self._put(
                f"{pfx}/h3.{leader}", wire, readers=len(locals_) - 1,
                local=True, to=[r for r in locals_ if r != leader],
            )

    def _sum_alltoall(self, arr: np.ndarray, np_dtype, pfx: str) -> None:
        """Uncompressed small-slice reduction: full exchange + local sum
        (Reducer::AllReduceAlltoAll, reducer.cc:35-94)."""
        ws, me = self._size, self._rank
        self._put(
            f"{pfx}/{me}", arr.astype(np_dtype, copy=False).tobytes(),
            readers=ws - 1,
        )
        for j in range(ws):
            if j == me:
                continue
            buf = self._take(f"{pfx}/{j}", readers=ws - 1, peer=j)
            arr += buf.view(np_dtype)

    def _allreduce_plain(self, t: torch.Tensor, op, seq: int) -> None:
        """Non-eligible dtypes/ops: exchange raw buffers, reduce locally
        (the reference's MPI_Allreduce fallback, ProcessGroupCGX.cc:408-413)."""
        ws, me = self._size, self._rank
        pfx = self._ns(f"cgx{seq}p")
        if t.dtype == torch.bfloat16:
            self._put(f"{pfx}/{me}", self._bytes_of(t), readers=ws - 1)
            parts = [t.detach().reshape(-1).clone()]
            for j in range(ws):
                if j == me:
                    continue
                buf = self._take(f"{pfx}/{j}", readers=ws - 1, peer=j)
                parts.append(
                    torch.from_numpy(buf.copy()).view(torch.bfloat16)
                )
            stack = torch.stack([p.to(torch.float32) for p in parts])
        else:
            np_dtype = _NP_OF_TORCH[t.dtype]
            arr = _to_np(t)
            self._put(f"{pfx}/{me}", arr.tobytes(), readers=ws - 1)
            parts = [torch.from_numpy(arr)]
            for j in range(ws):
                if j == me:
                    continue
                buf = self._take(f"{pfx}/{j}", readers=ws - 1, peer=j)
                parts.append(torch.from_numpy(buf.view(np_dtype).copy()))
            stack = torch.stack(parts)
        if op == dist.ReduceOp.SUM:
            red = stack.sum(dim=0)
        elif op == dist.ReduceOp.PRODUCT:
            red = stack.prod(dim=0)
        elif op == dist.ReduceOp.MIN:
            red = stack.min(dim=0).values
        elif op == dist.ReduceOp.MAX:
            red = stack.max(dim=0).values
        else:
            raise NotImplementedError(f"cgx: unsupported reduce op {op}")
        with torch.no_grad():
            t.detach().reshape(-1).copy_(red.to(t.dtype))

    # -- plain collectives (thin wrappers, ProcessGroupCGX.cc:341-833) ----

    def _check_single(self, tensors) -> None:
        if len(tensors) != 1:
            raise RuntimeError(
                "cgx backend supports single-tensor operations only "
                "(reference ProcessGroupCGX.cc:91-97)"
            )

    def _bytes_of(self, t: torch.Tensor) -> np.ndarray:
        """uint8 view of the tensor's bytes (zero-copy for contiguous
        tensors). _put copies it exactly once — into the store message or
        straight into the shm arena."""
        return t.detach().contiguous().reshape(-1).view(torch.uint8).numpy()

    def _tensor_from(self, buf: np.ndarray, like: torch.Tensor) -> torch.Tensor:
        a = buf if buf.flags.writeable else buf.copy()  # shm reads are owned
        return torch.from_numpy(a).view(like.dtype).reshape(like.shape)

    def broadcast(self, tensors, opts=None):
        self._check_single(tensors)
        t = tensors[0]
        root = int(opts.rootRank) if opts is not None else 0
        seq = self._next_seq()

        def run():
            if self._size == 1:
                return
            key = self._ns(f"cgx{seq}b")
            if self._rank == root:
                self._put(key, self._bytes_of(t), readers=self._size - 1)
            else:
                buf = self._take(key, readers=self._size - 1)
                with torch.no_grad():
                    t.copy_(self._tensor_from(buf, t))

        return self._submit(run, tensors, op="broadcast", seq=seq)

    def allgather(self, output_tensors, input_tensors, opts=None):
        self._check_single(input_tensors)
        inp = input_tensors[0]
        outs = output_tensors[0]
        seq = self._next_seq()

        def run():
            key = self._ns(f"cgx{seq}ag")
            self._put(
                f"{key}/{self._rank}", self._bytes_of(inp),
                readers=self._size - 1,
            )
            for j in range(self._size):
                if j == self._rank:
                    with torch.no_grad():
                        outs[j].copy_(inp)
                    continue
                buf = self._take(f"{key}/{j}", readers=self._size - 1)
                with torch.no_grad():
                    outs[j].copy_(self._tensor_from(buf, outs[j]))

        return self._submit(run, output_tensors, op="allgather", seq=seq)

    def allgather_coalesced(self, output_lists, input_tensors, opts=None):
        # The reference throws here (ProcessGroupCGX.cc:494-501); we loop
        # instead — DDP's CPU model-verification path needs it.
        works = [
            self.allgather([outs], [inp])
            for outs, inp in zip(output_lists, input_tensors)
        ]
        for w in works[:-1]:
            w.wait()
        return works[-1]

    def gather(self, output_tensors, input_tensors, opts=None):
        self._check_single(input_tensors)
        inp = input_tensors[0]
        root = int(opts.rootRank) if opts is not None else 0
        seq = self._next_seq()

        def run():
            key = self._ns(f"cgx{seq}g")
            if self._rank == root:
                outs = output_tensors[0]
                for j in range(self._size):
                    if j == root:
                        with torch.no_grad():
                            outs[j].copy_(inp)
                    else:
                        buf = self._take(f"{key}/{j}")
                        with torch.no_grad():
                            outs[j].copy_(self._tensor_from(buf, outs[j]))
            else:
                self._put(f"{key}/{self._rank}", self._bytes_of(inp),
                          to=[root])

        return self._submit(run, output_tensors, op="gather", seq=seq)

    def scatter(self, output_tensors, input_tensors, opts=None):
        self._check_single(output_tensors)
        out = output_tensors[0]
        root = int(opts.rootRank) if opts is not None else 0
        seq = self._next_seq()

        def run():
            key = self._ns(f"cgx{seq}sc")
            if self._rank == root:
                ins = input_tensors[0]
                for j in range(self._size):
                    if j == root:
                        with torch.no_grad():
                            out.copy_(ins[j])
                    else:
                        self._put(f"{key}/{j}", self._bytes_of(ins[j]),
                                  to=[j])
            else:
                buf = self._take(f"{key}/{self._rank}")
                with torch.no_grad():
                    out.copy_(self._tensor_from(buf, out))

        return self._submit(run, output_tensors, op="scatter", seq=seq)

    def reduce(self, tensors, opts=None):
        self._check_single(tensors)
        t = tensors[0]
        root = int(opts.rootRank) if opts is not None else 0
        op = opts.reduceOp if opts is not None else dist.ReduceOp.SUM
        seq = self._next_seq()

        def run():
            key = self._ns(f"cgx{seq}r")
            if self._rank == root:
                parts = [t.detach().reshape(-1).to(torch.float64)
                         if t.dtype in _TORCH_FLOATS
                         else t.detach().reshape(-1).clone()]
                for j in range(self._size):
                    if j == root:
                        continue
                    buf = self._take(f"{key}/{j}")
                    parts.append(
                        self._tensor_from(buf, t).reshape(-1).to(parts[0].dtype)
                    )
                stack = torch.stack(parts)
                if op == dist.ReduceOp.SUM:
                    red = stack.sum(dim=0)
                elif op == dist.ReduceOp.PRODUCT:
                    red = stack.prod(dim=0)
                elif op == dist.ReduceOp.MIN:
                    red = stack.min(dim=0).values
                elif op == dist.ReduceOp.MAX:
                    red = stack.max(dim=0).values
                else:
                    raise NotImplementedError(f"cgx: unsupported reduce op {op}")
                with torch.no_grad():
                    t.detach().reshape(-1).copy_(red.to(t.dtype))
            else:
                self._put(f"{key}/{self._rank}", self._bytes_of(t),
                          to=[root])

        return self._submit(run, tensors, op="reduce", seq=seq)

    def alltoall(self, output_tensors, input_tensors, opts=None):
        seq = self._next_seq()

        def run():
            key = self._ns(f"cgx{seq}a2a")
            for j in range(self._size):
                if j != self._rank:
                    self._put(f"{key}/{self._rank}>{j}",
                              self._bytes_of(input_tensors[j]), to=[j])
            for j in range(self._size):
                if j == self._rank:
                    with torch.no_grad():
                        output_tensors[j].copy_(input_tensors[j])
                else:
                    buf = self._take(f"{key}/{j}>{self._rank}")
                    with torch.no_grad():
                        output_tensors[j].copy_(
                            self._tensor_from(buf, output_tensors[j])
                        )

        return self._submit(run, output_tensors, op="alltoall", seq=seq)

    def _a2a_lengths(self, t: torch.Tensor, splits) -> Tuple[List[int], List[int]]:
        """Per-destination element (length, offset) pairs for alltoall_base —
        the c10d computeLengthsAndOffsets semantics: split sizes count dim-0
        rows; empty splits mean the even split (ProcessGroupCGX.cc:645-650,
        673-680)."""
        ws = self._size
        n = t.numel()
        if not splits:
            dim0 = t.shape[0] if t.dim() else 0
            if dim0 % ws:
                raise ValueError(
                    f"cgx alltoall_base: tensor dim 0 ({dim0}) does not "
                    f"divide equally across group size {ws}"
                )
            lens = [n // ws] * ws
        else:
            if len(splits) != ws:
                raise ValueError(
                    f"cgx alltoall_base: {len(splits)} split sizes for "
                    f"group size {ws}"
                )
            dim0 = t.shape[0] if t.dim() else 0
            if sum(int(s) for s in splits) != dim0:
                raise ValueError(
                    f"cgx alltoall_base: split sizes sum to "
                    f"{sum(int(s) for s in splits)}, tensor dim 0 is {dim0}"
                )
            row = n // dim0 if dim0 else 0
            lens = [int(s) * row for s in splits]
        offs, acc = [], 0
        for ln in lens:
            offs.append(acc)
            acc += ln
        return lens, offs

    def alltoall_base(
        self, output, input, output_split_sizes, input_split_sizes, opts=None
    ):
        """Single-tensor all-to-all — even (MPI_Alltoall) and uneven
        (MPI_Alltoallv) splits, the ``dist.all_to_all_single`` entry point
        (ProcessGroupCGX.cc:638-705)."""
        if output.dtype != input.dtype:
            raise ValueError(
                "cgx alltoall_base: tensors are not equal in data type"
            )
        # Validate on the calling thread, like the reference's TORCH_CHECKs
        # before enqueue.
        in_lens, in_offs = self._a2a_lengths(input, input_split_sizes)
        out_lens, out_offs = self._a2a_lengths(output, output_split_sizes)
        seq = self._next_seq()
        ws, me = self._size, self._rank

        def run():
            key = self._ns(f"cgx{seq}a2b")
            flat_in = input.detach().contiguous().reshape(-1)
            # reshape(-1) of a non-contiguous output is a detached copy —
            # stage there and copy back stride-aware at the end (same
            # hazard as _allgather_base).
            contig = output.is_contiguous()
            flat_out = (
                output.detach().reshape(-1)
                if contig
                else torch.empty(output.numel(), dtype=output.dtype)
            )
            for j in range(ws):
                if j == me:
                    continue
                piece = flat_in[in_offs[j] : in_offs[j] + in_lens[j]]
                self._put(
                    f"{key}/{me}>{j}",
                    self._bytes_of(piece) if in_lens[j] else b"",
                    to=[j],
                )
            with torch.no_grad():
                flat_out[out_offs[me] : out_offs[me] + out_lens[me]].copy_(
                    flat_in[in_offs[me] : in_offs[me] + in_lens[me]]
                )
                for j in range(ws):
                    if j == me:
                        continue
                    buf = self._take(f"{key}/{j}>{me}")
                    got = buf.size // flat_out.element_size()
                    if got != out_lens[j]:
                        raise RuntimeError(
                            f"cgx alltoall_base: rank {j} sent {got} elements "
                            f"but rank {me}'s output splits expect "
                            f"{out_lens[j]} — mismatched split sizes"
                        )
                    if got:
                        flat_out[
                            out_offs[j] : out_offs[j] + out_lens[j]
                        ].copy_(torch.from_numpy(buf.copy()).view(output.dtype))
                if not contig:
                    output.copy_(flat_out.reshape(output.shape))

        return self._submit(run, [output], op="alltoall_base", seq=seq)

    def barrier(self, opts=None):
        seq = self._next_seq()

        def run():
            # Arrival keys + blocking store.wait (no spin); the last rank
            # through GCs the round's keys via a done-refcount.
            pfx = self._ns(f"cgx{seq}bar")
            self._store.set(f"{pfx}/r{self._rank}", b"1")
            for r in range(self._size):
                self._wait_key(f"{pfx}/r{r}")
            if int(self._store.add(f"{pfx}/done", 1)) >= self._size:
                for r in range(self._size):
                    self._delete_key(f"{pfx}/r{r}")
                self._delete_key(f"{pfx}/done")

        return self._submit(run, None, op="barrier", seq=seq)

    # -- point-to-point (store mailboxes executed on a dedicated pool, so a
    # blocked recv stalls its Work future, not the caller or the collective
    # worker — the AsyncWork model, ProcessGroupCGX.cc:144-226). (src, tag)
    # sequence counters are claimed on the calling thread, so message order
    # is the issue order regardless of pool scheduling. ---------------------

    def _submit_p2p(self, fn, result) -> dist.Work:
        fut = Future()

        def run():
            try:
                fn()
                fut.set_result(result)
            except Exception as e:
                fut.set_exception(e)

        self._p2p_pool.submit(run)
        return _CGXWork(fut)

    def send(self, tensors, dst_rank, tag=0):
        self._check_single(tensors)
        t = tensors[0]
        with self._p2p_claim:
            cnt = self._p2p_send.get((dst_rank, tag), 0)
            self._p2p_send[(dst_rank, tag)] = cnt + 1
        key = self._ns(f"cgxp2p/{self._rank}>{dst_rank}/t{tag}/{cnt}")

        def run():
            self._put(key, self._bytes_of(t),
                      local=dst_rank in self._local_ranks, to=[dst_rank])
            # Announce for any-source matching: one ticket per send, written
            # under a dense per-(dst, tag) sequence so the receiver can
            # store.wait on the next ticket instead of polling mailboxes.
            seq = int(self._store.add(self._ns(f"cgxp2pann/{dst_rank}/t{tag}/n"), 1))
            self._store.set(
                self._ns(f"cgxp2pann/{dst_rank}/t{tag}/{seq}"),
                str(self._rank),
            )

        return self._submit_p2p(run, tensors)

    def recv(self, tensors, src_rank, tag=0):
        self._check_single(tensors)
        t = tensors[0]
        with self._p2p_claim:
            cnt = self._p2p_recv.get((src_rank, tag), 0)
            self._p2p_recv[(src_rank, tag)] = cnt + 1
        key = self._ns(f"cgxp2p/{src_rank}>{self._rank}/t{tag}/{cnt}")

        def run():
            buf = self._take(key, local=src_rank in self._local_ranks)
            with torch.no_grad():
                t.copy_(self._tensor_from(buf, t))

        return self._submit_p2p(run, tensors)

    def recv_anysource(self, tensors, tag=0):
        self._check_single(tensors)
        t = tensors[0]

        # Blocking any-source matching without polling (VERDICT r2 #10):
        # every send deposits an announce ticket under a dense sequence for
        # its destination; the receiver store.wait()s on the next unread
        # ticket — the store's own blocking get, no sleep loop. A ticket
        # whose source has already been drained past it by directed recv()
        # calls is stale and skipped (each send writes exactly one ticket;
        # each receive — directed or any — consumes exactly one payload).
        def run():
            while True:
                with self._p2p_claim:
                    seq = self._p2p_ann.get(tag, 0) + 1
                    self._p2p_ann[tag] = seq
                ann_key = self._ns(f"cgxp2pann/{self._rank}/t{tag}/{seq}")
                # Unbounded (MPI ANY_SOURCE may idle forever) but abort-
                # and shutdown-aware: parks in store.wait slices.
                self._wait_key(ann_key, bounded=False)
                src = int(bytes(self._store.get(ann_key)).decode())
                self._delete_key(ann_key)
                with self._p2p_claim:
                    used = self._p2p_ann_used.get((src, tag), 0)
                    consumed = self._p2p_recv.get((src, tag), 0)
                    self._p2p_ann_used[(src, tag)] = used + 1
                    if used < consumed:
                        claim = None  # stale: a directed recv took this one
                    else:
                        claim = consumed
                        self._p2p_recv[(src, tag)] = consumed + 1
                if claim is None:
                    continue
                key = self._ns(f"cgxp2p/{src}>{self._rank}/t{tag}/{claim}")
                buf = self._take(key, local=src in self._local_ranks)
                with torch.no_grad():
                    t.copy_(self._tensor_from(buf, t))
                return

        return self._submit_p2p(run, tensors)

    # -- unsupported, reference parity ------------------------------------

    # -- sharded-parameter collectives (beyond reference: it throws on all
    # three, ProcessGroupCGX.cc:422-428,631-636,827-833 — which is exactly
    # why FSDP cannot run on it. FSDP's hot collectives are
    # all_gather_into_tensor and reduce_scatter_tensor; the latter is the
    # first half of the SRA algorithm, so eligible tensors get the same
    # quantized treatment as allreduce.) -----------------------------------

    def _allgather_base(self, output, input, opts=None):
        seq = self._next_seq()
        cc = cfg.fsdp_allgather_config()
        compress = (
            cc is not None
            and cc.enabled
            and self._size > 1
            and input.dtype in _TORCH_FLOATS
            and input.numel() >= cfg.minimal_size()
            and not cfg.dummy_compression()
        )

        def run():
            key = self._ns(f"cgx{seq}agb")
            n = input.numel()
            # reshape(-1) of a non-contiguous output is a detached copy —
            # stage there and copy back stride-aware at the end.
            contig = output.is_contiguous()
            flat = output.reshape(-1) if contig else torch.empty(
                output.numel(), dtype=output.dtype
            )
            if compress:
                # Quantized parameter all-gather (CGX_FSDP_ALLGATHER_BITS):
                # each rank frames its shard once; EVERY rank — the owner
                # included — decodes the same wire bytes, so all replicas of
                # the gathered parameter are bit-identical (the error-
                # symmetry invariant, applied to ZeRO-3's unsharding).
                wdt = _wire_dtype(input.dtype)
                seg = [_Segment(0, n, cc.bits, cc.bucket_size)]
                arr = _to_np(input).astype(np.float32, copy=False)
                wire = _compress_frames(
                    arr, seg, False, self._stochastic_rng(), wdt
                )
                self._put(
                    f"{key}/{self._rank}", wire, readers=self._size - 1
                )
                scratch = np.empty(n, np.float32)
                for j in range(self._size):
                    if j == self._rank:
                        buf = np.frombuffer(wire, np.uint8)
                    else:
                        buf = self._take(
                            f"{key}/{j}", readers=self._size - 1
                        )
                    _decompress_frames(
                        buf, seg, scratch, False, add=False, wire_dtype=wdt
                    )
                    _from_np(flat[j * n : (j + 1) * n], scratch)
            else:
                self._put(
                    f"{key}/{self._rank}", self._bytes_of(input),
                    readers=self._size - 1,
                )
                for j in range(self._size):
                    dst = flat[j * n : (j + 1) * n]
                    if j == self._rank:
                        with torch.no_grad():
                            dst.copy_(input.reshape(-1))
                        continue
                    buf = self._take(f"{key}/{j}", readers=self._size - 1)
                    with torch.no_grad():
                        dst.copy_(self._tensor_from(buf, dst))
            if not contig:
                with torch.no_grad():
                    output.copy_(flat.reshape(output.shape))

        return self._submit(run, [output], op="all_gather_into_tensor", seq=seq)

    def _reduce_scatter_base(self, output, input, opts=None):
        """reduce_scatter_tensor: rank r receives the reduction of every
        rank's r-th chunk. Float SUM/AVG inputs are compressed per chunk
        (the scatter-reduce half of SRA, scatter_reduce_allgather.cc:
        116-155); other dtypes/ops exchange raw chunks."""
        op = opts.reduceOp if opts is not None else dist.ReduceOp.SUM
        seq = self._next_seq()
        ws, me = self._size, self._rank
        n = output.numel()
        cc = cfg.default_compression_config()
        do_compress = (
            input.dtype in _TORCH_FLOATS
            and op in (dist.ReduceOp.SUM, dist.ReduceOp.AVG)
            and ws > 1
            and cc.enabled
            and n >= cfg.minimal_size()
        )

        if op == dist.ReduceOp.AVG and not input.is_floating_point():
            raise ValueError(
                "reduce_scatter_tensor: ReduceOp.AVG requires a floating "
                f"dtype, got {input.dtype}"
            )

        def run():
            if ws == 1:
                with torch.no_grad():
                    output.copy_(
                        input.reshape(-1)[:n].reshape(output.shape)
                    )
                return
            key = self._ns(f"cgx{seq}rsb")
            arr = _to_np(input)  # natural dtype (bf16 upcast to f32)
            if do_compress:
                arr = arr.astype(np.float32, copy=False)
                rng = self._stochastic_rng()
                wdt = _wire_dtype(input.dtype)
                seg = [_Segment(0, n, cc.bits, cc.bucket_size)]
                for j in range(ws):
                    if j != me:
                        chunk = np.ascontiguousarray(
                            arr[j * n : (j + 1) * n]
                        )
                        self._put(
                            f"{key}/{me}>{j}",
                            _compress_frames(chunk, seg, False, rng, wdt),
                            to=[j],
                        )
                own = np.ascontiguousarray(arr[me * n : (me + 1) * n])
                for j in range(ws):
                    if j != me:
                        buf = self._take(f"{key}/{j}>{me}")
                        _decompress_frames(
                            buf, seg, own, False, add=True, wire_dtype=wdt
                        )
            else:
                np_dtype = _NP_OF_TORCH.get(input.dtype, np.float32)
                for j in range(ws):
                    if j != me:
                        self._put(
                            f"{key}/{me}>{j}",
                            np.ascontiguousarray(
                                arr[j * n : (j + 1) * n]
                            ).astype(np_dtype, copy=False).tobytes(),
                            to=[j],
                        )
                own = np.ascontiguousarray(arr[me * n : (me + 1) * n])
                for j in range(ws):
                    if j != me:
                        peer = self._take(f"{key}/{j}>{me}").view(np_dtype)
                        if op == dist.ReduceOp.MAX:
                            np.maximum(own, peer, out=own)
                        elif op == dist.ReduceOp.MIN:
                            np.minimum(own, peer, out=own)
                        elif op == dist.ReduceOp.PRODUCT:
                            own *= peer
                        else:
                            own += peer
            if op == dist.ReduceOp.AVG and np.issubdtype(
                own.dtype, np.floating
            ):
                own /= ws
            _from_np(output, own)

        return self._submit(run, [output], op="reduce_scatter_tensor", seq=seq)

    def reduce_scatter(self, output_tensors, input_tensors, opts=None):
        # List form: flatten the per-rank input list into one contiguous
        # buffer and reuse the tensor form.
        self._check_single(output_tensors)
        if len(input_tensors) != 1:
            raise RuntimeError(
                "ProcessGroupCGX supports single-tensor operations only"
            )
        ins = input_tensors[0]
        out = output_tensors[0]
        flat = torch.cat([t.reshape(-1) for t in ins])
        return self._reduce_scatter_base(out, flat, opts)

    # Current torch dispatches all_gather_into_tensor /
    # reduce_scatter_tensor through these names; the _-prefixed ones above
    # are the legacy hooks. Keep both.
    def all_gather_single(self, output, input, opts=None):
        return self._allgather_base(output, input, opts)

    def reduce_scatter_single(self, output, input, opts=None):
        return self._reduce_scatter_base(output, input, opts)

    def allreduce_coalesced(self, tensors, opts=None):
        raise NotImplementedError(
            "ProcessGroupCGX does not support allreduce_coalesced "
            "(reference ProcessGroupCGX.cc:422-428)"
        )

    # -- recovery (robustness/supervisor.py — docs/ROBUSTNESS.md) ---------

    @property
    def generation(self) -> int:
        return self._generation

    @property
    def global_rank(self) -> int:
        """This rank's identity in the ORIGINAL world — stable across
        reconfigurations (group-local ranks re-index on every shrink)."""
        return self._global_ranks[self._rank]

    @property
    def global_ranks(self) -> List[int]:
        return list(self._global_ranks)

    # -- asynchronous cross-slice plane (PR 13) ---------------------------

    @property
    def host_map(self) -> List[str]:
        """The per-rank host fingerprints of the CURRENT membership (the
        survivor-filtered map after a reconfigure) — what the async
        plane's ``Membership.from_hosts`` re-derives slice leaders
        from."""
        return list(self._host_by_rank)

    def async_slice_info(self):
        """(slice_idx, n_slices, leaders, leader_globals, generation)
        for the async plane, derived from the CURRENT host map — never a
        cached classification (the evicted-leader regression class)."""
        hosts = self._host_by_rank or [""] * self._size
        leaders = _slice_leaders(hosts)
        # slice index = position of my host's leader (leaders are in
        # first-seen host order, the slice-id order by construction)
        my_slice = [hosts[r] for r in leaders].index(hosts[self._rank])
        leader_globals = [self._global_ranks[r] for r in leaders]
        return my_slice, len(leaders), leaders, leader_globals, self._generation

    def async_sender(self):
        """The group's outer-exchange transport — one dedicated sender
        thread, created lazily and rebuilt whenever the generation moves
        (a pre-recovery stream's keys describe a dead membership; the
        new sender namespaces under ``g<N>/``)."""
        from . import async_bridge

        snd = self._async_sender
        if snd is None or snd.generation != self._generation:
            if snd is not None:
                snd.stop()
            my_slice, n_slices, leaders, _lg, gen = self.async_slice_info()
            # One consumer per peer slice: only LEADERS poll the DCN
            # streams (non-leaders apply the leader's fold through the
            # intra broadcast — parallel/async_plane.py's two-level
            # outer scheme), so each slice's stream has n_slices - 1
            # readers.
            readers = {
                s: max(1, n_slices - 1) for s in range(max(1, n_slices))
            }
            store = self._store
            if self._transport is not None:
                # PR 20: the outer-exchange stream rides the socket plane
                # toward the peer slice LEADERS — same keys, same
                # publish-after-write counters, framed payload hops. The
                # wrapper routes only the payload-prefix keys; counters
                # (add) and everything else stay on the store.
                from . import transport as transport_mod

                store = transport_mod.TransportStore(
                    self._store, self._transport,
                    peers=[str(r) for r in leaders if r != self._rank],
                    prefixes=(self._ns("cgxasync/"),),
                )
            snd = async_bridge.AsyncBridgeSender(
                store, my_slice, max(1, n_slices),
                ns=self._ns, injector=self._injector, generation=gen,
                readers_by_slice=readers,
            )
            self._async_sender = snd
        return snd

    def async_intra(self):
        """The intra-slice agreement channel for the outer fold
        (``async_bridge.IntraBroadcast``): the slice leader publishes
        its boundary fold bytes, non-leaders apply exactly those — an
        intra-slice (fast-tier) wait, bounded by the group timeout.
        Rebuilt per generation like the sender. None when this rank has
        no same-slice peers (one-process-per-host layouts): publishing
        full-parameter updates no follower ever consumes — or deletes —
        would leak one store key per outer round for the life of the
        run."""
        if len(self._local_ranks) <= 1:
            return None
        from . import async_bridge

        my_slice, _n, _leaders, _lg, gen = self.async_slice_info()
        return async_bridge.IntraBroadcast(
            self._store, my_slice,
            n_local=len(self._local_ranks),
            ns=self._ns, timeout_s=self._timeout_s, generation=gen,
        )

    def outer_exchange_post(self, round_idx: int, payload: bytes) -> None:
        """Non-blocking outer-exchange op: enqueue one outer round's
        compressed delta for the sender thread. Never touches the worker
        FIFO and never blocks — the PR 13 contract."""
        self.async_sender().post(round_idx, payload)

    def outer_exchange_poll(self):
        """Non-blocking outer-exchange op: every peer slice's
        newly-published (peer_slice, round, payload) rounds."""
        return self.async_sender().poll()

    def degrade_to_store(self) -> None:
        """Recovery ladder rung 2: close the shm byte plane and carry all
        payloads over the store. Must be applied group-wide (the
        supervisor coordinates it through the generation rendezvous) — a
        writer keeping shm while a reader degraded would deadlock the
        next collective."""
        if self._shm is not None:
            self._shm.close()
            self._shm = None
        if self._transport is not None:
            # Group-coordinated like the shm close above: every rank runs
            # this rung, so no writer keeps the socket plane while a
            # reader dropped to store-only waits.
            try:
                self._transport.close()
            except Exception as e:
                log.warning("cgx: socket plane close failed: %s", e)
            self._transport = None
        self._all_local = False
        metrics.add("cgx.recovery.transport_degraded")
        flightrec.record(
            "recovery", phase="degrade_transport", rank=self._rank,
            generation=self._generation,
        )
        log.warning(
            "cgx: shm byte plane degraded to store transport "
            "(generation %d)", self._generation,
        )

    def reconfigure(
        self,
        survivors: Sequence[int],
        generation: int,
        *,
        joiner_info: Optional[Mapping[int, str]] = None,
    ) -> None:
        """Recovery ladder rung 3 — and the elastic grow path: reshape
        this group in place to the agreed member set (GLOBAL rank ids)
        at a new generation. ``survivors`` may be any membership delta:
        a shrink (the PR 5 ladder), a grow (elastic join), or both at
        once; global-rank identity is preserved across every reshape.
        Members not currently in the group REQUIRE a ``joiner_info``
        entry (global rank → ``"<host_fp>|<pid>"``, carried by the join
        decision) — the host/pid maps extend from it without any store
        exchange, exactly as the shrink path filters them without one.

        * queued-but-unstarted work entries fail with
          :class:`StaleGenerationError` (the worker loop also re-checks
          each dequeued entry's generation tag),
        * group-local rank/size and the host/pid maps re-derive from the
          survivor subset — no new store exchange: the original
          rendezvous' facts, filtered (SRA/Ring chunk splits re-derive
          from the new ``size`` on the next collective automatically),
        * every store key moves to the ``g<generation>/`` namespace and
          the shm channel's epoch advances (tagged headers +
          drain-on-epoch-bump), so pre-recovery traffic is discarded
          instead of aliasing into the new group (the dead generation's
          already-posted store-path payload keys are NOT enumerable here
          and stay in the store — a bounded leak: at most
          ``max_generations`` incidents per run, collectives in flight
          at each),
        * the collective seq resets (all survivors reconfigure with the
          same arguments, so cross-rank seq agreement is preserved), and
        * the abort poison is cleared — it described the dead generation.

        Raises :class:`EvictedError` when this rank is not a survivor.
        The caller (supervisor) is expected to drive collectives
        synchronously around this call; in-flight work from the failed
        generation must already have completed or failed.
        """
        survivors = sorted(survivors)
        if generation <= self._generation:
            raise ValueError(
                f"reconfigure: generation must advance (have "
                f"{self._generation}, got {generation})"
            )
        me = self.global_rank
        if me not in survivors:
            raise EvictedError(
                f"cgx: global rank {me} is not in the agreed survivor set "
                f"{survivors} (generation {generation}) — evicted"
            )
        joiners = {
            int(g): str(v) for g, v in (joiner_info or {}).items()
        }
        unknown = [g for g in survivors if g not in self._global_ranks]
        missing = [g for g in unknown if g not in joiners]
        if missing:
            raise ValueError(
                f"reconfigure: members {missing} are not in this group "
                f"(globals {self._global_ranks}) and no joiner_info "
                "names their host — a grow without the join decision's "
                "hosts map cannot rebuild the topology"
            )
        evicted = [g for g in self._global_ranks if g not in survivors]
        # Fail everything still queued under the old generation.
        stale_err = StaleGenerationError(
            f"cgx: work from generation {self._generation} discarded by "
            f"reconfiguration to generation {generation}",
            found=self._generation,
            current=generation,
        )
        while True:
            try:
                _fn, fut, _res, _op, _seq, _gen = self._jobs.get_nowait()
            except _queue.Empty:
                break
            self._completions.submit(self._finish, (fut, None, stale_err))
        old_index = {g: i for i, g in enumerate(self._global_ranks)}
        if unknown:
            # Grow (or mixed delta): merge the retained members' facts
            # with the joiners' admitted host/pid info. A solo group has
            # no host map yet (size 1 skips _init_shm) — its own entry
            # derives locally.
            from . import shm as shm_mod

            info: Dict[int, str] = {}
            for g in self._global_ranks:
                i = old_index[g]
                if self._host_by_rank and i < len(self._host_by_rank):
                    pid = (
                        self._pid_by_rank[i]
                        if i < len(self._pid_by_rank) else -1
                    )
                    info[g] = f"{self._host_by_rank[i]}|{pid}"
            info.setdefault(
                me, f"{shm_mod.host_fingerprint()}|{os.getpid()}"
            )
            info.update(joiners)
            hosts, pids = [], []
            for g in survivors:
                h, _, p = info[g].rpartition("|")
                hosts.append(h)
                pids.append(int(p) if p.lstrip("-").isdigit() else -1)
            self._host_by_rank = hosts
            self._pid_by_rank = pids
        else:
            keep = [old_index[g] for g in survivors]
            self._host_by_rank = (
                [self._host_by_rank[i] for i in keep]
                if self._host_by_rank else []
            )
            self._pid_by_rank = (
                [self._pid_by_rank[i] for i in keep]
                if self._pid_by_rank else []
            )
        self._global_ranks = survivors
        self._rank = survivors.index(me)
        self._size = len(survivors)
        if self._host_by_rank:
            fp = self._host_by_rank[self._rank]
            self._local_ranks = [
                j for j, h in enumerate(self._host_by_rank) if h == fp
            ]
        else:
            self._local_ranks = [self._rank]
        self._generation = generation
        self._abort_key = self._ns("cgxctl/abort")
        self._aborted.clear()
        self._seq = 0
        # The p2p sequence maps are keyed by group-local rank ids (which
        # the shrink just re-indexed) and count messages of the dead
        # generation's namespace — same cross-rank-agreement argument as
        # the seq reset above, so they restart from zero too.
        with self._p2p_claim:
            self._p2p_send.clear()
            self._p2p_recv.clear()
            self._p2p_ann.clear()
            self._p2p_ann_used.clear()
        # The outer-exchange sender describes the dead generation's
        # membership/keys: stop it; async_sender() rebuilds at g<N>.
        if self._async_sender is not None:
            self._async_sender.stop()
            self._async_sender = None
        # The socket plane's links/seqs/address book all describe the
        # dead generation's membership: tear it down and rebuild — the
        # ns'd address keys re-exchange endpoints under g<N>/.
        if self._transport is not None:
            try:
                self._transport.close()
            except Exception as e:
                log.warning("cgx transport close on reconfigure: %s", e)
            self._transport = None
        try:
            self._init_transport()
        except Exception as e:
            log.warning(
                "cgx socket transport rebuild failed (%s); store path", e
            )
            self._transport = None
        if self._remote_live is None and len(set(self._host_by_rank)) > 1:
            # A grow just made the group span hosts: arm the cross-host
            # liveness judge exactly as boot would have.
            try:
                from . import shm as shm_mod

                hb_mod.attach_store(shm_mod.default_dir(), self._store)
                self._remote_live = hb_mod.RemoteLiveness(self._store)
            except Exception as e:
                log.warning("cgx store heartbeat setup failed (%s)", e)
        if self._shm is not None:
            if len(self._local_ranks) > 1:
                self._shm.bump_epoch(generation)
            else:
                # No same-host peers survive: the byte plane has no
                # readers left.
                self._shm.close()
                self._shm = None
        elif unknown and len(self._local_ranks) > 1 and cfg.shm_enabled():
            # A joiner landed on this host and this rank had no channel
            # (it was solo, or a prior degrade closed it): re-admit the
            # byte plane under the same quota/creation path as boot.
            # Consensus with the local peers rides the join protocol's
            # shmok flags, not a blocking handshake — on any mismatch
            # the coordinator degrades the whole group to the store.
            from . import shm as shm_mod

            try:
                hb_mod.ensure_heartbeat(shm_mod.default_dir())
                self._shm = shm_mod.ShmChannel(
                    self._store, self._rank, wait_key=self._wait_key
                )
                self._shm.bump_epoch(generation)
            except Exception as e:
                log.warning(
                    "cgx: shm re-admission on grow failed (%s); store "
                    "transport for this rank", e
                )
                self._shm = None
        self._all_local = (
            self._shm is not None and len(self._local_ranks) == self._size
        )
        metrics.add("cgx.recovery.reconfigurations")
        metrics.set("cgx.recovery.generation", float(generation))
        metrics.set("cgx.recovery.ws", float(self._size))
        flightrec.record(
            "recovery", phase="reconfigure", generation=generation,
            survivors=survivors, evicted=evicted, rank=self._rank,
            global_rank=me, ws=self._size,
        )
        timeline.instant(
            "recovery.reconfigure", generation=generation,
            ws=self._size, evicted=evicted,
        )
        log.warning(
            "cgx: group reconfigured to generation %d — survivors "
            "(global) %s, evicted %s; this rank is now %d/%d",
            generation, survivors, evicted, self._rank, self._size,
        )

    # -- identity ---------------------------------------------------------

    def getBackendName(self) -> str:
        return BACKEND_NAME

    def size(self) -> int:
        return self._size

    def rank(self) -> int:
        return self._rank

    def shutdown(self) -> None:
        self._shutdown.set()
        self._p2p_pool.shutdown(wait=False)
        if self._async_sender is not None:
            self._async_sender.stop()
            self._async_sender = None
        if self._transport is not None:
            try:
                self._transport.close()
            except Exception as e:
                log.warning("cgx transport close on shutdown: %s", e)
            self._transport = None
        # Observability flush: black-box dump + final metrics export + the
        # leader-side cross-rank merge over the store. Gated on
        # CGX_METRICS_DIR and leashed like the announce GC below — the
        # store may already be dead, and shutdown must stay bounded.
        if cfg.metrics_dir():
            obs = threading.Thread(
                target=self._export_observability,
                name="cgx-shutdown-obs",
                daemon=True,
            )
            obs.start()
            obs.join(timeout=5.0)
            if obs.is_alive():
                log.warning(
                    "cgx shutdown: observability export still running "
                    "after 5s (store backing gone?); abandoning it"
                )
                metrics.add("cgx.shutdown_obs_abandoned")
        # Announce-ticket GC is best-effort housekeeping on a store that
        # is being torn down — run it on a bounded leash. A c10d FileStore
        # whose backing file is already gone makes EVERY non-creating op
        # (check/get/deleteKey) spin in its open-retry loop for the full
        # store timeout (~30 min); hit mid-GC, that turned this rank's
        # destroy_process_group into a silent half-hour hang (found by the
        # fault harness's pool chaos runs). Shutdown must stay bounded —
        # the same contract the data plane now honors everywhere.
        gc = threading.Thread(
            target=self._gc_announce_tickets,
            name="cgx-shutdown-gc",
            daemon=True,
        )
        gc.start()
        gc.join(timeout=5.0)
        if gc.is_alive():
            log.warning(
                "cgx shutdown: announce-ticket GC still running after 5s "
                "(store backing gone?); abandoning it — keys may persist"
            )
            metrics.add("cgx.shutdown_gc_abandoned")
        # NOTE: the process heartbeat is deliberately NOT stopped here —
        # it is process-scoped (other live groups share it) and dies with
        # the process.
        if self._shm is not None:
            self._shm.close()
            self._shm = None
            self._all_local = False

    def _export_observability(self) -> None:
        """Shutdown-path observability flush (CGX_METRICS_DIR set): dump
        the flight recorder, flush the periodic exporter once more, and
        run the cross-rank aggregation over the store — rank 0 merges
        whatever snapshots arrive within its bounded window into
        ``cluster-report.jsonl`` (a rank that died mid-run shows up in
        ``missing_ranks``, it does not hang the merge)."""
        flightrec.dump(reason="shutdown")
        timeline.flush()
        # Drop this group's reference: flushes now, and stops the daemon
        # only when the LAST group releases — a destroyed group must not
        # leave the flusher appending stale snapshots forever, but a
        # subgroup's teardown must not silence a still-training main
        # group either (refcounted in the exporter module).
        obs_exporter.release_exporter()
        obs_exporter.aggregate_over_store(
            self._store, self._rank, self._size, timeout_s=3.0
        )
        # Cluster health view (no-op when the health engine is off): the
        # leader folds every rank's final health status into
        # cluster-health.jsonl over the same store control plane.
        watch_mod.aggregate_health_over_store(
            self._store, self._rank, self._size, timeout_s=2.0
        )
        # Cluster memory view (no-op when the memledger is off): same
        # merge shape — the leader folds every rank's final ledger
        # snapshot into cluster-mem.jsonl.
        watch_mod.aggregate_mem_over_store(
            self._store, self._rank, self._size, timeout_s=2.0
        )

    def _gc_announce_tickets(self) -> None:
        """Delete announce tickets for this rank's inbox that no
        recv_anysource consumed (directed-recv-only workloads never read
        them — without this, one key per send() would outlive the run).
        Tags are those seen by any receive on this rank; unmatched sends on
        never-received tags leak their payload anyway (MPI would hang), so
        cleaning those is out of scope."""
        tags = {t for (_, t) in self._p2p_recv} | set(self._p2p_ann)
        for tag in tags:
            try:
                n = int(self._store.add(self._ns(f"cgxp2pann/{self._rank}/t{tag}/n"), 0))
            except Exception:
                continue
            seen = self._p2p_ann.get(tag, 0)
            for seq in range(seen + 1, n + 1):
                self._delete_key(self._ns(f"cgxp2pann/{self._rank}/t{tag}/{seq}"))

    def __repr__(self) -> str:
        return f"ProcessGroupCGX(rank={self._rank}, size={self._size})"


def _create_cgx_pg(store, rank: int, size: int, timeout=None):
    return ProcessGroupCGX(store, rank, size, timeout)


_registered = False


def register_backend() -> None:
    """Register ``"cgx"`` with torch.distributed (idempotent). The reference
    does this in a static constructor at module load
    (ProcessGroupCGX.h:258-263); importing :mod:`torch_cgx_tpu.torch_backend`
    has the same effect."""
    global _registered
    if _registered or BACKEND_NAME in dist.Backend.backend_list:
        _registered = True
        return
    dist.Backend.register_backend(
        BACKEND_NAME, _create_cgx_pg, devices=["cpu"]
    )
    _registered = True
