"""Accelerator-resident codec for the torch bridge.

The reference runs its compression on the device that holds the gradients,
fenced by events on a side stream (/root/reference/src/ProcessGroupCGX.cc:
374-407). The TPU-host analogue: stage a bucket segment into a JAX array
(zero-copy from the torch CPU tensor via DLPack where possible), run the
jitted codec — the fused Pallas kernels on a TPU — and copy the compressed
wire bytes (8x smaller at 4 bits) back once. The Store remains the
transport; only the codec math moves off the host CPU.

Wire bytes are identical to the host codec's (``ops/codec_host.py``): the
same chunked-sublane format is implemented by all codec backends and
asserted byte-equal in tests, so a frame encoded on-device decodes on the
host path and vice versa — receivers never need to know which side encoded.

Enabled per CGX_BRIDGE_DEVICE_CODEC ("auto": only when JAX's default
backend is a TPU; "on" forces it — useful for CPU-jax tests; "off" keeps
everything on the host codec). Segments below CGX_BRIDGE_DEVICE_MIN_NUMEL
elements always stay on the host (the device hop has fixed latency).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .. import config as cfg

_state: Optional[dict] = None


def _jax_state() -> Optional[dict]:
    """Lazy jax import + capability probe (None = unavailable)."""
    global _state
    if _state is not None:
        return _state or None
    try:
        import jax

        from ..config import CompressionConfig  # noqa: F401
        from ..ops import dispatch  # noqa: F401

        _state = {"jax": jax, "backend": jax.default_backend()}
    except Exception:  # pragma: no cover - jax always present in-tree
        _state = {}
        return None
    return _state


def enabled(numel: int) -> bool:
    mode = cfg.bridge_device_codec()
    if mode == "off" or numel < cfg.bridge_device_min_numel():
        return False
    if mode == "auto" and not _jax_already_initialized():
        # Auto mode must never be the thing that *initializes* the
        # accelerator runtime: a pure-torch DDP user whose process never
        # touched JAX would otherwise pay (or hang on) device bring-up from
        # inside an allreduce. Auto engages only when JAX is already live
        # in this process; force with CGX_BRIDGE_DEVICE_CODEC=on otherwise.
        return False
    st = _jax_state()
    if st is None:
        return False
    if mode == "on":
        return True
    return st["backend"] == "tpu"


def _jax_already_initialized() -> bool:
    import sys

    j = sys.modules.get("jax")
    if j is None:
        return False
    try:
        from jax._src import xla_bridge as xb

        return bool(getattr(xb, "_backends", None))
    except Exception:
        return True  # unknown jax internals: assume live, let _jax_state try


def _to_device(x: np.ndarray):
    """Host float32 segment -> JAX array, zero-copy where DLPack allows."""
    import jax

    try:
        import torch
        import torch.utils.dlpack as tdlp

        # torch wraps the numpy buffer without a copy; jax imports the
        # DLPack capsule zero-copy on CPU, then XLA moves it to the
        # accelerator as one transfer.
        return jax.dlpack.from_dlpack(
            tdlp.to_dlpack(torch.from_numpy(np.ascontiguousarray(x)))
        )
    except Exception:
        import jax.numpy as jnp

        return jnp.asarray(x)


def quantize(
    x: np.ndarray,
    bits: int,
    bucket_size: int,
    *,
    stochastic_seed: Optional[int] = None,
    meta_dtype=np.float32,
) -> bytes:
    """Encode a float32 segment on the accelerator; returns host wire bytes
    (meta | packed) in the host codec's layout."""
    import jax

    from ..config import CompressionConfig
    from ..ops import dispatch

    cc = CompressionConfig(
        bits=bits, bucket_size=bucket_size, stochastic=stochastic_seed is not None
    )
    key = (
        jax.random.PRNGKey(stochastic_seed)
        if stochastic_seed is not None
        else None
    )
    q = dispatch.quantize_batch(_to_device(x)[None], cc, key=key)
    meta = np.asarray(q.meta[0]).astype(meta_dtype)
    packed = np.asarray(q.packed[0])
    return meta.tobytes() + packed.tobytes()


def dequantize(
    buf: np.ndarray,
    numel: int,
    bits: int,
    bucket_size: int,
    *,
    meta_dtype=np.float32,
) -> np.ndarray:
    """Decode host wire bytes on the accelerator -> float32[numel]."""
    import jax.numpy as jnp

    from ..ops import codec, codec_host as hcodec, dispatch

    meta_b, packed_b, _, total = hcodec.wire_layout(
        numel, bits, bucket_size, meta_dtype
    )
    if isinstance(buf, (bytes, bytearray)):
        buf = np.frombuffer(buf, np.uint8)
    raw = np.ascontiguousarray(buf.reshape(-1).view(np.uint8)[:total])
    nb = meta_b // (2 * np.dtype(meta_dtype).itemsize)
    meta = raw[:meta_b].view(meta_dtype).reshape(nb, 2)
    packed = raw[meta_b : meta_b + packed_b].view(np.uint32)
    q = codec.QTensor(
        packed=_to_device(packed.view(np.int32)).view(jnp.uint32)[None],
        meta=jnp.asarray(np.asarray(meta, dtype=np.float32))[None],
        residual=jnp.zeros((1, 0), jnp.float32),
        numel=numel,
        bits=bits,
        bucket_size=bucket_size,
        dtype=np.dtype(np.float32),
    )
    return np.asarray(
        dispatch.dequantize_batch(q, out_dtype=jnp.float32)[0]
    )
