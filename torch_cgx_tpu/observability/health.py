"""Streaming per-rank health engine: the observability→control bridge.

Everything PRs 2–3 built is post-hoc — metrics, flight-recorder dumps and
Chrome traces are read by a human *after* the run. This module closes the
loop while the job is still running: a low-overhead background evaluator
maintains **online** estimates over the existing typed instruments and
raises typed :class:`HealthEvent`\\ s the moment a trend crosses a gate:

* **straggler** — per-peer score from collective-phase skew. The bridge's
  takes are peer-attributed (``backend._take(peer=...)`` reports both
  completed wait durations and the age of still-in-flight waits), so a
  peer whose signal exceeds the median peer's by
  ``CGX_HEALTH_STRAGGLER_FACTOR`` — *sustained* over two consecutive
  samples — is flagged **before** its stall ever reaches
  ``CGX_BRIDGE_TIMEOUT_MS``.
* **step_regression** — fast EWMA of step time vs the slow baseline EWMA
  (``CGX_HEALTH_STEP_FACTOR``).
* **qerr_slo** — compression-quality SLO: any ``cgx.qerr.*`` histogram's
  recent p90 above ``CGX_HEALTH_QERR_SLO`` (the live relative-L2 stream
  ``CGX_QERR_STATS`` feeds).
* **arena_pressure** — the shm arena pressure-wait counter moving within
  a sample window (a dead/stalled reader trending toward the
  ``CGX_SHM_MAX_MB`` cap).

Events go to every registered **consumer** (the recovery supervisor turns
sustained straggler scores into first-class suspect evidence for the PR 5
policy ladder), to the ``cgx.health.*`` instruments, to the flight
recorder, and — when ``CGX_METRICS_DIR`` is set — to
``health-rank<N>.jsonl`` plus an atomically-replaced
``health-status-rank<N>.json`` snapshot that ``tools/cgx_top.py`` and the
Prometheus endpoint (:mod:`.watch`) render.

With ``CGX_HEALTH`` unset the engine is **inert**: no thread starts, the
hot-path hooks (:func:`wait_begin`/:func:`wait_end`/:func:`note_step`)
are attribute-check no-ops, and nothing in the staged program or wire
changes — the grad_sync bit-identity suite passes unchanged.

Estimator notes: the EWMA pair uses fast/slow half-lives so a regression
is judged against a baseline that forgets slowly; the quantile tracker is
the classic P² algorithm (Jain & Chlamtac 1985) — five markers per
quantile, O(1) update, no sample buffer — validated against numpy
percentile oracles in ``tests/test_health.py``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
import weakref
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config as cfg
from ..utils.logging import get_logger
from .instruments import metrics

log = get_logger()

# Event kinds (the taxonomy docs/OBSERVABILITY.md documents).
STRAGGLER = "straggler"
STEP_REGRESSION = "step_regression"
QERR_SLO = "qerr_slo"
ARENA_PRESSURE = "arena_pressure"
# Asynchronous cross-slice plane (PR 13): a peer slice's outer rounds are
# falling behind this slice's — raised by the async plane's bounded-
# staleness bookkeeping, long before any bridge wait could expire
# (the async plane never blocks on DCN, so no wait ever WOULD expire).
ASYNC_LAG = "async_lag"
# Elastic membership (PR 16): a peer announced a preemption with a
# comeback promise (the supervisor's rejoin rung reads the same notice),
# and membership actually changed (grow or shrink — the policy's
# cooldown anchor).
PREEMPT_NOTICE = "preempt_notice"
MEMBERSHIP = "membership"
# Planner drift (PR 17): a measured critical-path component (compute /
# quantize / wire / queue) is sustainedly off the plan's solve-time
# prediction — the PlanDriftMonitor's signal that the CostModel no
# longer describes this fabric and the planner must re-calibrate.
PLAN_DRIFT = "plan_drift"
# Memory plane (PR 18): the memledger's sliding-window leak detector
# names an owner whose alloc−release delta grows strictly monotonically
# across the full window; the OOM forecaster warns when a pool's
# linear-trend time-to-exhaustion drops inside the configured lead
# window — before the hard wall, not at it.
MEM_LEAK = "mem_leak"
MEM_PRESSURE = "mem_pressure"
# Transport plane (PR 20): a peer edge of the supervised socket plane
# exhausted its reconnect ladder (CGX_TRANSPORT_RETRIES) and degraded to
# the store path — the link, not the peer, is the suspect's failing
# component, but the peer rank is still the actionable name.
LINK_DOWN = "link_down"

# The closed kind registry (lint's health-event-kinds rule cross-checks
# every HealthEvent construction site against this tuple; the
# docs/OBSERVABILITY.md event table mirrors it).
EVENT_KINDS = (
    STRAGGLER, STEP_REGRESSION, QERR_SLO, ARENA_PRESSURE, ASYNC_LAG,
    PREEMPT_NOTICE, MEMBERSHIP, PLAN_DRIFT, MEM_LEAK, MEM_PRESSURE,
    LINK_DOWN,
)

# Wait-signal floor: peer skew is judged relative to the median peer, but
# a baseline of ~0 (healthy peers answer in microseconds) would make any
# noise an infinite ratio — the floor is the smallest wait considered
# operationally interesting at all.
_WAIT_FLOOR_S = 0.05
# A straggler/regression must hold for this many consecutive samples.
_SUSTAIN = 2
# Re-emission cooldown per (kind, suspect): a sustained condition stays
# one event stream, not one event per tick.
_COOLDOWN_S = 10.0


class Ewma:
    """Exponentially-weighted moving average with a configurable
    half-life in *samples* (alpha = 1 - 2^(-1/half_life))."""

    __slots__ = ("alpha", "value", "n")

    def __init__(self, half_life: float = 8.0):
        self.alpha = 1.0 - 2.0 ** (-1.0 / max(half_life, 1e-9))
        self.value = 0.0
        self.n = 0

    def update(self, x: float) -> float:
        x = float(x)
        self.n += 1
        if self.n == 1:
            self.value = x
        else:
            self.value += self.alpha * (x - self.value)
        return self.value


class P2Quantile:
    """P² streaming quantile estimator (Jain & Chlamtac 1985): five
    markers, O(1) per observation, no stored samples. Exact below five
    observations (falls back to sorting the seen values)."""

    __slots__ = ("q", "n", "_init", "_h", "_pos", "_des")

    def __init__(self, q: float):
        if not 0.0 < q < 1.0:
            raise ValueError(f"quantile must be in (0, 1), got {q}")
        self.q = q
        self.n = 0
        self._init: List[float] = []
        self._h: List[float] = []  # marker heights
        self._pos: List[float] = []  # marker positions (1-based)
        self._des: List[float] = []  # desired positions

    def update(self, x: float) -> None:
        x = float(x)
        self.n += 1
        if len(self._init) < 5:
            self._init.append(x)
            if len(self._init) == 5:
                self._init.sort()
                self._h = list(self._init)
                self._pos = [1.0, 2.0, 3.0, 4.0, 5.0]
                self._des = [
                    1.0,
                    1.0 + 2.0 * self.q,
                    1.0 + 4.0 * self.q,
                    3.0 + 2.0 * self.q,
                    5.0,
                ]
            return
        h, pos, des, q = self._h, self._pos, self._des, self.q
        if x < h[0]:
            h[0] = x
            k = 0
        elif x >= h[4]:
            h[4] = x
            k = 3
        else:
            k = 0
            for i in range(1, 4):
                if x < h[i]:
                    break
                k = i
        for i in range(k + 1, 5):
            pos[i] += 1.0
        incr = (0.0, q / 2.0, q, (1.0 + q) / 2.0, 1.0)
        for i in range(5):
            des[i] += incr[i]
        for i in (1, 2, 3):
            d = des[i] - pos[i]
            if (d >= 1.0 and pos[i + 1] - pos[i] > 1.0) or (
                d <= -1.0 and pos[i - 1] - pos[i] < -1.0
            ):
                s = 1.0 if d >= 0 else -1.0
                # parabolic (P²) candidate, linear fallback
                hp = h[i] + s / (pos[i + 1] - pos[i - 1]) * (
                    (pos[i] - pos[i - 1] + s)
                    * (h[i + 1] - h[i]) / (pos[i + 1] - pos[i])
                    + (pos[i + 1] - pos[i] - s)
                    * (h[i] - h[i - 1]) / (pos[i] - pos[i - 1])
                )
                if h[i - 1] < hp < h[i + 1]:
                    h[i] = hp
                else:
                    j = i + (1 if s > 0 else -1)
                    h[i] += s * (h[j] - h[i]) / (pos[j] - pos[i])
                pos[i] += s

    def value(self) -> float:
        if not self._h:
            if not self._init:
                return 0.0
            s = sorted(self._init)
            return s[min(int(self.q * len(s)), len(s) - 1)]
        return self._h[2]


@dataclasses.dataclass(frozen=True)
class HealthEvent:
    """One detected condition. ``suspect`` is a GLOBAL rank (stable
    across reconfigurations — the identity eviction votes use) when the
    event names a peer; ``value``/``threshold`` carry the measurement
    that crossed the gate."""

    kind: str
    rank: int  # emitting rank
    value: float
    threshold: float
    suspect: Optional[int] = None
    severity: str = "warn"
    detail: Tuple[Tuple[str, Any], ...] = ()
    ts: float = 0.0
    t_mono: float = 0.0

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["detail"] = dict(self.detail)
        return d


class _PeerWaits:
    """Per-peer wait signal: EWMA of completed take durations plus the
    oldest still-in-flight wait's age (the straggler case the completed
    stream cannot see — the wait that never finishes)."""

    __slots__ = ("ewma", "last_t")

    def __init__(self):
        self.ewma = Ewma(half_life=4.0)
        self.last_t = 0.0


class HealthEngine:
    """Per-rank streaming evaluator (one per process; see module funcs)."""

    def __init__(
        self,
        rank: int = 0,
        *,
        interval_s: Optional[float] = None,
        straggler_factor: Optional[float] = None,
        step_factor: Optional[float] = None,
        qerr_slo: Optional[float] = None,
    ):
        self.rank = rank
        self._interval = (
            interval_s if interval_s is not None else cfg.health_interval_s()
        )
        self._straggler_factor = (
            straggler_factor if straggler_factor is not None
            else cfg.health_straggler_factor()
        )
        self._step_factor = (
            step_factor if step_factor is not None else cfg.health_step_factor()
        )
        self._qerr_slo = qerr_slo if qerr_slo is not None else cfg.health_qerr_slo()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # wait tracking: token -> (global peer, t0); per-peer aggregates
        self._tok = 0
        self._inflight: Dict[int, Tuple[int, float]] = {}
        self._peers: Dict[int, _PeerWaits] = {}
        # step-time estimators
        self._step_fast = Ewma(half_life=4.0)
        self._step_slow = Ewma(half_life=64.0)
        self._step_p50 = P2Quantile(0.5)
        self._step_p99 = P2Quantile(0.99)
        # event plumbing
        self._consumers: List[Any] = []  # WeakMethod | callable
        self._events: List[HealthEvent] = []  # bounded recent ring
        self._last_emit: Dict[Tuple[str, Optional[int]], float] = {}
        self._sustain: Dict[Tuple[str, Optional[int]], int] = {}
        self._last_counters: Dict[str, float] = {}
        self._status: Dict[str, Any] = {}

    # -- hot-path hooks (called only when the engine is running) ----------

    def wait_begin(self, peer: int, key: str) -> int:
        t0 = time.perf_counter()
        with self._lock:
            self._tok += 1
            tok = self._tok
            self._inflight[tok] = (int(peer), t0)
        return tok

    def wait_end(self, tok: int) -> None:
        t1 = time.perf_counter()
        with self._lock:
            ent = self._inflight.pop(tok, None)
            if ent is None:
                return
            peer, t0 = ent
            pw = self._peers.get(peer)
            if pw is None:
                pw = self._peers[peer] = _PeerWaits()
            pw.ewma.update(t1 - t0)
            pw.last_t = t1

    def note_step(self, dt: float) -> None:
        with self._lock:
            self._step_fast.update(dt)
            self._step_slow.update(dt)
            self._step_p50.update(dt)
            self._step_p99.update(dt)

    def note_async_lag(
        self, suspect: int, lag: float, threshold: float
    ) -> Optional[HealthEvent]:
        """Async-plane hook: peer slice (leader ``suspect``, a GLOBAL
        rank) is ``lag`` outer rounds behind. Gauged every call; an event
        is emitted the moment the lag crosses ``threshold`` — no sustain
        window (outer rounds are already H steps apart; by the second
        crossing the staleness bound itself may have tripped), but the
        per-(kind, suspect) cooldown still applies so a stuck peer is one
        event stream, not one event per inner step. Returns the emitted
        event (None when below threshold or inside the cooldown)."""
        metrics.set(f"cgx.async.lag.r{int(suspect)}", round(float(lag), 4))
        if lag < threshold:
            return None
        ev = HealthEvent(
            kind=ASYNC_LAG, rank=self.rank, value=round(float(lag), 6),
            threshold=float(threshold), suspect=int(suspect),
            detail=(("lag_rounds", float(lag)),),
            ts=round(time.time(), 6),
            t_mono=round(time.perf_counter(), 6),
        )
        return ev if self._emit(ev) else None

    def note_link_down(
        self, suspect: int, failures: float, threshold: float, **detail
    ) -> Optional[HealthEvent]:
        """Transport-plane hook: the socket edge to peer ``suspect`` (a
        GLOBAL rank, like every other event's attribution — scores must
        survive reconfigurations) burned ``failures`` reconnect attempts against
        a ladder of ``threshold`` and degraded to the store path. No
        sustain window — the reconnect ladder already IS the sustain
        (each rung a full connect timeout + backoff); the per-(kind,
        suspect) cooldown keeps a flapping link to one event stream."""
        ev = HealthEvent(
            kind=LINK_DOWN, rank=self.rank, value=round(float(failures), 6),
            threshold=float(threshold), suspect=int(suspect),
            detail=tuple(detail.items()),
            ts=round(time.time(), 6),
            t_mono=round(time.perf_counter(), 6),
        )
        return ev if self._emit(ev) else None

    def note_plan_drift(
        self, ratio: float, threshold: float, component: str = "",
        **detail,
    ) -> Optional[HealthEvent]:
        """Drift-loop hook: a measured critical-path component is
        ``ratio``x the plan's solve-time prediction. No sustain window
        here — the ``PlanDriftMonitor`` already holds its own (it sees
        every comparison; the engine only sees crossings) — but the
        per-(kind, suspect) cooldown applies, so a persistently
        mis-modeled link is one event stream, not one event per step."""
        ev = HealthEvent(
            kind=PLAN_DRIFT, rank=self.rank, value=round(float(ratio), 6),
            threshold=float(threshold), suspect=None,
            detail=(("component", component),) + tuple(detail.items()),
            ts=round(time.time(), 6),
            t_mono=round(time.perf_counter(), 6),
        )
        return ev if self._emit(ev) else None

    def note_mem(
        self, kind: str, value: float, threshold: float, owner: str = "",
        **detail,
    ) -> Optional[HealthEvent]:
        """Memory-ledger hook: a ``mem_leak`` (value = outstanding
        alloc−release delta, threshold = window length) or
        ``mem_pressure`` (value = forecast time-to-exhaustion seconds,
        threshold = lead window seconds) finding. The ledger holds its
        own sustain window — the leak detector *is* a sustain window —
        so the engine only applies the per-(kind, suspect) cooldown;
        ``owner`` rides in detail because suspect is a rank slot."""
        if kind not in (MEM_LEAK, MEM_PRESSURE):
            raise ValueError(f"not a memory event kind: {kind!r}")
        ev = HealthEvent(
            kind=kind, rank=self.rank, value=round(float(value), 6),
            threshold=float(threshold), suspect=None,
            detail=(("owner", owner),) + tuple(detail.items()),
            ts=round(time.time(), 6),
            t_mono=round(time.perf_counter(), 6),
        )
        return ev if self._emit(ev) else None

    def rebind_rank(self, rank: int) -> None:
        """Late rank bind (see ``maybe_start``): the engine may be
        auto-started by ``make_train_step`` before the process knows its
        distributed rank. Status/event writes after this go to the new
        rank's files."""
        with self._lock:
            self.rank = int(rank)

    def forget_peers(self) -> None:
        """Recovery reconfiguration: drop all per-peer wait state plus the
        straggler sustain/cooldown bookkeeping. Post-recovery waits are a
        new stream (same contract as the qerr-cadence reset) — without
        this an evicted peer's wait EWMA freezes at the timeout value and
        re-emits a phantom straggler event every cooldown window forever.
        Gauges for forgotten peers are zeroed so dashboards don't show a
        stale maximal score."""
        with self._lock:
            # _inflight too: the canonical straggler never completes a
            # wait, so it has no _peers entry — only an in-flight one.
            dropped = set(self._peers) | {
                p for p, _ in self._inflight.values()
            }
            self._peers.clear()
            self._inflight.clear()
            # async_lag streams are peer-attributed too: an evicted
            # slice leader's cooldown entry must not suppress (or its
            # stale gauge misreport) the new generation's lag stream.
            self._sustain = {
                k: v for k, v in self._sustain.items()
                if k[0] not in (STRAGGLER, ASYNC_LAG)
            }
            self._last_emit = {
                k: v for k, v in self._last_emit.items()
                if k[0] not in (STRAGGLER, ASYNC_LAG)
            }
        for peer in dropped:
            metrics.set(f"cgx.health.straggler.r{peer}", 0.0)
            metrics.set(f"cgx.async.lag.r{peer}", 0.0)

    # -- consumers ---------------------------------------------------------

    def add_consumer(self, cb: Callable[[HealthEvent], None]) -> None:
        """Register an event consumer. Bound methods are held weakly (a
        supervisor must not be kept alive by the engine); plain functions
        are held strongly."""
        try:
            ref: Any = weakref.WeakMethod(cb)  # type: ignore[arg-type]
        except TypeError:
            ref = cb
        with self._lock:
            self._consumers.append(ref)

    def _notify(self, ev: HealthEvent) -> None:
        with self._lock:
            consumers = list(self._consumers)
        dead = []
        for ref in consumers:
            cb = ref() if isinstance(ref, weakref.WeakMethod) else ref
            if cb is None:
                dead.append(ref)
                continue
            try:
                cb(ev)
            except Exception as e:  # a consumer must not kill the engine
                log.warning("health consumer %r raised: %s", cb, e)
        if dead:
            with self._lock:
                self._consumers = [
                    r for r in self._consumers if r not in dead
                ]

    # -- evaluation --------------------------------------------------------

    def _peer_signals(self, now: float) -> Dict[int, float]:
        """Per-peer wait signal at ``now``: max(completed-wait EWMA,
        oldest in-flight wait age)."""
        with self._lock:
            sig = {p: pw.ewma.value for p, pw in self._peers.items()}
            for peer, t0 in self._inflight.values():
                age = now - t0
                if age > sig.get(peer, 0.0):
                    sig[peer] = age
        return sig

    def straggler_scores(self, now: Optional[float] = None) -> Dict[int, float]:
        """Per-peer skew score: signal over the median of the OTHER
        peers' signals (floored — see ``_WAIT_FLOOR_S``). >= the
        straggler factor means "this peer is holding the collective
        back"."""
        sig = self._peer_signals(now if now is not None else time.perf_counter())
        scores: Dict[int, float] = {}
        for peer, s in sig.items():
            others = sorted(v for p, v in sig.items() if p != peer)
            med = others[len(others) // 2] if others else 0.0
            scores[peer] = s / max(med, _WAIT_FLOOR_S)
        return scores

    def _emit(self, ev: HealthEvent) -> bool:
        """Publish one event unless its (kind, suspect) stream is inside
        the cooldown window. True = actually emitted."""
        key = (ev.kind, ev.suspect)
        now = time.monotonic()
        last = self._last_emit.get(key)
        if last is not None and now - last < _COOLDOWN_S:
            return False
        self._last_emit[key] = now
        with self._lock:
            self._events.append(ev)
            del self._events[:-64]
        metrics.add("cgx.health.events")
        metrics.add(f"cgx.health.events.{ev.kind}")
        from . import flightrec

        fields = ev.to_dict()
        fields["event"] = fields.pop("kind")  # "kind" is flightrec's own
        flightrec.record("health", **fields)
        log.warning(
            "health: %s (rank %d, value %.4g >= %.4g%s)",
            ev.kind, ev.rank, ev.value, ev.threshold,
            f", suspect global rank {ev.suspect}" if ev.suspect is not None
            else "",
        )
        self._append_event(ev)
        self._notify(ev)
        return True

    def _sustained(self, key: Tuple[str, Optional[int]], firing: bool) -> bool:
        if not firing:
            self._sustain.pop(key, None)
            return False
        n = self._sustain.get(key, 0) + 1
        self._sustain[key] = n
        return n >= _SUSTAIN

    def sample(self) -> List[HealthEvent]:
        """One evaluator tick (public for tests; the background thread
        calls it every ``CGX_HEALTH_INTERVAL_S``). Returns the events
        emitted this tick."""
        out: List[HealthEvent] = []
        now = time.perf_counter()
        ts = time.time()

        def mk(kind, value, threshold, suspect=None, **detail) -> HealthEvent:
            return HealthEvent(
                kind=kind, rank=self.rank, value=round(float(value), 6),
                threshold=float(threshold), suspect=suspect,
                detail=tuple(detail.items()), ts=round(ts, 6),
                t_mono=round(now, 6),
            )

        # 1. straggler skew
        scores = self.straggler_scores(now)
        for peer, score in scores.items():
            firing = score >= self._straggler_factor
            metrics.set(f"cgx.health.straggler.r{peer}", round(score, 4))
            if self._sustained((STRAGGLER, peer), firing):
                sig = self._peer_signals(now).get(peer, 0.0)
                out.append(mk(
                    STRAGGLER, score, self._straggler_factor, suspect=peer,
                    wait_s=round(sig, 4),
                ))
        # 2. step-time regression
        with self._lock:
            fast, slow = self._step_fast, self._step_slow
            ratio = (
                fast.value / slow.value
                if slow.n >= 8 and slow.value > 0 else 0.0
            )
        metrics.set("cgx.health.step_ratio", round(ratio, 4))
        if self._sustained((STEP_REGRESSION, None), ratio >= self._step_factor):
            out.append(mk(
                STEP_REGRESSION, ratio, self._step_factor,
                fast_s=round(fast.value, 6), slow_s=round(slow.value, 6),
            ))
        # 3. compression-quality SLO over the live qerr stream
        if self._qerr_slo is not None:
            snap = metrics.snapshot_typed()
            for name, h in snap.get("histograms", {}).items():
                if not name.startswith("cgx.qerr."):
                    continue
                p90 = h.get("p90", 0.0)
                if self._sustained((QERR_SLO, None), p90 > self._qerr_slo):
                    out.append(mk(
                        QERR_SLO, p90, self._qerr_slo,
                        layer=name[len("cgx.qerr."):],
                    ))
                    break  # one SLO event per tick is enough
        # 4. arena-pressure trend (pressure waits moving within a window)
        cur = metrics.get("cgx.arena_pressure_waits")
        prev = self._last_counters.get("cgx.arena_pressure_waits", cur)
        self._last_counters["cgx.arena_pressure_waits"] = cur
        if cur > prev:
            out.append(mk(ARENA_PRESSURE, cur - prev, 0.0))
        emitted = [ev for ev in out if self._emit(ev)]
        self._write_status()
        return emitted

    # -- status/event files (cgx_top + Prometheus read these) -------------

    def status(self) -> Dict[str, Any]:
        """Current health view: straggler scores, step-time estimates,
        recent events — the dict cgx_top renders and the Prometheus
        endpoint exposes as gauges."""
        with self._lock:
            events = [e.to_dict() for e in self._events[-8:]]
            step = {
                "ewma_fast_s": round(self._step_fast.value, 6),
                "ewma_slow_s": round(self._step_slow.value, 6),
                "p50_s": round(self._step_p50.value(), 6),
                "p99_s": round(self._step_p99.value(), 6),
                "n": self._step_slow.n,
            }
        pol = _policy
        return {
            "rank": self.rank,
            "ts": round(time.time(), 6),
            "straggler_scores": {
                str(p): round(s, 4) for p, s in self.straggler_scores().items()
            },
            "step": step,
            "events_recent": events,
            "membership": pol.status() if pol is not None else None,
        }

    def _events_path(self) -> Optional[str]:
        d = cfg.metrics_dir()
        if not d:
            return None
        return os.path.join(d, f"health-rank{self.rank}.jsonl")

    def _append_event(self, ev: HealthEvent) -> None:
        path = self._events_path()
        if path is None:
            return
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with open(path, "a") as f:
                f.write(json.dumps(ev.to_dict()) + "\n")
        except OSError as e:
            log.warning("health event write to %s failed: %s", path, e)

    def _write_status(self) -> None:
        d = cfg.metrics_dir()
        if not d:
            return
        path = os.path.join(d, f"health-status-rank{self.rank}.json")
        try:
            os.makedirs(d, exist_ok=True)
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.status(), f)
            os.replace(tmp, path)  # readers never see a torn status
        except OSError as e:
            log.warning("health status write to %s failed: %s", path, e)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "HealthEngine":
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="cgx-health", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.sample()
            except Exception as e:  # the evaluator must never die silently
                log.warning("health sample failed: %s", e)
                metrics.add("cgx.health.sample_errors")

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None


class MembershipPolicy:
    """The grow/shrink-deciding half of the health plane (PR 16).

    The engine above decides *whom to suspect*; this policy decides
    *when the group's membership should change*: it queues join intents,
    tracks preemption notices (a dying rank promising to come back —
    the supervisor's rejoin rung prefers re-admission over permanent
    eviction for those), rate-limits membership churn through a cooldown
    anchored at the last actual change, and ranks this rank's fitness as
    a snapshot donor. Advice only: the elastic coordinator
    (``robustness/elastic.py``) owns the store protocol that *acts*.
    """

    # Membership changes are expensive (rendezvous + reconfigure + trace
    # cache rebuild): back-to-back grows/shrinks within this window are
    # churn, not capacity management.
    COOLDOWN_S = 5.0
    # A rejoin reservation outlives the promised respawn delay by this
    # slack before the rank is treated as permanently gone.
    REJOIN_SLACK_S = 60.0

    def __init__(self, engine: Optional[HealthEngine] = None):
        self._engine = engine
        self._lock = threading.Lock()
        self._pending: Dict[int, float] = {}  # joiner global rank -> t seen
        self._rejoins: Dict[int, float] = {}  # global rank -> deadline
        self._last_change_t = 0.0

    # -- inputs ------------------------------------------------------------

    def note_join_intent(self, rank: int) -> None:
        with self._lock:
            self._pending[int(rank)] = time.monotonic()
        metrics.set("cgx.elastic.pending_joiners", float(len(self._pending)))

    def note_preempt_notice(self, rank: int, delay_s: float) -> None:
        """A peer published a comeback notice before dying: reserve its
        global rank for re-admission and surface the event."""
        deadline = time.monotonic() + float(delay_s) + self.REJOIN_SLACK_S
        with self._lock:
            self._rejoins[int(rank)] = deadline
        metrics.add("cgx.elastic.preempt_notices")
        eng = self._engine
        if eng is not None:
            eng._emit(HealthEvent(
                kind=PREEMPT_NOTICE, rank=eng.rank, value=float(delay_s),
                threshold=0.0, suspect=int(rank),
                detail=(("respawn_s", float(delay_s)),),
                ts=round(time.time(), 6),
                t_mono=round(time.perf_counter(), 6),
            ))

    def expect_rejoin(self, rank: int, deadline_s: float) -> None:
        """Reserve ``rank`` for re-admission until ``deadline_s`` from
        now (the supervisor's rejoin rung calls this when it shrinks a
        suspect that announced a comeback)."""
        with self._lock:
            self._rejoins[int(rank)] = time.monotonic() + float(deadline_s)

    def note_membership_change(self, generation: int, ws: int) -> None:
        """An actual grow/shrink landed: anchor the churn cooldown, clear
        admitted joiners, and surface the event."""
        now = time.monotonic()
        with self._lock:
            self._last_change_t = now
            self._pending.clear()
        metrics.set("cgx.elastic.pending_joiners", 0.0)
        eng = self._engine
        if eng is not None:
            eng._emit(HealthEvent(
                kind=MEMBERSHIP, rank=eng.rank, value=float(ws),
                threshold=0.0,
                detail=(("generation", int(generation)), ("ws", int(ws))),
                ts=round(time.time(), 6),
                t_mono=round(time.perf_counter(), 6),
            ))

    # -- outputs -----------------------------------------------------------

    def expected_rejoin(self, rank: int) -> bool:
        """True while ``rank`` holds a fresh comeback reservation."""
        now = time.monotonic()
        with self._lock:
            dl = self._rejoins.get(int(rank))
            if dl is not None and now > dl:
                del self._rejoins[int(rank)]
                dl = None
            return dl is not None

    def pending_joiners(self) -> List[int]:
        with self._lock:
            return sorted(self._pending)

    def load_score(self) -> float:
        """This rank's donor-fitness load: the fast step-time EWMA (the
        straggler signal's numerator) — lower means the rank has the
        most headroom to encode and ship snapshot pages. 0.0 with the
        engine off, so donor selection degrades to lowest-global-rank."""
        eng = self._engine
        if eng is None:
            return 0.0
        with eng._lock:
            return round(eng._step_fast.value, 6)

    def advise(self) -> Dict[str, Any]:
        """Current membership advice: ``grow`` (admit the pending
        joiners now — intents queued and the churn cooldown has
        passed), the pending joiner list, and sustained-straggler shrink
        candidates (peers the engine's skew score names, the same
        evidence the eviction vote consumes as hints)."""
        now = time.monotonic()
        with self._lock:
            pending = sorted(self._pending)
            cooled = now - self._last_change_t >= self.COOLDOWN_S
        shrink: List[int] = []
        eng = self._engine
        if eng is not None:
            factor = eng._straggler_factor
            shrink = sorted(
                p for p, s in eng.straggler_scores().items() if s >= factor
            )
        return {
            "grow": bool(pending) and cooled,
            "pending_joiners": pending,
            "shrink_candidates": shrink,
            "cooldown_passed": cooled,
        }

    def status(self) -> Dict[str, Any]:
        now = time.monotonic()
        with self._lock:
            rejoins = sorted(
                r for r, dl in self._rejoins.items() if dl >= now
            )
            pending = sorted(self._pending)
        return {
            "pending_joiners": pending,
            "expected_rejoins": rejoins,
            "ws": int(metrics.get("cgx.recovery.ws")),
            "generation": int(metrics.get("cgx.recovery.generation")),
        }


# ---------------------------------------------------------------------------
# Process singleton + zero-cost hot-path shims.
# ---------------------------------------------------------------------------

_engine: Optional[HealthEngine] = None
_engine_lock = threading.Lock()
_policy: Optional[MembershipPolicy] = None


def active() -> bool:
    """True iff the process health engine is running (the gate every
    hot-path hook checks first — one global load when off)."""
    return _engine is not None


def get_engine() -> Optional[HealthEngine]:
    return _engine


def maybe_start(rank: Optional[int] = None) -> Optional[HealthEngine]:
    """Start (idempotently) the process health engine iff ``CGX_HEALTH``
    is set. Returns None — and starts nothing — otherwise.

    ``rank`` may be unknown at the earliest call site
    (``make_train_step`` can run before dist init): the first caller
    that knows a nonzero rank rebinds an engine auto-started as rank 0
    (flightrec's first-wins ``bind_rank`` convention), so per-rank
    health files never collide on a shared metrics dir."""
    global _engine
    if not cfg.health_enabled():
        return None
    with _engine_lock:
        if _engine is None:
            _engine = HealthEngine(rank or 0).start()
        elif rank and _engine.rank == 0:
            _engine.rebind_rank(rank)
        return _engine


def membership_policy() -> MembershipPolicy:
    """The process membership policy (created lazily; bound to the
    running engine when there is one, engine-less otherwise — the
    elastic coordinator works either way, it just loses the event
    emission and straggler-derived advice)."""
    global _policy
    with _engine_lock:
        if _policy is None:
            _policy = MembershipPolicy(_engine)
        elif _policy._engine is None and _engine is not None:
            _policy._engine = _engine
        return _policy


def stop() -> None:
    """Stop and drop the process engine (tests / explicit teardown)."""
    global _engine, _policy
    with _engine_lock:
        eng, _engine = _engine, None
        _policy = None
    if eng is not None:
        eng.stop()


def add_consumer(cb: Callable[[HealthEvent], None]) -> bool:
    """Attach an event consumer to the running engine (False = engine
    not running; the caller loses nothing — with health off there are no
    events to consume)."""
    eng = _engine
    if eng is None:
        return False
    eng.add_consumer(cb)
    return True


def wait_begin(peer: Optional[int], key: str) -> Optional[int]:
    """Hot-path hook: a peer-attributed bridge wait is starting. No-op
    (None) when the engine is off or the peer is unknown."""
    eng = _engine
    if eng is None or peer is None or peer < 0:
        return None
    return eng.wait_begin(peer, key)


def wait_end(tok: Optional[int]) -> None:
    if tok is None:
        return
    eng = _engine
    if eng is not None:
        eng.wait_end(tok)


def note_step(dt: float) -> None:
    """Hot-path hook: one train step took ``dt`` seconds."""
    eng = _engine
    if eng is not None:
        eng.note_step(dt)


def note_async_lag(
    suspect: Optional[int], lag: float, threshold: float
) -> Optional["HealthEvent"]:
    """Async-plane hook: report a peer slice's outer-round lag (no-op
    when the engine is off or the suspect is unknown). Returns the
    emitted ``async_lag`` event, if any — the async plane folds it into
    its own error detail when the staleness bound trips."""
    eng = _engine
    if eng is None or suspect is None or suspect < 0:
        return None
    return eng.note_async_lag(suspect, lag, threshold)


def note_plan_drift(
    ratio: float, threshold: float, component: str = "", **detail
) -> Optional["HealthEvent"]:
    """Drift-loop hook: report a sustained predicted-vs-measured
    component gap (no-op when the engine is off — the monitor's
    re-calibration poke does not depend on the event plane)."""
    eng = _engine
    if eng is None:
        return None
    return eng.note_plan_drift(ratio, threshold, component, **detail)


def note_link_down(
    suspect: Optional[int], failures: float, threshold: float, **detail
) -> Optional["HealthEvent"]:
    """Transport-plane hook: report a peer edge degraded off the socket
    plane (no-op when the engine is off or the peer is unknown — the
    transport's own metrics/flight-recorder trail does not depend on the
    event plane)."""
    eng = _engine
    if eng is None or suspect is None or suspect < 0:
        return None
    return eng.note_link_down(suspect, failures, threshold, **detail)


def note_mem_event(
    kind: str, value: float, threshold: float, owner: str = "", **detail
) -> Optional["HealthEvent"]:
    """Memory-ledger hook: report a leak/pressure finding (no-op when
    the engine is off — the ledger's gauges, flight-recorder records
    and jsonl snapshots do not depend on the event plane)."""
    eng = _engine
    if eng is None:
        return None
    return eng.note_mem(kind, value, threshold, owner, **detail)


def forget_peers() -> None:
    """Drop per-peer wait state on the running engine (no-op when off) —
    called by ``supervisor.invalidate_trace_caches`` on recovery
    reconfiguration."""
    eng = _engine
    if eng is not None:
        eng.forget_peers()


# ---------------------------------------------------------------------------
# Plan-drift monitor (ISSUE 17): the critical-path feedback loop.
# ---------------------------------------------------------------------------


class PlanDriftMonitor:
    """Compares a plan's solve-time component predictions
    (``StepPlan.pred_components`` / the ``cgx.plan.pred_component.*``
    gauges) against measured critical-path components
    (``observability.critpath`` step analyses / the
    ``cgx.critpath.component.*`` gauges). Past a sustained
    ``factor``x gap on any comparable component it emits ONE
    ``plan_drift`` HealthEvent (engine cooldown keeps the stream to one
    event per window) and pokes the planner's idempotent re-calibration
    (``StepPlanner.update`` — adopt-on-change, so a poke that finds the
    model already right is a no-op).

    Engine-independence: with ``CGX_HEALTH`` unset the event is skipped
    but the gauges and the re-calibration poke still run — closing the
    loop must not require the event plane."""

    # Components whose predicted/measured pairing is meaningful; the
    # measured queue-wait maps onto the predicted per-chunk overhead.
    COMPONENT_MAP = {
        "compute": "compute",
        "quantize": "quantize",
        "wire": "wire",
        "overhead": "queue_wait",
    }
    # Predictions under this are noise, not a baseline (a ratio against
    # ~0 would make any measurement an infinite drift).
    _PRED_FLOOR_S = 1e-6

    def __init__(
        self,
        planner=None,
        *,
        factor: Optional[float] = None,
        sustain: int = _SUSTAIN,
    ):
        self.planner = planner
        self.factor = (
            factor if factor is not None else cfg.health_plan_drift_factor()
        )
        self.sustain = max(1, int(sustain))
        self._n = 0
        self.events: List[HealthEvent] = []
        self.replans = 0

    def ratios(
        self, predicted: Dict[str, float], measured: Dict[str, float]
    ) -> Dict[str, float]:
        """measured/predicted per comparable component (gauged under
        ``cgx.critpath.drift.<component>`` every call)."""
        out: Dict[str, float] = {}
        for pred_key, meas_key in self.COMPONENT_MAP.items():
            p = float(predicted.get(pred_key, 0.0) or 0.0)
            m = measured.get(meas_key)
            if p < self._PRED_FLOOR_S or m is None:
                continue
            r = float(m) / p
            out[pred_key] = r
            metrics.set(f"cgx.critpath.drift.{pred_key}", round(r, 4))
        return out

    def observe(
        self,
        predicted: Dict[str, float],
        measured: Dict[str, float],
    ) -> Optional[HealthEvent]:
        """One comparison (typically once per analyzed step window).
        Returns the ``plan_drift`` event when this observation crossed
        the sustained threshold, None otherwise."""
        ratios = self.ratios(predicted, measured)
        if not ratios:
            return None
        worst_comp, worst = max(ratios.items(), key=lambda kv: kv[1])
        firing = worst >= self.factor
        self._n = self._n + 1 if firing else 0
        if self._n < self.sustain:
            return None
        self._n = 0
        metrics.add("cgx.critpath.drift_trips")
        ev = note_plan_drift(
            worst, self.factor, component=worst_comp,
            ratios=tuple(sorted((k, round(v, 4)) for k, v in ratios.items())),
        )
        if ev is not None:
            self.events.append(ev)
            del self.events[:-16]
        if self.planner is not None:
            try:
                if self.planner.update():
                    self.replans += 1
            except Exception as e:  # the poke must not kill the caller
                log.warning("plan-drift re-calibration poke failed: %s", e)
        return ev

    def poll(self) -> Optional[HealthEvent]:
        """Gauge-driven comparison: read the plan's
        ``cgx.plan.pred_component.*`` gauges and the engine's
        ``cgx.critpath.component.*`` gauges (both already maintained by
        their writers) — the zero-argument form background consumers
        call."""
        predicted = {
            k: float(metrics.get(f"cgx.plan.pred_component.{k}"))
            for k in self.COMPONENT_MAP
        }
        measured = {
            v: float(metrics.get(f"cgx.critpath.component.{v}"))
            for v in self.COMPONENT_MAP.values()
        }
        measured = {k: v for k, v in measured.items() if v > 0.0}
        return self.observe(predicted, measured)
