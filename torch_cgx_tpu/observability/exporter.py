"""Periodic JSONL metrics export + leader-side cross-rank aggregation.

Two consumers of the instrument registry
(:mod:`.instruments`) that get the numbers OUT of the process:

* :class:`MetricsExporter` — a daemon thread appending one typed
  snapshot line to ``CGX_METRICS_DIR/metrics-rank<N>.jsonl`` every
  ``CGX_METRICS_FLUSH_S`` seconds (and once on stop), so a wedged or
  killed rank leaves a trail of its last healthy state.
* :func:`aggregate_over_store` — a cross-rank merge riding the group's
  existing control plane (the c10d Store the bridge already holds): every
  rank publishes its snapshot under a well-known key, the leader polls
  them in with a bounded deadline (a dead rank yields a named gap, not a
  hang — the data plane's own contract), merges counters by sum and
  histograms by component, and appends one cluster line to
  ``CGX_METRICS_DIR/cluster-report.jsonl``.

Both are inert unless ``CGX_METRICS_DIR`` is set.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Dict, List, Optional

from .. import config as cfg
from ..utils.logging import get_logger
from .instruments import metrics

log = get_logger()


class MetricsExporter:
    """Daemon flusher for one rank's registry (use :func:`start_exporter`)."""

    def __init__(self, directory: str, rank: int, flush_s: float):
        self._path = os.path.join(directory, f"metrics-rank{rank}.jsonl")
        self._rank = rank
        self._flush_s = flush_s
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def path(self) -> str:
        return self._path

    def start(self) -> "MetricsExporter":
        os.makedirs(os.path.dirname(self._path) or ".", exist_ok=True)
        self._thread = threading.Thread(
            target=self._run, name="cgx-metrics-exporter", daemon=True
        )
        self._thread.start()
        return self

    def flush(self) -> None:
        rec = {
            "ts": round(time.time(), 6),
            "rank": self._rank,
            "pid": os.getpid(),
            **metrics.snapshot_typed(),
        }
        try:
            with open(self._path, "a") as f:
                f.write(json.dumps(rec) + "\n")
        except OSError as e:  # export must never take down training
            log.warning("metrics export to %s failed: %s", self._path, e)

    def _run(self) -> None:
        while not self._stop.wait(self._flush_s):
            self.flush()

    def stop(self, final_flush: bool = True) -> None:
        self._stop.set()
        if final_flush:
            self.flush()


_exporter: Optional[MetricsExporter] = None
_exporter_refs = 0
_exporter_lock = threading.Lock()
_final_flush_installed = False


def _final_flush() -> None:
    """One last snapshot to disk: the periodic exporter's current state
    plus any buffered timeline spans. Runs from atexit and SIGTERM so a
    rank torn down *between* periodic flushes (the common chaos-run
    shape: SIGTERM from a launcher reaping a failed peer) still leaves
    its last metrics on disk. Never raises."""
    with _exporter_lock:
        ex = _exporter
    try:
        if ex is not None:
            ex.flush()
    except Exception:
        pass
    try:
        from . import timeline

        timeline.flush()
    except Exception:
        pass


def _install_final_flush() -> None:
    """Idempotently register the atexit hook and chain a SIGTERM
    handler (SIGKILL is unhookable by design — that case is what the
    *survivors'* flight dumps are for)."""
    global _final_flush_installed
    if _final_flush_installed:
        return
    _final_flush_installed = True
    atexit.register(_final_flush)
    try:
        import signal

        prev = signal.getsignal(signal.SIGTERM)

        def _on_sigterm(signum, frame):
            # The flush runs on a SEPARATE thread with a bounded join:
            # the handler interrupts the main thread at an arbitrary
            # point, possibly inside one of the registry/timeline locks
            # (metrics.add on a hot path) — flushing inline would then
            # self-deadlock on a non-reentrant lock held by the very
            # frame we interrupted. A worker thread blocks on that lock
            # instead, the join times out, and the process still dies.
            t = threading.Thread(
                target=_final_flush, name="cgx-sigterm-flush", daemon=True
            )
            t.start()
            # Generous but bounded: a contended box can take seconds to
            # schedule the flush thread, and launchers typically allow
            # tens of seconds between SIGTERM and SIGKILL.
            t.join(timeout=10.0)
            if prev is signal.SIG_IGN:
                return  # the process chose to ignore SIGTERM: honor it
            if callable(prev):
                prev(signum, frame)
            else:
                # Restore the default disposition and re-deliver so the
                # process still dies with the conventional 143.
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_sigterm)
    except (ValueError, OSError, ImportError):
        # Non-main thread or platform without signals: atexit still runs.
        pass


def start_exporter(rank: int = 0) -> Optional[MetricsExporter]:
    """Start (idempotently) the process's periodic exporter and take a
    reference on it. Returns None — and starts nothing — when
    ``CGX_METRICS_DIR`` is unset. Each ``start_exporter`` is balanced by
    a :func:`release_exporter` (the process-group lifecycle) or a final
    :func:`stop_exporter` (tests / explicit teardown)."""
    directory = cfg.metrics_dir()
    if not directory:
        return None
    global _exporter, _exporter_refs
    _install_final_flush()
    with _exporter_lock:
        if _exporter is None:
            _exporter = MetricsExporter(
                directory, rank, cfg.metrics_flush_s()
            ).start()
        _exporter_refs += 1
        return _exporter


def release_exporter() -> None:
    """Drop one reference: flush now, and stop the daemon only when the
    last holder releases — a subgroup's shutdown must not silence the
    exporter while the main group is still training."""
    global _exporter, _exporter_refs
    with _exporter_lock:
        _exporter_refs = max(0, _exporter_refs - 1)
        ex = _exporter
        last = _exporter_refs == 0
        if last:
            _exporter = None
    if ex is not None:
        if last:
            ex.stop()
        else:
            ex.flush()


def stop_exporter() -> None:
    """Stop the process exporter after one final flush, dropping all
    references (idempotent)."""
    global _exporter, _exporter_refs
    with _exporter_lock:
        ex, _exporter = _exporter, None
        _exporter_refs = 0
    if ex is not None:
        ex.stop()


_AGG_PREFIX = "cgxmetrics/agg"


def _bounded_store_get(store, key: str, deadline: float):
    """Fetch a store key with the deadline actually enforced against real
    c10d stores: a bare ``get`` on a missing key parks for the STORE's
    own timeout (~300 s — the FileStore open-retry spin PR 1's shutdown
    leash documents), which would let it trump ours. So when the store
    supports ``wait(keys, timeout)`` the park happens in 200 ms slices
    with our deadline checked between them; stores without ``wait``
    (test doubles) are polled with backoff. None = deadline expired."""
    import datetime as _dt

    slice_ = _dt.timedelta(milliseconds=200)
    backoff = 0.001
    can_wait: Optional[bool] = None
    while True:
        slept_in_wait = False
        if can_wait is not False:
            t0 = time.monotonic()
            try:
                store.wait([key], slice_)
                return store.get(key)
            except (NotImplementedError, AttributeError, TypeError):
                can_wait = False  # store double without wait support
            except Exception:
                can_wait = True  # a real wait that timed out its slice
                # A wait that failed in well under its slice didn't time
                # out — it errored (broken store). Don't busy-spin on it.
                slept_in_wait = time.monotonic() - t0 >= 0.1
        else:
            try:
                return store.get(key)
            except Exception:
                pass
        if time.monotonic() >= deadline:
            return None
        if not slept_in_wait:
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.05)


def aggregate_over_store(
    store,
    rank: int,
    world_size: int,
    round_id: int = 0,
    timeout_s: float = 5.0,
) -> Optional[Dict]:
    """Merge every rank's snapshot into one report on the leader.

    Rides the group's existing store control plane — no new transport.
    Every rank (leader included) publishes its typed snapshot under
    ``cgxmetrics/agg/<round>/r<rank>``; rank 0 then polls the keys in
    with a single bounded deadline shared across ranks and merges what
    arrived: counters/gauge sums, histograms by mergeable component
    (count/sum/min/max). Ranks that never published within ``timeout_s``
    are listed in ``missing_ranks`` — a killed rank degrades the report,
    never hangs it.

    Returns the merged report on rank 0 (also appended to
    ``CGX_METRICS_DIR/cluster-report.jsonl`` when set), None elsewhere.
    Never raises: aggregation is housekeeping on a store that may be
    dying (shutdown path).
    """
    try:
        snap = metrics.snapshot_typed()
        key = f"{_AGG_PREFIX}/{round_id}/r{rank}"
        store.set(key, json.dumps({"rank": rank, **snap}).encode())
    except Exception as e:
        log.warning("metrics aggregation publish failed: %s", e)
        return None
    if rank != 0:
        return None
    per_rank: Dict[int, Dict] = {}
    missing: List[int] = []
    deadline = time.monotonic() + timeout_s
    for r in range(world_size):
        raw = _bounded_store_get(
            store, f"{_AGG_PREFIX}/{round_id}/r{r}", deadline
        )
        if raw is None:
            missing.append(r)
            continue
        try:
            per_rank[r] = json.loads(bytes(raw).decode())
        except (ValueError, UnicodeDecodeError):
            missing.append(r)
    counters: Dict[str, float] = {}
    hists: Dict[str, Dict[str, float]] = {}
    for r, snap_r in per_rank.items():
        for k, v in snap_r.get("counters", {}).items():
            counters[k] = counters.get(k, 0.0) + v
        for k, h in snap_r.get("histograms", {}).items():
            m = hists.setdefault(
                k,
                {"count": 0.0, "sum": 0.0, "min": float("inf"),
                 "max": float("-inf")},
            )
            m["count"] += h.get("count", 0.0)
            m["sum"] += h.get("sum", 0.0)
            m["min"] = min(m["min"], h.get("min", float("inf")))
            m["max"] = max(m["max"], h.get("max", float("-inf")))
    for m in hists.values():
        if m["count"]:
            m["mean"] = m["sum"] / m["count"]
        else:
            m.pop("min", None)
            m.pop("max", None)
    report = {
        "ts": round(time.time(), 6),
        "round": round_id,
        "world_size": world_size,
        "ranks_reporting": sorted(per_rank),
        "missing_ranks": missing,
        "counters": counters,
        "histograms": hists,
        "gauges_per_rank": {
            r: s.get("gauges", {}) for r, s in per_rank.items()
        },
    }
    directory = cfg.metrics_dir()
    if directory:
        try:
            os.makedirs(directory, exist_ok=True)
            with open(
                os.path.join(directory, "cluster-report.jsonl"), "a"
            ) as f:
                f.write(json.dumps(report) + "\n")
        except OSError as e:
            log.warning("cluster report write failed: %s", e)
    return report
