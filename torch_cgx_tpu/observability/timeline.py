"""Cross-rank trace timeline: the structured span layer.

PR 2's flight recorder keeps per-rank *evidence*; correlating the same
collective across N ranks still meant a human diffing N JSONL files.
This module is the missing span layer: every interesting host-side
interval — a worker-loop collective, an SRA/Ring phase, a codec
compress/decompress, an shm put/take, a ``trace_span`` body — is
recorded as a **span** carrying

* ``t_mono`` (``time.perf_counter`` — the alignment clock; wall clocks
  on two hosts cannot be trusted) and ``dur_s``,
* the **collective sequence number** and **message key** where one
  exists (``cgx{seq}q/s0>1``-style keys already travel across the shm
  bridge in the store header, so the same allreduce is linkable across
  ranks by key), and
* track metadata (rank, pid, thread id + name) so a merger can lay the
  spans out one track per rank.

Spans are buffered and appended to
``CGX_METRICS_DIR/spans-rank<N>.jsonl`` (first line is a ``meta``
header with the rank's mono→wall delta). ``tools/cgx_trace.py`` merges
the per-rank files into a single Chrome trace-event ``trace.json``
(flow arrows joining matching collectives, clock-offset estimation
from put→take round trips) plus a step-time attribution report.

With ``CGX_METRICS_DIR`` unset the layer is **inert**: ``span()`` is a
plain ``yield``, nothing is buffered, no file is touched, and no
staged program changes (the PR 2 bit-identity suite covers this).
"""

from __future__ import annotations

import atexit
import contextlib
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from .. import config as cfg
from ..utils.logging import get_logger

log = get_logger()

# Ops the bridge worker loop (torch_backend/backend.py ``_run_loop``)
# emits collective spans for. ``tools/lint.py`` cross-checks this list
# against the literal ``op=`` names passed to ``_submit`` — a new
# collective added to the backend without a timeline entry is a lint
# failure, the same style as the print/metric-namespace rules.
# ``tools/cgx_trace.py`` uses it to label per-op attribution rows.
BRIDGE_OPS = frozenset({
    "allreduce",
    "broadcast",
    "allgather",
    "gather",
    "scatter",
    "reduce",
    "alltoall",
    "alltoall_base",
    "barrier",
    "all_gather_into_tensor",
    "reduce_scatter_tensor",
})

# Span categories the attribution report decomposes step time into.
CAT_COLLECTIVE = "collective"  # worker-loop op, end to end
CAT_PHASE = "phase"  # SRA/Ring scatter-reduce vs allgather
CAT_QUANTIZE = "quantize"  # codec frame compress/decompress
CAT_WIRE = "wire"  # byte movement: shm/store put + take copy
CAT_WAIT = "wait"  # queue wait: header/key waits
CAT_SPAN = "span"  # generic trace_span bodies
CAT_TRACE = "trace"  # JAX trace-time structure instants
CAT_RECOVERY = "recovery"  # supervisor ladder: retries, rendezvous, rebuild

_FLUSH_EVERY = 128  # buffered spans before an automatic flush


class Timeline:
    """Buffered per-rank span sink (one per process; see module funcs)."""

    def __init__(self, rank: Optional[int] = None):
        self.rank = rank
        self._buf: List[Dict[str, Any]] = []
        self._lock = threading.Lock()
        self._flush_lock = threading.Lock()  # serializes file appends
        self._atexit_installed = False
        # Paths THIS process has already written: the first flush to a
        # path truncates (a rerun with the same CGX_METRICS_DIR must not
        # append under a stale meta header — collective seqs restart per
        # run, so mixed-run files would cross-link unrelated
        # collectives in the merger); later flushes append.
        self._owned_paths: set = set()
        # Last generation written into each path's meta header: flush
        # re-emits the header when the recovery generation moved so the
        # merger can split the file into per-(rank, generation) tracks
        # (a rejoined rank's spans must not conflate with the dead
        # generation's on one track).
        self._meta_gen: Dict[str, int] = {}

    # -- gating -----------------------------------------------------------

    @staticmethod
    def enabled() -> bool:
        """Timeline recording is on iff ``CGX_METRICS_DIR`` is set
        (re-read per call, like every CGX_* knob)."""
        return cfg.metrics_dir() is not None

    # -- rank binding (same contract as flightrec) ------------------------

    def _effective_rank(self) -> int:
        if self.rank is not None:
            return self.rank
        import sys

        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            try:
                self.rank = int(jax_mod.process_index())
                return self.rank
            except Exception:
                pass
        return 0

    # -- recording --------------------------------------------------------

    def record(
        self,
        name: str,
        cat: str,
        t_mono: float,
        dur_s: float,
        **fields: Any,
    ) -> None:
        """Record a completed span retroactively (callers that already
        hold start/stop perf_counter readings — the hot paths — pay no
        extra clock reads)."""
        if not self.enabled():
            return
        t = threading.current_thread()
        ev = {
            "kind": "span",
            "name": name,
            "cat": cat,
            "t_mono": round(t_mono, 7),
            "dur_s": round(dur_s, 7),
            "tid": t.ident,
            "tname": t.name,
        }
        ev.update(fields)
        self._push(ev)

    def instant(self, name: str, cat: str = CAT_TRACE, **fields: Any) -> None:
        if not self.enabled():
            return
        t = threading.current_thread()
        ev = {
            "kind": "instant",
            "name": name,
            "cat": cat,
            "t_mono": round(time.perf_counter(), 7),
            "tid": t.ident,
            "tname": t.name,
        }
        ev.update(fields)
        self._push(ev)

    @contextlib.contextmanager
    def span(self, name: str, cat: str = CAT_SPAN, **fields: Any):
        """Context manager form; a span whose body raises is still
        recorded (``ok: false``) — failed collectives are the
        interesting ones."""
        if not self.enabled():
            yield
            return
        t0 = time.perf_counter()
        try:
            yield
        except BaseException:
            self.record(
                name, cat, t0, time.perf_counter() - t0, ok=False, **fields
            )
            raise
        self.record(name, cat, t0, time.perf_counter() - t0, **fields)

    def _push(self, ev: Dict[str, Any]) -> None:
        with self._lock:
            self._buf.append(ev)
            n = len(self._buf)
            if not self._atexit_installed:
                # A rank torn down between flushes must still leave its
                # spans on disk (the exporter's SIGTERM hook also calls
                # flush() — this is the belt for plain exits).
                self._atexit_installed = True
                atexit.register(self.flush)
        if n >= _FLUSH_EVERY:
            self.flush()

    # -- output -----------------------------------------------------------

    def path(self) -> Optional[str]:
        d = cfg.metrics_dir()
        if not d:
            return None
        return os.path.join(d, f"spans-rank{self._effective_rank()}.jsonl")

    def flush(self) -> None:
        """Append buffered spans to the rank's span file. Never raises —
        flushes run on failure/teardown paths."""
        with self._lock:
            if not self._buf:
                return
            buf, self._buf = self._buf, []
        path = self.path()
        if path is None:
            return  # CGX_METRICS_DIR raced off between record and flush
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with self._flush_lock:
                first = path not in self._owned_paths
                self._owned_paths.add(path)
                gen = self._generation()
                reheader = first or self._meta_gen.get(path) != gen
                # cgx-analysis: allow(lock-blocking) — the flush lock exists precisely to serialize this append (truncate-vs-append races); event writers never take it
                with open(path, "w" if first else "a") as f:
                    if reheader:
                        f.write(json.dumps(self._meta(gen)) + "\n")
                        self._meta_gen[path] = gen
                    for ev in buf:
                        f.write(json.dumps(ev) + "\n")
        except Exception as e:
            log.warning("timeline flush to %s failed: %s", path, e)

    @staticmethod
    def _generation() -> int:
        """Current recovery generation (the backend's
        ``cgx.recovery.generation`` gauge; 0 before any recovery)."""
        try:
            from ..utils.logging import metrics

            return int(metrics.get("cgx.recovery.generation"))
        except Exception:
            return 0

    def _meta(self, generation: Optional[int] = None) -> Dict[str, Any]:
        """File header: the rank's identity, recovery generation, and
        its mono→wall mapping — the merger's *fallback* alignment when
        no cross-rank message pairs exist (the primary alignment never
        trusts wall clocks)."""
        t_mono = time.perf_counter()
        t_wall = time.time()
        return {
            "kind": "meta",
            "rank": self._effective_rank(),
            "generation": (
                self._generation() if generation is None else int(generation)
            ),
            "pid": os.getpid(),
            "t_mono": round(t_mono, 7),
            "t_wall": round(t_wall, 6),
            "mono_wall_delta": round(t_wall - t_mono, 6),
        }


_timeline: Optional[Timeline] = None
_timeline_lock = threading.Lock()


def get_timeline() -> Timeline:
    global _timeline
    with _timeline_lock:
        if _timeline is None:
            _timeline = Timeline()
        return _timeline


def enabled() -> bool:
    return Timeline.enabled()


def bind_rank(rank: int) -> Timeline:
    """First-wins rank binding (mirror of ``flightrec.bind_rank``: a
    subgroup's group-local rank must not steal the file of the default
    group's process-global rank)."""
    tl = get_timeline()
    if tl.rank is None:
        tl.rank = rank
    return tl


def set_rank(rank: int) -> Timeline:
    tl = get_timeline()
    tl.rank = rank
    return tl


def record(name: str, cat: str, t_mono: float, dur_s: float, **fields) -> None:
    get_timeline().record(name, cat, t_mono, dur_s, **fields)


def instant(name: str, cat: str = CAT_TRACE, **fields) -> None:
    get_timeline().instant(name, cat, **fields)


def span(name: str, cat: str = CAT_SPAN, **fields):
    return get_timeline().span(name, cat, **fields)


def flush() -> None:
    get_timeline().flush()


def reset() -> None:
    """Drop the process timeline (tests: fresh buffer per case)."""
    global _timeline
    with _timeline_lock:
        tl, _timeline = _timeline, None
    if tl is not None:
        try:
            atexit.unregister(tl.flush)
        except Exception:
            pass
