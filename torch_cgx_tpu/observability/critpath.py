"""Distributed critical-path engine (ISSUE 17).

Reconstructs the cross-rank dependency DAG from the span streams the
timeline layer already leaves in ``CGX_METRICS_DIR`` — collective
``(group, op, seq)`` rounds, ``put → take`` happens-before message keys,
sched chunk spans, and the serving plane's request-tagged frames — and
walks the **distributed critical path** backward through it:

* per train step (``step`` instants when the trainer emits them,
  collective rounds otherwise): which rank/edge/phase the step's wall
  time actually sat on, decomposed into the dominator taxonomy
  ``compute / quantize / wire / queue_wait / straggler_wait`` (the last
  carrying the suspect rank — the cluster was idle waiting on it);
* per serving request (``req``-tagged spans threaded prefill → ship →
  decode): a TTFT decomposition into
  ``admission / prefill / ship / decode / other``.

The walk is a single backward chain: start at the window's latest span
end, attribute the segment under the cursor to its most-specific
covering span's category, and *jump tracks* at happens-before edges —
a take-wait whose matching put published late jumps to the sender, a
collective exit gated by the last entrant jumps to the straggler.
Un-spanned gaps on the critical track are ``straggler_wait`` charged to
that rank: the cluster waited on it doing nothing recorded.

Every span-file read is **bounded** (``CGX_CRITPATH_MAX_MB`` per file,
tail-biased — lint's unbounded-wait rule forbids argless reads in this
file), and the per-directory analysis memo is reset-reachable from
``robustness.supervisor.invalidate_trace_caches`` via
:func:`invalidate_critpath_cache` (the analyzer's orphan-memo pass
proves it).

Loadable standalone (``tools/cgx_critpath.py`` / ``cgx_top`` load this
file by path, stdlib only): package imports are guarded — outside the
package the metric hooks become no-ops.

Metrics: ``cgx.critpath.*`` (docs/OBSERVABILITY.md "Metric namespaces").
"""

from __future__ import annotations

import glob
import json
import os
from collections import OrderedDict, defaultdict
from typing import Any, Dict, List, Optional, Tuple

if __package__:
    from ..utils.logging import metrics
else:  # standalone load (tools/): metric hooks are no-ops

    class _NullMetrics:
        def add(self, *a, **k):
            return 0.0

        def set(self, *a, **k):
            return None

        def get(self, *a, **k):
            return 0.0

    metrics = _NullMetrics()  # type: ignore[assignment]

# Category string literals (== observability.timeline CAT_*; literal so
# the module loads standalone).
_CAT_COLLECTIVE = "collective"
_CAT_PHASE = "phase"
_CAT_QUANTIZE = "quantize"
_CAT_WIRE = "wire"
_CAT_WAIT = "wait"
_CAT_SPAN = "span"

_PUT_NAMES = ("shm.put", "store.put")
_TAKE_WAIT_NAMES = ("shm.take.wait", "store.take.wait")

#: Dominator taxonomy (docs/OBSERVABILITY.md "Critical path & drift").
COMPONENTS = ("compute", "quantize", "wire", "queue_wait", "straggler_wait")

_CAT_TO_COMPONENT = {
    _CAT_QUANTIZE: "quantize",
    _CAT_WIRE: "wire",
    _CAT_WAIT: "queue_wait",
    _CAT_SPAN: "compute",
    _CAT_COLLECTIVE: "compute",
    _CAT_PHASE: "compute",
}

# Track keys: rank for generation 0, rank + gen * stride otherwise —
# the same convention tools/cgx_trace.py uses for per-(rank, generation)
# tracks after an elastic membership change.
GEN_STRIDE = 100000

_EPS = 1e-9
_WALK_CAP = 200000  # backward-walk iteration bound (reads are bounded too)


def _max_read_bytes() -> int:
    """Per-file read cap: ``CGX_CRITPATH_MAX_MB`` (default 64)."""
    raw = os.environ.get("CGX_CRITPATH_MAX_MB", "")
    if not raw:
        mb = 64.0
    else:
        try:
            mb = float(raw)
        except ValueError:
            raise ValueError(
                f"env var CGX_CRITPATH_MAX_MB must be a float, got {raw!r}"
            ) from None
    return max(1 << 16, int(mb * (1 << 20)))


def _read_jsonl_bounded(
    path: str, max_bytes: int
) -> Tuple[List[dict], bool]:
    """Parse up to ``max_bytes`` of a span JSONL file, tail-biased: an
    over-cap file keeps its newest spans (the window being analyzed) and
    drops the head. Torn lines (killed writer, seek landing mid-line)
    are skipped. Returns (rows, truncated)."""
    truncated = False
    try:
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if size > max_bytes:
                truncated = True
                f.seek(size - max_bytes)
            data = f.read(max_bytes)
    except OSError:
        return [], False
    lines = data.decode("utf-8", "replace").split("\n")
    if truncated and lines:
        lines = lines[1:]  # the seek's partial first line
    rows: List[dict] = []
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            rows.append(row)
    return rows, truncated


def load_tracks(
    directory: str, max_bytes_per_file: Optional[int] = None
) -> Dict[int, dict]:
    """{track key: {"rank", "generation", "meta", "events",
    "truncated"}} — one track per (rank, generation) segment, split at
    generation-tagged ``meta`` headers (elastic membership: a rejoined
    rank's spans must not conflate with the dead generation's)."""
    cap = max_bytes_per_file or _max_read_bytes()
    tracks: Dict[int, dict] = {}
    for p in sorted(glob.glob(os.path.join(directory, "spans-rank*.jsonl"))):
        name = os.path.basename(p)
        try:
            rank = int(name[len("spans-rank"):].split(".")[0])
        except (ValueError, IndexError):
            continue
        rows, truncated = _read_jsonl_bounded(p, cap)
        segs: List[Tuple[int, Optional[dict], List[dict]]] = []
        cur_gen, cur_meta, cur_events = 0, None, []  # type: ignore[var-annotated]
        for r in rows:
            kind = r.get("kind")
            if kind == "meta":
                g = int(r.get("generation") or 0)
                if cur_meta is None and not cur_events:
                    cur_gen, cur_meta = g, r
                elif g != cur_gen:
                    segs.append((cur_gen, cur_meta, cur_events))
                    cur_gen, cur_meta, cur_events = g, r, []
            elif kind in ("span", "instant") and isinstance(
                r.get("t_mono"), (int, float)
            ):
                cur_events.append(r)
        segs.append((cur_gen, cur_meta, cur_events))
        segs = [s for s in segs if s[1] is not None or s[2]]
        if not segs:
            tracks[rank] = {
                "rank": rank, "generation": 0, "meta": None,
                "events": [], "truncated": truncated,
            }
            continue
        multi = len(segs) > 1
        for gen, meta, events in segs:
            key = rank + gen * GEN_STRIDE if multi and gen else rank
            ent = tracks.get(key)
            if ent is not None:  # same (rank, gen) re-headed: merge
                ent["events"].extend(events)
                continue
            tracks[key] = {
                "rank": rank, "generation": gen, "meta": meta,
                "events": events, "truncated": truncated,
            }
    return tracks


def estimate_offsets(tracks: Dict[int, dict]) -> Dict[int, float]:
    """Per-track additive mono-clock correction (reference = lowest
    track key): put-end happens-before take-wait-end bounds per message
    key, NTP midpoint when both directions exist, wall-clock meta delta
    for disconnected tracks. Compact mirror of the cgx_trace estimator."""
    keys = sorted(tracks)
    if not keys:
        return {}
    puts: Dict[str, List[Tuple[int, float]]] = defaultdict(list)
    takes: Dict[str, List[Tuple[int, float]]] = defaultdict(list)
    for tk, data in tracks.items():
        for ev in data["events"]:
            mk = ev.get("key")
            if not mk:
                continue
            if ev.get("name") in _PUT_NAMES:
                puts[mk].append((tk, ev["t_mono"] + ev.get("dur_s", 0.0)))
            elif ev.get("name") in _TAKE_WAIT_NAMES:
                takes[mk].append((tk, ev["t_mono"] + ev.get("dur_s", 0.0)))
    lo: Dict[Tuple[int, int], float] = {}
    for mk, senders in puts.items():
        if len(senders) != 1:
            continue
        a, t_pub = senders[0]
        for b, t_hdr in takes.get(mk, []):
            if a == b:
                continue
            bound = t_pub - t_hdr
            cur = lo.get((a, b))
            if cur is None or bound > cur:
                lo[(a, b)] = bound
    est: Dict[Tuple[int, int], float] = {}
    for (a, b), lob in lo.items():
        est[(a, b)] = (lob + -lo[(b, a)]) / 2.0 if (b, a) in lo else lob
    offsets: Dict[int, float] = {keys[0]: 0.0}
    frontier = [keys[0]]
    while frontier:
        a = frontier.pop()
        for b in keys:
            if b in offsets:
                continue
            if (a, b) in est:
                offsets[b] = offsets[a] + est[(a, b)]
                frontier.append(b)
            elif (b, a) in est:
                offsets[b] = offsets[a] - est[(b, a)]
                frontier.append(b)
    ref_meta = tracks[keys[0]].get("meta") or {}
    ref_delta = ref_meta.get("mono_wall_delta")
    for k in keys:
        if k in offsets:
            continue
        delta = (tracks[k].get("meta") or {}).get("mono_wall_delta")
        if ref_delta is not None and delta is not None:
            offsets[k] = delta - ref_delta
        else:
            offsets[k] = 0.0
    return offsets


# ---------------------------------------------------------------------------
# DAG assembly: aligned spans, message edges, collective gates.
# ---------------------------------------------------------------------------


def _aligned(tracks: Dict[int, dict], offsets: Dict[int, float]) -> dict:
    """One pass over every track: aligned span/instant lists plus the
    cross-track edge indexes (unique put senders per message key, last
    entrant per collective round)."""
    spans: Dict[int, List[dict]] = {}
    instants: Dict[int, List[dict]] = {}
    put_src: Dict[str, Tuple[int, float]] = {}
    put_multi: set = set()
    rounds: Dict[Tuple[int, str, int], List[Tuple[int, float, float]]] = (
        defaultdict(list)
    )
    for tk, data in tracks.items():
        off = offsets.get(tk, 0.0)
        ss: List[dict] = []
        ii: List[dict] = []
        for ev in data["events"]:
            t0 = float(ev["t_mono"]) + off
            if ev.get("kind") == "instant":
                ii.append({
                    "name": ev.get("name"), "cat": ev.get("cat"),
                    "t": t0, "req": ev.get("req"), "ev": ev,
                })
                continue
            t1 = t0 + float(ev.get("dur_s", 0.0))
            s = {
                "name": ev.get("name"), "cat": ev.get("cat"),
                "t0": t0, "t1": t1, "key": ev.get("key"),
                "seq": ev.get("seq"), "group": ev.get("group"),
                "req": ev.get("req"), "track": tk,
            }
            ss.append(s)
            if s["key"] and s["name"] in _PUT_NAMES:
                if s["key"] in put_src and put_src[s["key"]][0] != tk:
                    put_multi.add(s["key"])
                else:
                    put_src[s["key"]] = (tk, t1)
            if s["cat"] == _CAT_COLLECTIVE and s["seq"] is not None:
                rounds[(int(ev.get("group", 0)), s["name"], int(s["seq"]))
                       ].append((tk, t0, t1))
        ss.sort(key=lambda s: (s["t1"], s["t0"]))
        spans[tk] = ss
        instants[tk] = sorted(ii, key=lambda i: i["t"])
    for mk in put_multi:
        put_src.pop(mk, None)
    # Last entrant per round: the participant whose START gates everyone
    # else's exit (the straggler edge of the collective barrier).
    gates: Dict[Tuple[int, str, int], Tuple[int, float]] = {}
    for rk, parts in rounds.items():
        if len(parts) < 2:
            continue
        tk, t0, _t1 = max(parts, key=lambda p: p[1])
        gates[rk] = (tk, t0)
    return {
        "spans": spans, "instants": instants,
        "puts": put_src, "rounds": rounds, "gates": gates,
    }


def _step_windows(dag: dict) -> List[Tuple[float, float, str]]:
    """Step window boundaries: trainer ``step`` instants when present
    (the grad_sync cadence marker), else collective rounds — each
    round's cluster-wide exit closes a window that opened at the
    previous round's exit. Returns [(t0, t1, label)]."""
    step_ts: List[float] = sorted(
        i["t"]
        for ii in dag["instants"].values()
        for i in ii
        if i["name"] == "step"
    )
    all_t0 = [s["t0"] for ss in dag["spans"].values() for s in ss]
    if not all_t0:
        return []
    t_min = min(all_t0)
    t_max = max(s["t1"] for ss in dag["spans"].values() for s in ss)
    if len(step_ts) >= 2:
        bounds = [t_min] + step_ts + [t_max]
        return [
            (bounds[i], bounds[i + 1], f"step{i}")
            for i in range(len(bounds) - 1)
            if bounds[i + 1] - bounds[i] > _EPS
        ]
    # Collective-round segmentation: one window per multi-rank round.
    ends = sorted(
        (max(t1 for _tk, _t0, t1 in parts), rk)
        for rk, parts in dag["rounds"].items()
        if len(parts) >= 2
    )
    if not ends:
        return [(t_min, t_max, "window0")]
    out: List[Tuple[float, float, str]] = []
    prev = t_min
    for i, (t_end, rk) in enumerate(ends):
        if t_end - prev > _EPS:
            out.append((prev, t_end, f"{rk[1]}#{rk[2]}"))
            prev = t_end
    return out


# ---------------------------------------------------------------------------
# The backward walk.
# ---------------------------------------------------------------------------


def _covering(spans: List[dict], t: float) -> List[dict]:
    return [s for s in spans if s["t0"] < t - _EPS and s["t1"] >= t - _EPS]


def _prev_end(spans: List[dict], t: float) -> Optional[float]:
    best = None
    for s in spans:
        if s["t1"] <= t - _EPS and (best is None or s["t1"] > best):
            best = s["t1"]
    return best


def _walk_window(dag: dict, tracks: Dict[int, dict], w0: float, w1: float) -> dict:
    """One window's critical path: backward chain from the latest span
    end, segment attribution per the dominator taxonomy, cross-track
    jumps at message keys and collective gates."""
    spans = dag["spans"]
    comp = {c: 0.0 for c in COMPONENTS}
    by_rank: Dict[int, float] = defaultdict(float)
    suspects: Dict[int, float] = defaultdict(float)
    edges: List[dict] = []

    def rank_of(tk: int) -> int:
        return int(tracks[tk]["rank"]) if tk in tracks else int(tk % GEN_STRIDE)

    # Window event index per track + the terminal (latest end).
    win: Dict[int, List[dict]] = {}
    term_tk, term_t = None, None
    for tk, ss in spans.items():
        sel = [s for s in ss if s["t1"] > w0 + _EPS and s["t0"] < w1 - _EPS]
        if not sel:
            continue
        win[tk] = sel
        end = min(max(s["t1"] for s in sel), w1)
        if term_t is None or end > term_t:
            term_tk, term_t = tk, end
    if term_tk is None:
        return {
            "components": comp, "by_rank": {}, "suspects": {},
            "edges": [], "path_s": 0.0,
        }

    def charge(tk: int, component: str, lo: float, hi: float,
               suspect: Optional[int] = None) -> None:
        d = hi - lo
        if d <= _EPS:
            return
        comp[component] += d
        r = suspect if suspect is not None else rank_of(tk)
        by_rank[r] += d
        if component == "straggler_wait":
            suspects[r] += d

    # Per-track segment boundaries: every span edge. A covering leaf is
    # only charged down to the nearest boundary below the cursor — the
    # walk must re-classify at each edge so sub-spans nested inside a
    # collective (the quantize/wire/wait breakdown) each get their own
    # segment instead of the enclosing span swallowing them.
    bnds: Dict[int, List[float]] = {
        k: sorted({b for s in ss for b in (s["t0"], s["t1"])})
        for k, ss in win.items()
    }

    def below(tk: int, t: float) -> float:
        best = w0
        for b in bnds.get(tk, ()):
            if b >= t - _EPS:
                break
            if b > best:
                best = b
        return best

    tk, t = term_tk, term_t
    for _ in range(_WALK_CAP):
        if t <= w0 + _EPS:
            break
        cover = _covering(win.get(tk, []), t)
        if not cover:
            pe = _prev_end(win.get(tk, []), t)
            lo = max(pe if pe is not None else w0, w0)
            # Un-spanned gap on the critical track: the cluster waited
            # on this rank doing nothing recorded.
            charge(tk, "straggler_wait", lo, t, suspect=rank_of(tk))
            t = lo
            continue
        leaf = min(cover, key=lambda s: s["t1"] - s["t0"])
        lo = below(tk, t)
        if leaf["cat"] == _CAT_WAIT:
            src = dag["puts"].get(leaf["key"]) if leaf["key"] else None
            if src is not None and src[0] != tk:
                jump_t = min(src[1], t)
                if jump_t > lo + _EPS:
                    # Sender published late: the receiver's wait up to
                    # the publish is the SENDER's time — jump tracks.
                    charge(tk, "queue_wait", jump_t, t)
                    edges.append({
                        "kind": "msg", "key": leaf["key"],
                        "src": rank_of(src[0]), "dst": rank_of(tk),
                        "exposed_s": round(jump_t - max(leaf["t0"], w0), 6),
                        "t": round(jump_t, 6),
                    })
                    tk, t = src[0], jump_t
                    continue
            gate = None
            enclosing = [
                c for c in cover
                if c["cat"] == _CAT_COLLECTIVE and c["seq"] is not None
            ]
            if enclosing:
                c0 = enclosing[0]
                gate = dag["gates"].get(
                    (int(c0.get("group") or 0), c0["name"], int(c0["seq"]))
                )
            if src is None and gate is not None and gate[0] != tk:
                jump_t = min(gate[1], t)
                if jump_t > lo + _EPS:
                    # Keyless wait inside a gated collective: the last
                    # entrant is the straggler holding this rank.
                    charge(tk, "straggler_wait", jump_t, t,
                           suspect=rank_of(gate[0]))
                    edges.append({
                        "kind": "collective",
                        "key": f"{enclosing[0]['name']}"
                               f"#{enclosing[0]['seq']}",
                        "src": rank_of(gate[0]), "dst": rank_of(tk),
                        "exposed_s": round(jump_t - max(leaf["t0"], w0), 6),
                        "t": round(jump_t, 6),
                    })
                    tk, t = gate[0], jump_t
                    continue
            charge(tk, "queue_wait", lo, t)
            t = lo
            continue
        charge(tk, _CAT_TO_COMPONENT.get(leaf["cat"], "compute"), lo, t)
        t = lo
    return {
        "components": {c: round(v, 6) for c, v in comp.items()},
        "by_rank": {r: round(v, 6) for r, v in sorted(by_rank.items())},
        "suspects": {r: round(v, 6) for r, v in sorted(suspects.items())},
        "edges": sorted(
            edges, key=lambda e: e["exposed_s"], reverse=True
        )[:8],
        "path_s": round(sum(comp.values()), 6),
    }


def _dominant(step: dict) -> Tuple[str, Optional[int]]:
    """(dominator label, dominant rank) of one step record: the largest
    component — rendered ``wait:r<suspect>`` when stragglers dominate —
    plus the rank carrying the most critical-path time."""
    comp = step["components"]
    by_rank = step["by_rank"]
    if not by_rank or all(v <= 0.0 for v in comp.values()):
        return "", None
    dom_rank = max(by_rank, key=lambda r: by_rank[r])
    name = max(comp, key=lambda c: comp[c])
    if name == "straggler_wait" and step["suspects"]:
        sus = max(step["suspects"], key=lambda r: step["suspects"][r])
        return f"wait:r{sus}", int(dom_rank)
    return name, int(dom_rank)


def analyze_steps(
    tracks: Dict[int, dict], offsets: Optional[Dict[int, float]] = None
) -> List[dict]:
    """Per-step critical-path records over loaded tracks."""
    offsets = offsets if offsets is not None else estimate_offsets(tracks)
    dag = _aligned(tracks, offsets)
    out: List[dict] = []
    for i, (w0, w1, label) in enumerate(_step_windows(dag)):
        rec = _walk_window(dag, tracks, w0, w1)
        rec["step"] = i
        rec["label"] = label
        rec["t0"] = round(w0, 6)
        rec["t1"] = round(w1, 6)
        rec["total_s"] = round(w1 - w0, 6)
        dom, dom_rank = _dominant(rec)
        rec["dominant"] = dom
        rec["dominant_rank"] = dom_rank
        out.append(rec)
    return out


# ---------------------------------------------------------------------------
# Serving request flows (TTFT decomposition).
# ---------------------------------------------------------------------------

_PREFILL_NAMES = ("serve.prefill", "serve.prefill.local")
_SHIP_NAMES = ("kv.ship", "serve.ingest")


def _interval_union(iv: List[Tuple[float, float]]) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, e in sorted(iv):
        if e <= s:
            continue
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], e))
        else:
            out.append((s, e))
    return out


def _union_len(iv: List[Tuple[float, float]]) -> float:
    return sum(e - s for s, e in iv)


def analyze_requests(
    tracks: Dict[int, dict], offsets: Optional[Dict[int, float]] = None
) -> Dict[str, dict]:
    """Per-request TTFT decomposition from ``req``-tagged spans:
    ``admission`` (submit → first prefill start), ``prefill`` (prefill
    span union), ``ship`` (page-stream activity not hidden under
    prefill), ``decode`` (stream complete → first-token admission) and
    ``other`` (the remainder — stall/failover windows)."""
    offsets = offsets if offsets is not None else estimate_offsets(tracks)
    dag = _aligned(tracks, offsets)
    reqs: Dict[str, dict] = {}

    def ent(rid: str) -> dict:
        return reqs.setdefault(rid, {
            "submit": None, "admit": None, "prefill": [], "ship": [],
            "failovers": 0, "tracks": set(), "events": 0,
        })

    for tk, ii in dag["instants"].items():
        for i in ii:
            rid = i["req"]
            if rid is None:
                continue
            e = ent(str(rid))
            e["events"] += 1
            e["tracks"].add(tk)
            if i["name"] == "serve.submit":
                e["submit"] = (
                    i["t"] if e["submit"] is None else min(e["submit"], i["t"])
                )
            elif i["name"] == "serve.admit":
                e["admit"] = (
                    i["t"] if e["admit"] is None else min(e["admit"], i["t"])
                )
            elif i["name"] == "serve.failover":
                e["failovers"] += 1
            elif i["name"] == "kv.recv":
                e["ship"].append((i["t"], i["t"]))
    for tk, ss in dag["spans"].items():
        for s in ss:
            rid = s["req"]
            if rid is None:
                continue
            e = ent(str(rid))
            e["events"] += 1
            e["tracks"].add(tk)
            if s["name"] in _PREFILL_NAMES:
                e["prefill"].append((s["t0"], s["t1"]))
            elif s["name"] in _SHIP_NAMES:
                e["ship"].append((s["t0"], s["t1"]))
    out: Dict[str, dict] = {}
    for rid, e in sorted(reqs.items()):
        pf = _interval_union(e["prefill"])
        sh = _interval_union(e["ship"])
        submit, admit = e["submit"], e["admit"]
        p0 = pf[0][0] if pf else None
        stream_end = max(
            [iv[1] for iv in pf] + [iv[1] for iv in sh], default=None
        )
        comp = {
            "admission": 0.0, "prefill": 0.0, "ship": 0.0,
            "decode": 0.0, "other": 0.0,
        }
        comp["prefill"] = round(_union_len(pf), 6)
        # Exposed ship: page-stream activity not hidden under prefill.
        exposed = 0.0
        for s0, s1 in sh:
            covered = 0.0
            for q0, q1 in pf:
                covered += max(0.0, min(s1, q1) - max(s0, q0))
            exposed += max(0.0, (s1 - s0) - covered)
        comp["ship"] = round(exposed, 6)
        ttft = None
        if submit is not None and admit is not None:
            ttft = max(0.0, admit - submit)
            if p0 is not None:
                comp["admission"] = round(max(0.0, p0 - submit), 6)
            if stream_end is not None:
                comp["decode"] = round(max(0.0, admit - stream_end), 6)
            comp["other"] = round(max(
                0.0, ttft - sum(v for k, v in comp.items() if k != "other")
            ), 6)
        out[rid] = {
            "ttft_s": round(ttft, 6) if ttft is not None else None,
            "components": comp,
            "failovers": e["failovers"],
            "tracks": sorted(e["tracks"]),
            "events": e["events"],
        }
    return out


# ---------------------------------------------------------------------------
# The memoized directory entry point.
# ---------------------------------------------------------------------------

# Per-directory analysis memo keyed by the span files' stat signature —
# a changed/grown file can never serve a stale analysis; recovery
# reconfiguration clears it outright via invalidate_critpath_cache
# (reached from supervisor.invalidate_trace_caches).
_ANALYSIS_CACHE: "OrderedDict[Tuple[Any, ...], dict]" = OrderedDict()
_ANALYSIS_CACHE_MAX = 4


def _dir_signature(directory: str, cap: int) -> Tuple[Any, ...]:
    sig: List[Tuple[str, int, int]] = []
    for p in sorted(glob.glob(os.path.join(directory, "spans-rank*.jsonl"))):
        try:
            st = os.stat(p)
        except OSError:
            continue
        sig.append((os.path.basename(p), st.st_mtime_ns, st.st_size))
    return (os.path.abspath(directory), cap, tuple(sig))


def invalidate_critpath_cache(reason: str = "") -> None:
    """Drop the per-directory analysis memo (recovery reconfiguration:
    post-recovery spans are a new stream at a bumped generation — a
    cached DAG would attribute the fresh world against dead tracks)."""
    _ANALYSIS_CACHE.clear()
    metrics.add("cgx.critpath.cache_invalidations")


def analyze(
    directory: str,
    max_bytes_per_file: Optional[int] = None,
    use_cache: bool = True,
) -> dict:
    """The full report for one metrics dir: per-step critical paths,
    the dominator histogram, the slowest cross-rank edges, and the
    serving request decompositions. Memoized on the span files' stat
    signature."""
    cap = max_bytes_per_file or _max_read_bytes()
    key = _dir_signature(directory, cap) if use_cache else None
    if key is not None:
        hit = _ANALYSIS_CACHE.get(key)
        if hit is not None:
            _ANALYSIS_CACHE.move_to_end(key)
            metrics.add("cgx.critpath.cache_hits")
            return hit
    tracks = load_tracks(directory, cap)
    offsets = estimate_offsets(tracks)
    steps = analyze_steps(tracks, offsets)
    requests = analyze_requests(tracks, offsets)
    hist: Dict[str, int] = defaultdict(int)
    for s in steps:
        if s["dominant"]:
            hist[s["dominant"]] += 1
    edges = sorted(
        (e for s in steps for e in s["edges"]),
        key=lambda e: e["exposed_s"], reverse=True,
    )[:8]
    report = {
        "directory": os.path.abspath(directory),
        "tracks": [
            {
                "key": k, "rank": t["rank"], "generation": t["generation"],
                "events": len(t["events"]), "truncated": t["truncated"],
            }
            for k, t in sorted(tracks.items())
        ],
        "clock_offsets_s": {str(k): round(o, 6) for k, o in offsets.items()},
        "steps": steps,
        "dominators": dict(sorted(hist.items())),
        "edges": edges,
        "requests": requests,
    }
    metrics.add("cgx.critpath.analyses")
    metrics.set("cgx.critpath.steps", float(len(steps)))
    if steps:
        last = steps[-1]
        for c, v in last["components"].items():
            metrics.set(f"cgx.critpath.component.{c}", float(v))
        if last["dominant_rank"] is not None:
            metrics.set(
                "cgx.critpath.dominant_rank", float(last["dominant_rank"])
            )
    if key is not None:
        _ANALYSIS_CACHE[key] = report
        while len(_ANALYSIS_CACHE) > _ANALYSIS_CACHE_MAX:
            _ANALYSIS_CACHE.popitem(last=False)
    return report


def live_dominator(
    directory: str, max_bytes_per_file: int = 1 << 18
) -> str:
    """The last analyzed window's dominator label (``compute`` /
    ``wire`` / ``wait:r<rank>`` / "") over tail-bounded reads — the
    cheap form ``cgx_top``'s ``crit`` column polls."""
    try:
        tracks = load_tracks(directory, max_bytes_per_file)
        steps = analyze_steps(tracks)
    except Exception:
        return ""
    for s in reversed(steps):
        if s["dominant"]:
            return s["dominant"]
    return ""
