"""Typed metric instruments + the process-wide registry.

The seed's ``Metrics`` was one flat ``Dict[str, float]`` — fine for test
assertions, useless for operating a quantized-collective stack: a
compression ratio and a bridge-timeout tally need different shapes (a
distribution vs a monotonic count), and an exporter needs to know which
is which. This module upgrades the registry to three instrument types
while keeping the seed's ``add/set/get/snapshot/reset`` call sites
working unchanged:

* :class:`Counter` — monotonic accumulator (``metrics.add``). Fault
  tallies, wire bytes, step counts.
* :class:`Gauge` — last-write-wins level (``metrics.set``). Arena bytes
  in flight, current bits/bucket.
* :class:`Histogram` — streaming distribution with exact count/sum/
  min/max and quantile estimates from a bounded reservoir of the most
  recent samples (``metrics.observe``). Phase durations, queue waits,
  quantization error.

Deliberately dependency-free (stdlib only, no package-internal imports):
``utils.logging`` re-exports the singleton, so this module sits below
everything else in the import graph.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

# Reservoir depth per histogram: quantiles describe the *recent* window
# (the operationally interesting one — a 10-minute-old stall should not
# dilute this step's p99), exact count/sum/min/max cover all time.
RESERVOIR = 512

_QUANTILES = (0.5, 0.9, 0.99)


class Counter:
    """Monotonic accumulator."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def add(self, v: float) -> None:
        self.value += v


class Gauge:
    """Last-write-wins level."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = v


class Histogram:
    """Count/sum/min/max over all observations + quantiles over a bounded
    reservoir of the most recent :data:`RESERVOIR` samples."""

    __slots__ = ("count", "sum", "min", "max", "_recent")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._recent: deque = deque(maxlen=RESERVOIR)

    def observe(self, v: float) -> None:
        v = float(v)
        self.count += 1
        self.sum += v
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        self._recent.append(v)

    def quantile(self, q: float) -> float:
        """q-quantile of the recent reservoir (nearest-rank); 0.0 when
        empty."""
        if not self._recent:
            return 0.0
        s = sorted(self._recent)
        return s[min(int(q * len(s)), len(s) - 1)]

    def stats(self) -> Dict[str, float]:
        if self.count == 0:
            return {"count": 0, "sum": 0.0}
        out = {
            "count": float(self.count),
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.sum / self.count,
        }
        for q in _QUANTILES:
            out[f"p{int(q * 100)}"] = self.quantile(q)
        return out


class Metrics:
    """Process-wide instrument registry (thread-safe).

    Backward compatible with the seed's flat-counter API: ``add`` feeds a
    :class:`Counter`, ``set`` a :class:`Gauge`, the new ``observe`` a
    :class:`Histogram`; ``get``/``snapshot`` read all three (histograms
    flatten to ``<name>.count/.sum/.mean/.min/.max/.p50/.p90/.p99``).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter()
            c.add(value)

    def set(self, name: str, value: float) -> None:
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge()
            g.set(value)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram()
            h.observe(value)

    def get(self, name: str) -> float:
        """Counter/gauge value; for a histogram, its observation count;
        0.0 for an unknown name (seed semantics)."""
        with self._lock:
            c = self._counters.get(name)
            if c is not None:
                return c.value
            g = self._gauges.get(name)
            if g is not None:
                return g.value
            h = self._histograms.get(name)
            if h is not None:
                return float(h.count)
            return 0.0

    def histogram_stats(self, name: str) -> Optional[Dict[str, float]]:
        with self._lock:
            h = self._histograms.get(name)
            return h.stats() if h is not None else None

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """Flat view of every instrument, optionally filtered by name
        prefix — e.g. ``metrics.snapshot("cgx.faults.")`` for the
        fault-injection tally. Histograms flatten to dotted stat keys so
        existing dict consumers keep working."""
        with self._lock:
            out: Dict[str, float] = {
                k: c.value for k, c in self._counters.items()
            }
            out.update({k: g.value for k, g in self._gauges.items()})
            for k, h in self._histograms.items():
                for stat, v in h.stats().items():
                    out[f"{k}.{stat}"] = v
        if not prefix:
            return out
        return {k: v for k, v in out.items() if k.startswith(prefix)}

    def snapshot_typed(self) -> Dict[str, Dict]:
        """Structured view for the exporter/aggregator: instruments kept
        by type so a merge can sum counters but combine histograms by
        component (count/sum/min/max are mergeable; quantiles are not)."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "histograms": {
                    k: h.stats() for k, h in self._histograms.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


metrics = Metrics()
