"""Observability subsystem: typed instruments, flight recorder, exporter.

Three layers (see ``docs/OBSERVABILITY.md``):

* :mod:`.instruments` — counters/gauges/histograms behind the process
  registry (``torch_cgx_tpu.utils.logging.metrics`` is the same object;
  the seed's flat-counter API still works).
* :mod:`.flightrec` — per-rank bounded ring of structured events, dumped
  to ``CGX_METRICS_DIR/flightrec-rank<N>.jsonl`` on data-plane failures,
  guard trips, shutdown, and on demand.
* :mod:`.exporter` — periodic per-rank JSONL snapshots
  (``CGX_METRICS_FLUSH_S``) plus a leader-side cross-rank merge riding
  the group's store control plane.
* :mod:`.timeline` — structured span layer: per-rank span JSONL
  (``spans-rank<N>.jsonl``) with monotonic clocks and collective
  seq/key correlation, merged by ``tools/cgx_trace.py`` into a Chrome
  trace-event file with cross-rank flow arrows.
* :mod:`.health` — streaming per-rank health engine: online EWMA/P²
  estimators over the instruments, straggler scoring from
  collective-phase skew, typed ``HealthEvent`` publication to the
  recovery supervisor and the files ``cgx_top`` renders (CGX_HEALTH).
* :mod:`.watch` — health-plane consumers: Prometheus text exposition
  endpoint (CGX_PROM_PORT) and the leader-side cluster health merge.

``instruments`` is imported eagerly (``utils.logging`` depends on it);
``flightrec``/``exporter`` load lazily so this package root stays
importable from anywhere in the import graph without cycles.
"""

from __future__ import annotations

from . import instruments
from .instruments import Counter, Gauge, Histogram, Metrics, metrics

_LAZY = ("flightrec", "exporter", "timeline", "health", "watch")


def __getattr__(name: str):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(f".{name}", __name__)
        globals()[name] = mod
        return mod
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "instruments",
    "flightrec",
    "exporter",
    "timeline",
    "health",
    "watch",
    "Counter",
    "Gauge",
    "Histogram",
    "Metrics",
    "metrics",
]
