"""Health-plane consumers: Prometheus exposition + cluster health merge.

Two ways the live health state (:mod:`.health`) and the instrument
registry leave the process *while the job runs* — the post-hoc JSONL
exporter's (:mod:`.exporter`) online siblings:

* :class:`PromServer` — a stdlib ``http.server`` thread serving
  Prometheus **text exposition format** on
  ``127.0.0.1:$CGX_PROM_PORT/metrics``: every counter/gauge/histogram in
  the registry (histograms as summaries with p50/p90/p99 quantile
  samples) plus the health engine's straggler scores and step estimates
  as gauges. Port 0 binds an ephemeral port; the bound port is published
  to ``CGX_METRICS_DIR/prom-rank<N>.json`` so a scraper (or the chaos
  suite) can find it without races.
* :func:`aggregate_health_over_store` — the leader-side cluster health
  view, riding the same store control plane (and bounded-get helper) as
  the exporter's metrics merge: every rank publishes its health status,
  rank 0 merges what arrives within the deadline into one line of
  ``CGX_METRICS_DIR/cluster-health.jsonl`` (max straggler score across
  the fleet, per-rank step estimates, ranks missing).

Both are inert unless their knob is set (``CGX_PROM_PORT`` /
``CGX_HEALTH``): with everything unset no socket is bound, no thread
runs, and nothing changes on the clean path.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from typing import Any, Dict, List, Optional

from .. import config as cfg
from ..utils.logging import get_logger
from . import health as health_mod
from .instruments import metrics

log = get_logger()

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Registry name -> Prometheus metric name (``cgx.sra.wire_bytes_out``
    -> ``cgx_sra_wire_bytes_out``; leading digits guarded)."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _fmt_value(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f)


def render_prometheus(
    snapshot: Optional[Dict[str, Dict]] = None,
    status: Optional[Dict[str, Any]] = None,
    rank: int = 0,
) -> str:
    """Text exposition (version 0.0.4) of a typed registry snapshot plus
    an optional health status dict. Pure function — unit-testable without
    a socket."""
    snap = snapshot if snapshot is not None else metrics.snapshot_typed()
    lines: List[str] = []
    for name, v in sorted(snap.get("counters", {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {_fmt_value(v)}")
    for name, v in sorted(snap.get("gauges", {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {_fmt_value(v)}")
    for name, h in sorted(snap.get("histograms", {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} summary")
        for q in ("p50", "p90", "p99"):
            if q in h:
                lines.append(
                    f'{pn}{{quantile="0.{q[1:]}"}} {_fmt_value(h[q])}'
                )
        lines.append(f"{pn}_sum {_fmt_value(h.get('sum', 0.0))}")
        lines.append(f"{pn}_count {_fmt_value(h.get('count', 0.0))}")
    if status is None:
        eng = health_mod.get_engine()
        status = eng.status() if eng is not None else None
    if status:
        lines.append("# TYPE cgx_health_straggler_score gauge")
        for peer, score in sorted(
            (status.get("straggler_scores") or {}).items()
        ):
            lines.append(
                f'cgx_health_straggler_score{{peer="{peer}"}} '
                f"{_fmt_value(score)}"
            )
        step = status.get("step") or {}
        for k in ("ewma_fast_s", "ewma_slow_s", "p50_s", "p99_s"):
            if k in step:
                lines.append(f"# TYPE cgx_health_step_{k} gauge")
                lines.append(
                    f"cgx_health_step_{k} {_fmt_value(step[k])}"
                )
    lines.append("# TYPE cgx_up gauge")
    lines.append(f'cgx_up{{rank="{rank}"}} 1.0')
    return "\n".join(lines) + "\n"


class PromServer:
    """Per-process Prometheus endpoint (use :func:`maybe_start_prom`)."""

    def __init__(self, port: int, rank: int = 0):
        self.rank = rank
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port
        self.port: Optional[int] = None

    def start(self) -> "PromServer":
        import http.server

        rank = self.rank

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server contract
                if self.path not in ("/", "/metrics", "/healthz"):
                    self.send_error(404)
                    return
                if self.path == "/healthz":
                    eng = health_mod.get_engine()
                    body = json.dumps(
                        eng.status() if eng is not None
                        else {"rank": rank, "health_engine": "off"}
                    ).encode()
                    ctype = "application/json"
                else:
                    metrics.add("cgx.health.prom_scrapes")
                    body = render_prometheus(rank=rank).encode()
                    ctype = "text/plain; version=0.0.4; charset=utf-8"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # quiet: no stderr per scrape
                pass

        self._httpd = http.server.ThreadingHTTPServer(
            ("127.0.0.1", self._requested_port), Handler
        )
        self._httpd.daemon_threads = True
        self.port = int(self._httpd.server_address[1])
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="cgx-prom",
            daemon=True,
        )
        self._thread.start()
        self._publish_port()
        log.info(
            "cgx: Prometheus exposition on http://127.0.0.1:%d/metrics",
            self.port,
        )
        return self

    def _publish_port(self) -> None:
        """Drop the bound port where scrapers/tests can find it (matters
        for port 0 — the ephemeral-bind mode CI uses to avoid
        collisions)."""
        d = cfg.metrics_dir()
        if not d:
            return
        try:
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"prom-rank{self.rank}.json")
            tmp = f"{path}.tmp{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(
                    {"port": self.port, "pid": os.getpid(),
                     "rank": self.rank}, f,
                )
            os.replace(tmp, path)
        except OSError as e:
            log.warning("prom port publish failed: %s", e)

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        self._thread = None


_prom: Optional[PromServer] = None
_prom_lock = threading.Lock()


def maybe_start_prom(rank: int = 0) -> Optional[PromServer]:
    """Start (idempotently) the process Prometheus endpoint iff
    ``CGX_PROM_PORT`` is set. Bind failures degrade to a warning — an
    occupied port must not take down training."""
    port = cfg.prom_port()
    if port is None:
        return None
    global _prom
    with _prom_lock:
        if _prom is None:
            try:
                _prom = PromServer(port, rank).start()
            except OSError as e:
                log.warning(
                    "cgx: Prometheus endpoint bind on port %d failed: %s",
                    port, e,
                )
                return None
        return _prom


def stop_prom() -> None:
    global _prom
    with _prom_lock:
        srv, _prom = _prom, None
    if srv is not None:
        srv.stop()


# ---------------------------------------------------------------------------
# Leader-side cluster health view (the exporter merge's online sibling).
# ---------------------------------------------------------------------------

_HEALTH_PREFIX = "cgxhealth/agg"


def aggregate_health_over_store(
    store,
    rank: int,
    world_size: int,
    round_id: int = 0,
    timeout_s: float = 3.0,
) -> Optional[Dict]:
    """Merge every rank's health status into one cluster view on the
    leader (same contract as ``exporter.aggregate_over_store``: bounded
    deadline, missing ranks named, never raises). Returns the merged
    view on rank 0 — also appended to
    ``CGX_METRICS_DIR/cluster-health.jsonl`` when set — None elsewhere
    or when this rank's engine is not running."""
    from .exporter import _bounded_store_get

    eng = health_mod.get_engine()
    if eng is None:
        return None
    try:
        key = f"{_HEALTH_PREFIX}/{round_id}/r{rank}"
        store.set(key, json.dumps(eng.status()).encode())
    except Exception as e:
        log.warning("health aggregation publish failed: %s", e)
        return None
    if rank != 0:
        return None
    per_rank: Dict[int, Dict] = {}
    missing: List[int] = []
    deadline = time.monotonic() + timeout_s
    for r in range(world_size):
        raw = _bounded_store_get(
            store, f"{_HEALTH_PREFIX}/{round_id}/r{r}", deadline
        )
        if raw is None:
            missing.append(r)
            continue
        try:
            per_rank[r] = json.loads(bytes(raw).decode())
        except (ValueError, UnicodeDecodeError):
            missing.append(r)
    worst: Optional[Dict[str, Any]] = None
    for r, st in per_rank.items():
        for peer, score in (st.get("straggler_scores") or {}).items():
            if worst is None or score > worst["score"]:
                worst = {"score": score, "suspect": int(peer),
                         "reported_by": r}
    view = {
        "ts": round(time.time(), 6),
        "round": round_id,
        "world_size": world_size,
        "ranks_reporting": sorted(per_rank),
        "missing_ranks": missing,
        "worst_straggler": worst,
        "events": sum(
            len(st.get("events_recent") or ()) for st in per_rank.values()
        ),
        "step_per_rank": {
            r: st.get("step", {}) for r, st in per_rank.items()
        },
    }
    directory = cfg.metrics_dir()
    if directory:
        try:
            os.makedirs(directory, exist_ok=True)
            with open(
                os.path.join(directory, "cluster-health.jsonl"), "a"
            ) as f:
                f.write(json.dumps(view) + "\n")
        except OSError as e:
            log.warning("cluster health write failed: %s", e)
    return view


_MEM_PREFIX = "cgxmem/agg"


def aggregate_mem_over_store(
    store,
    rank: int,
    world_size: int,
    round_id: int = 0,
    timeout_s: float = 3.0,
) -> Optional[Dict]:
    """Merge every rank's memory-ledger snapshot into one cluster view
    on the leader (same contract as :func:`aggregate_health_over_store`:
    bounded deadline, missing ranks named, never raises). Returns the
    merged view on rank 0 — also appended to
    ``CGX_METRICS_DIR/cluster-mem.jsonl`` when set — None elsewhere or
    when this rank's ledger is not running."""
    from .exporter import _bounded_store_get
    from . import memledger as memledger_mod

    led = memledger_mod.get_ledger()
    if led is None:
        return None
    try:
        snap = led.last_snapshot() or led.sample()
        key = f"{_MEM_PREFIX}/{round_id}/r{rank}"
        store.set(key, json.dumps(snap).encode())
    except Exception as e:
        log.warning("mem aggregation publish failed: %s", e)
        return None
    if rank != 0:
        return None
    per_rank: Dict[int, Dict] = {}
    missing: List[int] = []
    deadline = time.monotonic() + timeout_s
    for r in range(world_size):
        raw = _bounded_store_get(
            store, f"{_MEM_PREFIX}/{round_id}/r{r}", deadline
        )
        if raw is None:
            missing.append(r)
            continue
        try:
            per_rank[r] = json.loads(bytes(raw).decode())
        except (ValueError, UnicodeDecodeError):
            missing.append(r)
    # Worst pool by forecast: the rank/pool closest to its wall is the
    # cluster's memory story in one line.
    worst: Optional[Dict[str, Any]] = None
    for r, snap_r in per_rank.items():
        for row in snap_r.get("pools") or ():
            tte = row.get("tte_s")
            if tte is not None and (worst is None or tte < worst["tte_s"]):
                worst = {"tte_s": tte, "pool": row.get("pool"), "rank": r}
    view = {
        "ts": round(time.time(), 6),
        "round": round_id,
        "world_size": world_size,
        "ranks_reporting": sorted(per_rank),
        "missing_ranks": missing,
        "total_mb": round(
            sum(s.get("total_mb") or 0.0 for s in per_rank.values()), 3
        ),
        "peak_mb_max": max(
            (s.get("peak_mb") or 0.0 for s in per_rank.values()),
            default=0.0,
        ),
        "nearest_exhaustion": worst,
        "leak_suspects": sorted({
            owner
            for s in per_rank.values()
            for f in s.get("findings") or ()
            if f.get("kind") == "mem_leak"
            for owner in (f.get("owner"),)
            if owner
        }),
        "per_rank_total_mb": {
            r: s.get("total_mb") for r, s in per_rank.items()
        },
    }
    directory = cfg.metrics_dir()
    if directory:
        try:
            os.makedirs(directory, exist_ok=True)
            with open(
                os.path.join(directory, "cluster-mem.jsonl"), "a"
            ) as f:
                f.write(json.dumps(view) + "\n")
        except OSError as e:
            log.warning("cluster mem write failed: %s", e)
    return view
