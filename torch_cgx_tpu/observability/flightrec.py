"""Per-rank flight recorder: a bounded ring of structured events.

When a ``BridgeTimeoutError`` fires today, the evidence — which
collective, how many bytes, what bits/bucket, how long each phase took —
dies with the process; the exception message is all that survives. The
flight recorder keeps the last ``CGX_FLIGHTREC_CAP`` (default 512)
events in memory at near-zero cost and writes them to
``CGX_METRICS_DIR/flightrec-rank<N>.jsonl`` when it matters:

* automatically, on a :class:`~..robustness.errors.BridgeTimeoutError`,
  :class:`~..robustness.errors.WireCorruptionError`, or a non-finite
  guard trip (the instrumented raise sites call :func:`record_failure`),
* on ``ProcessGroup.shutdown()``,
* on demand (:func:`dump`).

Each dump atomically rewrites the rank's file with the full current ring
(tmp + rename — a reader, human or ``tools/cgx_report.py``, never sees a
torn file). With ``CGX_METRICS_DIR`` unset, recording still happens (the
ring is cheap and an explicit ``dump(path=...)`` can target anywhere)
but automatic dumps are no-ops — the clean path touches no filesystem.

Events are plain dicts: ``{"ts", "seq", "kind", ...caller fields}``.
Kinds in use: ``collective`` (op/seq/bytes/algo), ``shm_put``/
``shm_take`` (bytes, wait/copy seconds), ``failure`` (error type +
context), ``nonfinite_guard``, ``heartbeat_suspect``, ``qerr``
(per-layer relative-L2 quantization error), ``dump`` (the header line).
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

from .. import config as cfg
from ..utils.logging import get_logger, metrics

log = get_logger()


class FlightRecorder:
    """Bounded, thread-safe event ring for one rank."""

    def __init__(self, rank: Optional[int] = None, capacity: Optional[int] = None):
        self.rank = rank
        self._events: deque = deque(
            maxlen=capacity if capacity is not None else cfg.flightrec_cap()
        )
        self._seq = 0
        self._lock = threading.Lock()
        # Serializes dump(): a p2p-pool failure dump racing a worker-loop
        # dump would share the same tmp path (same pid) and publish a
        # torn file — exactly the evidence loss the atomic rename exists
        # to prevent.
        self._dump_lock = threading.Lock()

    def record(self, kind: str, **fields: Any) -> None:
        # Both clocks: ``ts`` (wall) for humans, ``t_mono``
        # (perf_counter) so the cross-rank merger can align ranks
        # without trusting wall clocks (tools/cgx_trace.py).
        ev = {
            "ts": round(time.time(), 6),
            "t_mono": round(time.perf_counter(), 6),
            "kind": kind,
        }
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)

    def events(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._events)

    def _effective_rank(self) -> int:
        """Rank for the dump filename. The torch bridge binds it
        explicitly (set_rank); JAX-only multi-process runs never do, so
        fall back to ``jax.process_index()`` when jax is already loaded —
        otherwise N processes sharing one CGX_METRICS_DIR would all
        clobber ``flightrec-rank0.jsonl``. Never imports jax itself."""
        if self.rank is not None:
            return self.rank
        import sys

        jax_mod = sys.modules.get("jax")
        if jax_mod is not None:
            try:
                self.rank = int(jax_mod.process_index())
                return self.rank
            except Exception:
                pass
        return 0

    def dump(
        self, reason: str = "on_demand", path: Optional[str] = None
    ) -> Optional[str]:
        """Write the ring as JSONL (header line first, then events oldest
        to newest). Returns the path written, or None when no target
        exists (``path`` not given and ``CGX_METRICS_DIR`` unset). Never
        raises: a dump runs on failure paths where a second exception
        would mask the first."""
        if path is None:
            d = cfg.metrics_dir()
            if not d:
                return None
            path = os.path.join(
                d, f"flightrec-rank{self._effective_rank()}.jsonl"
            )
        try:
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            with self._lock:
                events = list(self._events)
                seq = self._seq
            with self._dump_lock:
                return self._write_dump(path, reason, events, seq)
        except Exception as e:  # a dump must never mask the real failure
            log.warning("flight recorder dump failed: %s", e)
            return None

    def _write_dump(self, path, reason, events, seq) -> str:
        header = {
            "ts": round(time.time(), 6),
            "t_mono": round(time.perf_counter(), 6),
            "kind": "dump",
            "reason": reason,
            "rank": self._effective_rank(),
            "pid": os.getpid(),
            "events": len(events),
            "events_total": seq,
            "metrics": metrics.snapshot("cgx."),
        }
        tmp = f"{path}.tmp{os.getpid()}.{threading.get_ident()}"
        with open(tmp, "w") as f:
            f.write(json.dumps(header) + "\n")
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        os.replace(tmp, path)
        metrics.add("cgx.flightrec.dumps")
        return path


_recorder: Optional[FlightRecorder] = None
_recorder_lock = threading.Lock()


def get_recorder() -> FlightRecorder:
    """The process's recorder (created on first use, rank unset)."""
    global _recorder
    with _recorder_lock:
        if _recorder is None:
            _recorder = FlightRecorder()
        return _recorder


def set_rank(rank: int) -> FlightRecorder:
    """Explicitly bind the process recorder to a rank (overrides any
    previous binding)."""
    rec = get_recorder()
    rec.rank = rank
    return rec


def bind_rank(rank: int) -> FlightRecorder:
    """First-wins rank binding for implicit callers
    (``ProcessGroupCGX.__init__``, ``ShmChannel``): the first group a
    process constructs is the default/global one, whose rank is the
    process-global rank — a later ``dist.new_group`` subgroup passes its
    GROUP-LOCAL rank, and rebinding to that would make two processes
    dump to (and clobber) the same ``flightrec-rank<N>.jsonl``."""
    rec = get_recorder()
    if rec.rank is None:
        rec.rank = rank
    return rec


def record(kind: str, **fields: Any) -> None:
    get_recorder().record(kind, **fields)


def dump(reason: str = "on_demand", path: Optional[str] = None) -> Optional[str]:
    return get_recorder().dump(reason, path)


def record_failure(exc: BaseException, **fields: Any) -> None:
    """Record a failure event and dump the ring — the black-box write the
    recorder exists for. Call at (or just before) a raise site."""
    rec = get_recorder()
    rec.record(
        "failure",
        error=type(exc).__name__,
        message=str(exc),
        **fields,
    )
    rec.dump(reason=type(exc).__name__)


def reset() -> None:
    """Drop the process recorder (tests: fresh ring + seq per case)."""
    global _recorder
    with _recorder_lock:
        _recorder = None
