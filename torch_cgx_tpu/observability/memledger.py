"""Per-rank memory ledger: one byte accountant over every owning surface.

The repo's byte accounting was scattered one-off gauges — the KV pool
set ``cgx.serve.pool_free`` inside its own mutators, arena pressure left
a counter trend, the staged-program caches reported nothing — so nobody
could answer "where do the bytes live right now", let alone "when do we
hit the wall". This module is the unified answer, in the spirit of
GC3's buffer-footprint-as-compiler-input (arxiv 2201.11840): memory is
a first-class *planned* quantity, not a post-mortem surprise.

One :class:`MemLedger` per process (module singleton, same zero-cost
shim discipline as :mod:`.health`) tracks two complementary truths:

* **Site deltas** (push): instrumented alloc/release sites call
  :func:`note_alloc` / :func:`note_release` with a stable *owner label*
  (``shm.arena``, ``serve.kv_pool``, ...). The sliding-window leak
  detector watches each owner's alloc−release delta: strictly monotone
  growth across the full ``CGX_MEM_LEAK_WINDOW`` samples names the
  owner in a ``mem_leak`` HealthEvent — the classic slow leak caught by
  its *shape*, not by exhaustion. The analyzer's ``mem-ledger-pairing``
  pass proves every alloc site has a reachable release/reset partner.
* **Pool occupancy** (pull): every sample tick the ledger discovers the
  live byte-owning surfaces through weak liveness sets and
  ``sys.modules`` probes — shm arena rings (occupancy + fragmentation =
  1 − largest-free-extent / total-free), paged KV pools (occupancy +
  fork-dedup savings), supervisor snapshot rings, the five
  staged-program caches (per-entry footprint estimated from buffer
  shapes), and ``jax.live_arrays()`` as the HBM cross-check when jax is
  already in the process. Pull means registration order can't be wrong:
  a pool created before the ledger starts is still found.

On top of occupancy sits the **OOM forecaster**: a least-squares linear
trend over each bounded pool's free-level history extrapolates
time-to-exhaustion; a pool forecast to exhaust within the lead window
(``CGX_MEM_LEAK_WINDOW × CGX_MEM_FLUSH_S`` seconds) raises
``mem_pressure`` *before* the hard wall so admission/supervision can
shed load while there is still headroom to act. The planner consumes
the same idea at solve time through ``CostModel.memory_envelope()``.

Surfaces: ``cgx.mem.*`` gauges (Prometheus via watch), periodic
``mem-rank<N>.jsonl`` snapshots (merged leader-side like
cluster-health by ``watch.aggregate_mem_over_store``), the
``tools/cgx_mem.py`` CLI, cgx_top's mem/frag columns, and a
``cgx_report == memory ==`` section.

Inert by default: ``CGX_MEMLEDGER`` unset means :func:`maybe_start`
returns None, every hot-path hook is a single global load, the planner
keeps its staging-budget filter out of the plan key, and staged
programs / store keys / wire bytes are bit-identical to the ledger not
existing. All ledger state is reset-reachable from
``supervisor.invalidate_trace_caches`` (the recovery cascade calls
:func:`reset_ledger`).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from .. import config as cfg
from ..utils.logging import get_logger
from . import flightrec
from .instruments import metrics

log = get_logger()

# Slope quieter than this (units/second) is flat, not a trend — free
# levels dithering by rounding noise must not forecast an exhaustion.
_SLOPE_EPS = 1e-9

# Deep-size walk guards: the estimator is a bounded *estimate*, never a
# full heap traversal (a pathological cache entry must cost microseconds,
# not a GC pause).
_SIZE_DEPTH = 5
_SIZE_MAX_ITEMS = 4096

# The five staged-program caches (mirrors tools/analysis/knobs.py's
# default_surfaces; the train-step build cache is closure-held and
# covered by the jax.live_arrays cross-check instead).
_CACHE_SURFACES: Tuple[Tuple[str, str, str], ...] = (
    ("cache.layout", "torch_cgx_tpu.parallel.allreduce", "_LAYOUT_CACHE"),
    ("cache.schedule", "torch_cgx_tpu.parallel.schedule", "_SCHED_CACHE"),
    ("cache.plan", "torch_cgx_tpu.parallel.planner", "_PLAN_CACHE"),
    (
        "cache.xla_program",
        "torch_cgx_tpu.parallel.xla_allreduce",
        "_PROGRAM_CACHE",
    ),
    (
        "cache.serve_program",
        "torch_cgx_tpu.serving.scheduler",
        "_PROGRAM_CACHE",
    ),
)


def deep_nbytes(obj: Any, depth: int = _SIZE_DEPTH) -> int:
    """Bounded byte-footprint estimate of a cache entry / snapshot.

    Leaves with ``.nbytes`` (numpy/jax arrays, torch tensors expose it
    too) report themselves; objects with ``.shape``+``.dtype`` but no
    nbytes are computed from the product; containers recurse
    depth-limited with an identity seen-set. Everything else counts 0 —
    an under-estimate by design (Python object overhead is noise next
    to the buffers this ledger exists to find)."""
    seen: set = set()
    budget = [_SIZE_MAX_ITEMS]

    def walk(o: Any, d: int) -> int:
        if d < 0 or budget[0] <= 0:
            return 0
        budget[0] -= 1
        oid = id(o)
        if oid in seen:
            return 0
        seen.add(oid)
        nb = getattr(o, "nbytes", None)
        if isinstance(nb, int) and nb >= 0:
            return nb
        shape = getattr(o, "shape", None)
        dtype = getattr(o, "dtype", None)
        if shape is not None and dtype is not None:
            try:
                n = 1
                for dim in shape:
                    n *= int(dim)
                return n * int(getattr(dtype, "itemsize", 0) or 0)
            except (TypeError, ValueError):
                return 0
        if isinstance(o, dict):
            return sum(walk(v, d - 1) for v in list(o.values()))
        if isinstance(o, (list, tuple, set, frozenset)):
            return sum(walk(v, d - 1) for v in list(o))
        inner = getattr(o, "__dict__", None)
        if isinstance(inner, dict):
            return sum(walk(v, d - 1) for v in list(inner.values()))
        return 0

    try:
        return walk(obj, depth)
    except (RuntimeError, ReferenceError):
        # A container mutated mid-walk (the ledger samples live state
        # without the owners' locks — by contract, see sample()).
        return 0


def _trend_tte_s(hist: "deque") -> Optional[float]:
    """Least-squares time-to-exhaustion over a (t_s, free_units)
    history. None = no downward trend (flat, rising, or under 3 points
    — two points cannot distinguish a trend from noise). 0.0 = already
    exhausted. The math the docs chapter states: slope b from the
    normal equations, tte = −free_now / b for b < 0."""
    if len(hist) < 3:
        return None
    t0 = hist[0][0]
    xs = [t - t0 for t, _ in hist]
    ys = [f for _, f in hist]
    n = float(len(xs))
    sx = sum(xs)
    sy = sum(ys)
    sxx = sum(x * x for x in xs)
    sxy = sum(x * y for x, y in zip(xs, ys))
    denom = n * sxx - sx * sx
    if denom <= 0:
        return None
    slope = (n * sxy - sx * sy) / denom
    if slope >= -_SLOPE_EPS:
        return None
    free_now = ys[-1]
    if free_now <= 0:
        return 0.0
    return free_now / -slope


# ---------------------------------------------------------------------------
# Pull-model pool samplers (sys.modules probes: the ledger never imports
# a data plane — a serving-only process must not pay for the training
# stack, and vice versa).
# ---------------------------------------------------------------------------


def _arena_rows() -> List[Dict[str, Any]]:
    shm = sys.modules.get("torch_cgx_tpu.torch_backend.shm")
    if shm is None:
        return []
    rows: List[Dict[str, Any]] = []
    for arena in list(getattr(shm, "_LIVE_ARENAS", ())):
        try:
            st = arena.mem_stats()
        except (RuntimeError, OSError):
            continue  # an arena mid-close is not a sample worth fighting
        rows.append({
            "pool": f"shm.arena.{st['name']}",
            "kind": "arena",
            "used_bytes": int(st["live_bytes"]),
            "capacity_bytes": int(st["cap_bytes"]),
            "free_units": float(st["cap_bytes"] - st["live_bytes"]),
            "capacity_units": float(st["cap_bytes"]),
            "frag": float(st["frag"]),
            "detail": {
                "gens": st["gens"],
                "mapped_bytes": st["capacity_bytes"],
                "largest_free_bytes": st["largest_free_bytes"],
                "pending_regions": st["pending_regions"],
            },
        })
    return rows


def _kv_rows() -> List[Dict[str, Any]]:
    kv = sys.modules.get("torch_cgx_tpu.serving.kv_cache")
    if kv is None:
        return []
    rows: List[Dict[str, Any]] = []
    for i, cache in enumerate(list(getattr(kv, "_LIVE", ()))):
        try:
            # publish_pool_gauges IS the satellite fix: the ledger tick
            # refreshes cgx.serve.pool_free/pool_dedup_pages between
            # decode steps, so scrapes see live truth, not the value as
            # of the last mutator.
            st = cache.publish_pool_gauges()
        except (RuntimeError, ReferenceError):
            continue
        used = st["max_pages"] - st["free_pages"]
        rows.append({
            "pool": "serve.kv_pool" if i == 0 else f"serve.kv_pool.{i}",
            "kind": "kv_pool",
            "used_bytes": 0,  # byte size lives with the device pool arrays
            "capacity_bytes": 0,
            "free_units": float(st["free_pages"]),
            "capacity_units": float(st["max_pages"]),
            "frag": None,
            "detail": {
                "live_pages": st["live_pages"],
                "dedup_pages": st["dedup_pages"],
                "leaked_pages": st["leaked_pages"],
                "seqs": st["seqs"],
                "page_tokens": st["page_tokens"],
            },
        })
    return rows


def _snapshot_rows() -> List[Dict[str, Any]]:
    sup = sys.modules.get("torch_cgx_tpu.robustness.supervisor")
    if sup is None:
        return []
    rows: List[Dict[str, Any]] = []
    for i, s in enumerate(list(getattr(sup, "_LIVE_SUPERVISORS", ()))):
        snaps = getattr(s, "_snapshots", None)
        if not isinstance(snaps, dict):
            continue
        try:
            items = list(snaps.items())
        except RuntimeError:
            continue  # resized mid-copy; next tick sees it
        rows.append({
            "pool": "snap.ring" if i == 0 else f"snap.ring.{i}",
            "kind": "snap_ring",
            "used_bytes": sum(deep_nbytes(v) for _, v in items),
            "capacity_bytes": 0,
            "free_units": 0.0,
            "capacity_units": 0.0,
            "frag": None,
            "detail": {"snapshots": len(items),
                       "steps": sorted(k for k, _ in items)[-4:]},
        })
    return rows


def _cache_rows() -> List[Dict[str, Any]]:
    rows: List[Dict[str, Any]] = []
    for pool, modname, attr in _CACHE_SURFACES:
        mod = sys.modules.get(modname)
        if mod is None:
            continue
        cache = getattr(mod, attr, None)
        if not isinstance(cache, dict):
            continue
        try:
            values = list(cache.values())
        except RuntimeError:
            continue
        rows.append({
            "pool": pool,
            "kind": "staged_cache",
            "used_bytes": sum(deep_nbytes(v) for v in values),
            "capacity_bytes": 0,
            "free_units": 0.0,
            "capacity_units": 0.0,
            "frag": None,
            "detail": {"entries": len(values)},
        })
    return rows


def _jax_rows() -> List[Dict[str, Any]]:
    """HBM cross-check: total live jax array bytes, when jax is already
    imported (the ledger itself must never pull the jax runtime in)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return []
    live = getattr(jax, "live_arrays", None)
    if not callable(live):
        return []
    try:
        arrays = live()
        total = sum(int(getattr(a, "nbytes", 0) or 0) for a in arrays)
        count = len(arrays)
    except (RuntimeError, TypeError, ValueError):
        return []
    return [{
        "pool": "hbm.jax_live",
        "kind": "hbm",
        "used_bytes": total,
        "capacity_bytes": 0,
        "free_units": 0.0,
        "capacity_units": 0.0,
        "frag": None,
        "detail": {"arrays": count},
    }]


_BUILTIN_SAMPLERS: Tuple[Callable[[], List[Dict[str, Any]]], ...] = (
    _arena_rows, _kv_rows, _snapshot_rows, _cache_rows, _jax_rows,
)


class MemLedger:
    """One rank's byte ledger (use :func:`maybe_start`).

    Lock discipline: instrumented sites call :meth:`register_alloc` /
    :meth:`register_release` possibly while holding their OWN pool lock
    (arena lock, KV lock), so those take only the ledger lock — and the
    sampler collects pool rows (which take pool locks) with the ledger
    lock NOT held. The only order that ever forms is
    pool-lock → ledger-lock; the reverse edge does not exist."""

    def __init__(
        self,
        rank: int = 0,
        flush_s: Optional[float] = None,
        leak_window: Optional[int] = None,
    ):
        self.rank = int(rank)
        self._flush_s = float(flush_s if flush_s else cfg.mem_flush_s())
        self._window = int(leak_window if leak_window else cfg.mem_leak_window())
        self._lock = threading.Lock()
        # owner -> [allocs, releases, bytes_alloc, bytes_release]
        self._sites: Dict[str, List[float]] = {}
        # owner -> outstanding-count history (one point per sample)
        self._site_hist: Dict[str, "deque"] = {}
        # pool -> (t_mono, free_units) history for the forecaster
        self._pool_hist: Dict[str, "deque"] = {}
        # extra sampler callbacks: fn() -> list of pool rows
        self._samplers: List[Callable[[], List[Dict[str, Any]]]] = []
        self._cool: Dict[Tuple[str, str], float] = {}
        self._leaking: set = set()
        self.peak_bytes = 0
        self._last_snapshot: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- registration API --------------------------------------------------

    def register_alloc(self, owner: str, n: int = 1, nbytes: int = 0) -> None:
        with self._lock:
            s = self._sites.setdefault(owner, [0.0, 0.0, 0.0, 0.0])
            s[0] += n
            s[2] += nbytes

    def register_release(self, owner: str, n: int = 1, nbytes: int = 0) -> None:
        with self._lock:
            s = self._sites.setdefault(owner, [0.0, 0.0, 0.0, 0.0])
            s[1] += n
            s[3] += nbytes

    def register_sampler(
        self, fn: Callable[[], List[Dict[str, Any]]]
    ) -> None:
        """Attach an extra pool sampler (returns rows in the builtin
        schema) — the registration point for surfaces this module does
        not know about."""
        with self._lock:
            self._samplers.append(fn)

    # -- the tick ----------------------------------------------------------

    def pool_table(self) -> List[Dict[str, Any]]:
        """Current pool rows from every sampler (builtin + registered).
        Collected WITHOUT the ledger lock — samplers take pool locks."""
        with self._lock:
            extra = list(self._samplers)
        rows: List[Dict[str, Any]] = []
        for fn in _BUILTIN_SAMPLERS + tuple(extra):
            try:
                rows.extend(fn())
            except Exception as e:
                # One broken sampler must not blind the whole ledger —
                # but say so, loudly enough to fix it.
                log.warning("memledger sampler %r failed: %s", fn, e)
        return rows

    def sample(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One ledger tick: sample pools, advance the leak/forecast
        windows, refresh gauges, emit findings. Returns the snapshot
        dict (what ``mem-rank<N>.jsonl`` records). ``now`` is a
        monotonic-clock override for deterministic tests."""
        t = time.monotonic() if now is None else float(now)
        rows = self.pool_table()
        findings: List[Dict[str, Any]] = []
        lead_s = self._window * self._flush_s
        with self._lock:
            for row in rows:
                cap = row.get("capacity_units") or 0.0
                if cap > 0:
                    h = self._pool_hist.setdefault(
                        row["pool"], deque(maxlen=max(self._window, 4))
                    )
                    h.append((t, float(row.get("free_units") or 0.0)))
                    tte = _trend_tte_s(h)
                    if tte is not None:
                        row["tte_s"] = round(tte, 3)
                        if tte <= lead_s:
                            findings.append({
                                "kind": "mem_pressure",
                                "owner": row["pool"],
                                "value": round(tte, 3),
                                "threshold": lead_s,
                                "free_units": row.get("free_units"),
                                "capacity_units": cap,
                            })
            self._leaking.clear()
            sites_out: Dict[str, Dict[str, float]] = {}
            for owner, s in self._sites.items():
                outstanding = s[0] - s[1]
                h = self._site_hist.setdefault(
                    owner, deque(maxlen=self._window)
                )
                h.append(outstanding)
                sites_out[owner] = {
                    "allocs": s[0], "releases": s[1],
                    "outstanding": outstanding,
                    "bytes_outstanding": s[2] - s[3],
                }
                grew = len(h) == self._window and all(
                    h[i] < h[i + 1] for i in range(len(h) - 1)
                )
                if grew and h[-1] > 0:
                    self._leaking.add(owner)
                    findings.append({
                        "kind": "mem_leak",
                        "owner": owner,
                        "value": outstanding,
                        "threshold": float(self._window),
                        "grew_by": h[-1] - h[0],
                    })
            total = sum(int(r.get("used_bytes") or 0) for r in rows)
            self.peak_bytes = max(self.peak_bytes, total)
            peak = self.peak_bytes
            # Cooldown: a sustained condition is one event stream, one
            # emission per lead window per (kind, owner).
            emit = []
            for f in findings:
                key = (f["kind"], f["owner"])
                last = self._cool.get(key)
                if last is None or t - last >= max(lead_s, self._flush_s):
                    self._cool[key] = t
                    emit.append(f)
            leak_count = len(self._leaking)
        self._publish(rows, total, peak, leak_count)
        for f in emit:
            self._emit_finding(f)
        snap = {
            "ts": round(time.time(), 6),
            "t_mono": round(t, 6),
            "rank": self.rank,
            "total_mb": round(total / (1 << 20), 3),
            "peak_mb": round(peak / (1 << 20), 3),
            "pools": rows,
            "sites": sites_out,
            "findings": findings,
            "window": self._window,
            "flush_s": self._flush_s,
        }
        with self._lock:
            self._last_snapshot = snap
        return snap

    def _publish(
        self, rows: List[Dict[str, Any]], total: int, peak: int,
        leak_count: int,
    ) -> None:
        metrics.add("cgx.mem.samples")
        metrics.set("cgx.mem.total_mb", round(total / (1 << 20), 3))
        metrics.set("cgx.mem.peak_mb", round(peak / (1 << 20), 3))
        metrics.set("cgx.mem.pools", float(len(rows)))
        metrics.set("cgx.mem.leak_suspects", float(leak_count))
        worst_frag = 0.0
        for row in rows:
            name = row["pool"]
            metrics.set(
                f"cgx.mem.pool_used_mb.{name}",
                round(int(row.get("used_bytes") or 0) / (1 << 20), 3),
            )
            if row.get("capacity_units"):
                metrics.set(
                    f"cgx.mem.pool_free.{name}",
                    float(row.get("free_units") or 0.0),
                )
            if row.get("tte_s") is not None:
                metrics.set(f"cgx.mem.pool_tte_s.{name}", row["tte_s"])
            frag = row.get("frag")
            if frag is not None:
                metrics.set(f"cgx.mem.pool_frag.{name}", frag)
                if row.get("kind") == "arena":
                    worst_frag = max(worst_frag, frag)
        metrics.set("cgx.mem.arena_frag", round(worst_frag, 4))

    def _emit_finding(self, f: Dict[str, Any]) -> None:
        metrics.add(f"cgx.mem.events.{f['kind']}")
        flightrec.record(
            "mem", event=f["kind"],
            **{k: v for k, v in f.items() if k != "kind"},
        )
        detail = {
            k: v for k, v in f.items()
            if k not in ("kind", "owner", "value", "threshold")
        }
        # Lazy: the event plane is optional — gauges/flightrec/jsonl
        # carry the finding even with CGX_HEALTH off.
        from . import health as health_mod

        health_mod.note_mem_event(
            f["kind"], f["value"], f["threshold"], owner=f["owner"],
            **detail,
        )

    # -- surfaces ----------------------------------------------------------

    def peak_mb(self) -> float:
        with self._lock:
            return round(self.peak_bytes / (1 << 20), 3)

    def last_snapshot(self) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._last_snapshot

    def leak_suspects(self) -> List[str]:
        with self._lock:
            return sorted(self._leaking)

    # -- lifecycle ---------------------------------------------------------

    def reset(self, reason: str = "reset") -> None:
        """Recovery cascade entry (``supervisor.invalidate_trace_caches``
        → :func:`reset_ledger`): every derived window restarts — a
        reconfigured group's alloc/release streams and free-level trends
        are a new regime; carrying pre-recovery history across would
        fabricate leaks out of the epoch bump itself."""
        with self._lock:
            self._sites.clear()
            self._site_hist.clear()
            self._pool_hist.clear()
            self._cool.clear()
            self._leaking.clear()
            self.peak_bytes = 0
            self._last_snapshot = None
        metrics.add("cgx.mem.resets")
        metrics.set("cgx.mem.leak_suspects", 0.0)
        log.info("memledger reset (%s)", reason)

    def rebind_rank(self, rank: int) -> None:
        with self._lock:
            self.rank = int(rank)

    def _snapshot_path(self) -> Optional[str]:
        directory = cfg.metrics_dir()
        if not directory:
            return None
        return os.path.join(directory, f"mem-rank{self.rank}.jsonl")

    def flush(self) -> Optional[Dict[str, Any]]:
        """Sample and (when ``CGX_METRICS_DIR`` is set) append the
        snapshot line. Never raises — same contract as the exporter."""
        snap = self.sample()
        path = self._snapshot_path()
        if path:
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                with open(path, "a") as f:
                    f.write(json.dumps(snap) + "\n")
            except (OSError, TypeError, ValueError) as e:
                log.warning("memledger snapshot to %s failed: %s", path, e)
        return snap

    def start(self) -> "MemLedger":
        self._thread = threading.Thread(
            target=self._run, name="cgx-memledger", daemon=True
        )
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self._flush_s):
            try:
                self.flush()
            except Exception as e:
                # The accountant must never take down the workload it
                # is counting for.
                log.warning("memledger tick failed: %s", e)

    def stop(self) -> None:
        self._stop.set()


# ---------------------------------------------------------------------------
# Process singleton + zero-cost hot-path shims (health.py discipline:
# one global load when off).
# ---------------------------------------------------------------------------

_ledger: Optional[MemLedger] = None
_ledger_lock = threading.Lock()


def active() -> bool:
    """True iff the process memory ledger is running."""
    return _ledger is not None


def get_ledger() -> Optional[MemLedger]:
    return _ledger


def maybe_start(rank: Optional[int] = None) -> Optional[MemLedger]:
    """Start (idempotently) the process ledger iff ``CGX_MEMLEDGER`` is
    set. Returns None — and starts nothing — otherwise. Late rank bind
    follows flightrec's first-wins convention: an early caller that
    doesn't know its rank starts as 0; the first caller with a nonzero
    rank rebinds, so per-rank ``mem-rank<N>.jsonl`` files never
    collide."""
    global _ledger
    if not cfg.memledger_enabled():
        return None
    with _ledger_lock:
        if _ledger is None:
            _ledger = MemLedger(rank or 0).start()
        elif rank and _ledger.rank == 0:
            _ledger.rebind_rank(rank)
        return _ledger


def stop() -> None:
    """Stop and drop the process ledger (tests / explicit teardown)."""
    global _ledger
    with _ledger_lock:
        led, _ledger = _ledger, None
    if led is not None:
        led.stop()


def note_alloc(owner: str, n: int = 1, nbytes: int = 0) -> None:
    """Hot-path alloc hook (one global load when the ledger is off).
    Every call site needs a matching :func:`note_release`/reset partner
    — the analyzer's mem-ledger-pairing pass enforces it."""
    led = _ledger
    if led is not None:
        led.register_alloc(owner, n=n, nbytes=nbytes)


def note_release(owner: str, n: int = 1, nbytes: int = 0) -> None:
    """Hot-path release hook (one global load when the ledger is off)."""
    led = _ledger
    if led is not None:
        led.register_release(owner, n=n, nbytes=nbytes)


def reset_ledger(reason: str = "reset") -> None:
    """Recovery-cascade entry point: reset the running ledger's derived
    state (no-op when off)."""
    led = _ledger
    if led is not None:
        led.reset(reason)


def peak_mb() -> Optional[float]:
    """The running ledger's peak total (MiB), or None when off — the
    bench harness attaches this to every BENCH_LOG record. Samples once
    if the periodic thread hasn't ticked yet (a short bench run must
    not race the first flush into recording peak 0)."""
    led = _ledger
    if led is None:
        return None
    if led.last_snapshot() is None:
        led.sample()
    return led.peak_mb()
