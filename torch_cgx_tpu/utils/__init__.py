from . import env
from .logging import get_logger, metrics
from .tracing import named_scope, profile_capture, trace_span
from .tree import leaf_paths, path_str, round_up, tree_size_bytes

__all__ = [
    "env",
    "get_logger",
    "metrics",
    "named_scope",
    "profile_capture",
    "trace_span",
    "leaf_paths",
    "path_str",
    "round_up",
    "tree_size_bytes",
]
