"""Pytree helpers: flatten-with-paths, alignment, size utilities."""

from __future__ import annotations

from typing import Any, List, Tuple

import jax
import numpy as np


def round_up(value: int, multiple: int) -> int:
    """Reference ``round_to`` (utils.cc) — round up to a multiple."""
    if multiple <= 0:
        return value
    return ((value + multiple - 1) // multiple) * multiple


def leaf_paths(tree: Any) -> List[Tuple[str, Any]]:
    """Flatten a pytree into (dotted-path, leaf) pairs, stable order."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        out.append((path_str(path), leaf))
    return out


def path_str(path) -> str:
    parts = []
    for p in path:
        if isinstance(p, jax.tree_util.DictKey):
            parts.append(str(p.key))
        elif isinstance(p, jax.tree_util.SequenceKey):
            parts.append(str(p.idx))
        elif isinstance(p, jax.tree_util.GetAttrKey):
            parts.append(p.name)
        elif isinstance(p, jax.tree_util.FlattenedIndexKey):
            parts.append(str(p.key))
        else:
            parts.append(str(p))
    return ".".join(parts)


def tree_size_bytes(tree: Any) -> int:
    leaves = jax.tree_util.tree_leaves(tree)
    return int(sum(np.prod(l.shape) * l.dtype.itemsize for l in leaves))
