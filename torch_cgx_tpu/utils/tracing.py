"""Tracing / profiling spans.

The reference's only tracing is c10d ``profilingTitle`` strings surfaced to
torch.profiler (SURVEY.md §5.1). The TPU-native equivalent: every collective
wraps itself in a ``jax.profiler.TraceAnnotation`` (visible in XLA/Perfetto
traces) and records wall-clock spans into the metrics registry for host-side
inspection.
"""

from __future__ import annotations

import contextlib
import time

import jax

from .logging import get_logger, metrics

log = get_logger()


@contextlib.contextmanager
def trace_span(name: str):
    """Annotate a host-side span: XLA trace annotation + duration counter
    (``span.{name}.seconds`` / ``span.{name}.count`` in ``metrics``) and
    a duration histogram (``span.{name}.duration_s`` — distinct name so
    its flattened ``.count``/``.sum`` stats never collide with the legacy
    counter keys in ``snapshot()``).

    With ``CGX_METRICS_DIR`` set the span also lands in the cross-rank
    timeline (``observability.timeline``) so it shows up as a slice in
    the merged ``trace.json``.

    The duration sample is recorded in a ``finally`` so a span whose body
    raises still lands in the registry — failed collectives are the
    interesting ones; ``span.{name}.errors`` counts them.
    """
    from ..observability import timeline

    start = time.perf_counter()
    ok = True
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    except BaseException:
        ok = False
        metrics.add(f"span.{name}.errors", 1.0)
        raise
    finally:
        dur = time.perf_counter() - start
        metrics.add(f"span.{name}.seconds", dur)
        metrics.add(f"span.{name}.count", 1.0)
        metrics.observe(f"span.{name}.duration_s", dur)
        timeline.record(name, timeline.CAT_SPAN, start, dur, ok=ok)


def named_scope(name: str):
    """Annotation for traced (jitted) code regions — shows up in the XLA HLO
    and device profile."""
    return jax.named_scope(name)


@contextlib.contextmanager
def profile_capture(subdir: str = "cgx"):
    """Write a device profile (Perfetto/XPlane, viewable in TensorBoard or
    ui.perfetto.dev) for the enclosed region when ``CGX_TRACE_DIR`` is set;
    a no-op otherwise. Wrap a few training steps:

        with profile_capture("step100"):
            for _ in range(3):
                params, opt_state, loss = step(params, opt_state, batch, i)
            jax.block_until_ready(params)
    """
    import os

    base = os.environ.get("CGX_TRACE_DIR")
    if not base:
        yield
        return
    path = os.path.join(base, subdir)
    # A nonexistent CGX_TRACE_DIR used to make jax.profiler.trace fail
    # (or silently drop the capture, backend-dependent) — create it and
    # say where the capture went.
    os.makedirs(path, exist_ok=True)
    log.info("cgx: writing device profile capture to %s", path)
    with jax.profiler.trace(path):
        yield
