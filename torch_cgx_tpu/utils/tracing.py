"""Tracing / profiling spans.

The reference's only tracing is c10d ``profilingTitle`` strings surfaced to
torch.profiler (SURVEY.md §5.1). The TPU-native equivalent: every collective
wraps itself in a ``jax.profiler.TraceAnnotation`` (visible in XLA/Perfetto
traces) and records wall-clock spans into the metrics registry for host-side
inspection.
"""

from __future__ import annotations

import contextlib
import time

import jax

from .logging import metrics


@contextlib.contextmanager
def trace_span(name: str):
    """Annotate a host-side span: XLA trace annotation + duration counter
    (``span.<name>.seconds`` / ``span.<name>.count`` in ``metrics``)."""
    start = time.perf_counter()
    with jax.profiler.TraceAnnotation(name):
        yield
    dur = time.perf_counter() - start
    metrics.add(f"span.{name}.seconds", dur)
    metrics.add(f"span.{name}.count", 1.0)


def named_scope(name: str):
    """Annotation for traced (jitted) code regions — shows up in the XLA HLO
    and device profile."""
    return jax.named_scope(name)
