"""Structured logging + metrics.

The reference has printf-only observability (SURVEY.md §5.5); here we
provide leveled logging (``CGX_LOG_LEVEL``) and the process-wide metric
registry. The registry itself lives in
:mod:`torch_cgx_tpu.observability.instruments` — typed counters, gauges
and histograms with quantile snapshots — and is re-exported here under
its historical name so every ``from ..utils.logging import metrics``
call site (and the seed's ``add/set/get/snapshot/reset`` API) keeps
working unchanged.
"""

from __future__ import annotations

import logging
import os

from ..observability.instruments import Metrics, metrics  # noqa: F401

_LOGGER_NAME = "torch_cgx_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s %(levelname)s] %(message)s")
        )
        logger.addHandler(handler)
        level = os.environ.get("CGX_LOG_LEVEL", "WARNING").upper()
        logger.setLevel(getattr(logging, level, logging.WARNING))
        logger.propagate = False
    return logger
