"""Structured logging + metrics.

The reference has printf-only observability (SURVEY.md §5.5); here we provide
leveled logging (``CGX_LOG_LEVEL``) and a tiny in-process metrics registry so
benchmarks/tests can assert on counters.
"""

from __future__ import annotations

import logging
import os
import threading
from collections import defaultdict
from typing import Dict

_LOGGER_NAME = "torch_cgx_tpu"


def get_logger() -> logging.Logger:
    logger = logging.getLogger(_LOGGER_NAME)
    if not logger.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(
            logging.Formatter("[%(asctime)s %(name)s %(levelname)s] %(message)s")
        )
        logger.addHandler(handler)
        level = os.environ.get("CGX_LOG_LEVEL", "WARNING").upper()
        logger.setLevel(getattr(logging, level, logging.WARNING))
        logger.propagate = False
    return logger


class Metrics:
    """Process-wide counter/gauge registry (thread-safe)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = defaultdict(float)

    def add(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._counters[name] = value

    def get(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self, prefix: str = "") -> Dict[str, float]:
        """All counters, optionally filtered by name prefix — e.g.
        ``metrics.snapshot("cgx.faults.")`` for the fault-injection tally
        or ``metrics.snapshot("cgx.wire")`` for wire-integrity events."""
        with self._lock:
            if not prefix:
                return dict(self._counters)
            return {
                k: v
                for k, v in self._counters.items()
                if k.startswith(prefix)
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()


metrics = Metrics()
