"""Environment-variable helpers.

TPU-native re-expression of the reference env-parsing layer
(/root/reference/src/common/utils.h:30-57, utils.cc:25-91): the same
``CGX_*`` surface, read lazily so tests can mutate variables between calls
(the reference re-reads env on every bucket, mpi_allreduce_operations.cc:238).
"""

from __future__ import annotations

import os
from typing import Optional


def get_int_env_or_default(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return int(raw)
    except ValueError:
        raise ValueError(f"env var {name} must be an int, got {raw!r}")


def get_float_env_or_default(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(f"env var {name} must be a float, got {raw!r}")


def get_bool_env_or_default(name: str, default: bool) -> bool:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip().lower() in ("1", "true", "yes", "on")


def get_str_env_or_default(name: str, default: str) -> str:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return default
    return raw.strip()


def get_optional_str_env(name: str) -> Optional[str]:
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return None
    return raw.strip()
