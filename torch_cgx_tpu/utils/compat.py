"""JAX version compatibility shims.

The codebase targets the modern surface (``jax.shard_map`` with
``axis_names``/``check_vma``, ``jax.set_mesh``,
``jax.distributed.is_initialized``); older runtimes (<= 0.4.x) expose the
same machinery under ``jax.experimental.shard_map`` /
``jax.sharding``-era names with different keyword spellings. Routing every
call site through this module keeps the robustness/chaos suite runnable on
both — a wedged-container debug session should not also be a jax-upgrade
session.
"""

from __future__ import annotations

import contextlib
from typing import Optional

import jax


def shard_map(
    f,
    *,
    mesh,
    in_specs,
    out_specs,
    axis_names=None,
    check_vma: Optional[bool] = None,
    check_rep: Optional[bool] = None,
):
    """``jax.shard_map`` when available, else the
    ``jax.experimental.shard_map`` spelling with keywords translated:
    ``check_vma`` -> ``check_rep`` and ``axis_names`` -> the complementary
    ``auto`` set (old shard_map names the *non*-manual axes)."""
    if hasattr(jax, "shard_map"):
        kw = {}
        if axis_names is not None:
            kw["axis_names"] = axis_names
        if check_vma is not None or check_rep is not None:
            kw["check_vma"] = check_vma if check_vma is not None else check_rep
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    kw = {}
    if check_vma is not None or check_rep is not None:
        kw["check_rep"] = check_vma if check_vma is not None else check_rep
    if axis_names is not None:
        auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        if auto:
            kw["auto"] = auto
    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
    )


def set_mesh(mesh):
    """Context manager installing ``mesh`` as the ambient mesh:
    ``jax.set_mesh`` / ``jax.sharding.use_mesh`` when present, else the
    legacy ``with mesh:`` context (old global-mesh semantics)."""
    if hasattr(jax, "set_mesh"):
        return jax.set_mesh(mesh)
    use_mesh = getattr(jax.sharding, "use_mesh", None)
    if use_mesh is not None:
        return use_mesh(mesh)
    return contextlib.nullcontext(mesh) if mesh is None else mesh


def ensure_cpu_collectives() -> None:
    """Arm cross-process collectives for CPU-backend multi-process runs.

    jaxlib ships a Gloo CPU-collectives implementation, but jax 0.4.x
    defaults the ``jax_cpu_collectives_implementation`` flag to none — a
    multi-process CPU program then fails every collective with
    "Multiprocess computations aren't implemented on the CPU backend"
    (newer jax defaults to gloo). Called only when a distributed runtime
    is about to initialize (``mesh.init_distributed`` behind its
    coordinator check — gloo needs the distributed client; arming it on a
    single-host process fails CPU backend init outright). A no-op when
    the platform is explicitly pinned away from CPU, when the flag is
    already set (an explicit mpi/gloo choice is respected), or on
    runtimes without the flag (initialize() surfaces the gap there).
    An UNSET platform still arms it: jax may auto-select the CPU backend
    (CPU-only hosts), and on accelerator pods the secondary CPU client
    takes gloo harmlessly once the distributed client exists."""
    import os

    plats = str(
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS")
        or ""
    ).lower()
    if plats and "cpu" not in plats:
        return
    try:
        from jax._src import xla_bridge as _xb

        flag = getattr(_xb, "CPU_COLLECTIVES_IMPLEMENTATION", None)
        if flag is not None and flag.value in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        pass


def distributed_is_initialized() -> bool:
    """``jax.distributed.is_initialized`` with a state-probe fallback for
    runtimes that predate the accessor."""
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        return bool(probe())
    try:
        from jax._src.distributed import global_state

        return global_state.client is not None
    except Exception:
        return False


def axis_size(axis_name) -> int:
    """``jax.lax.axis_size`` (static mapped-axis extent inside shard_map)
    with the classic ``psum(1, axis)`` constant-fold fallback for runtimes
    that predate the accessor."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)
