"""Two-level ICI/DCN topology router for compressed collectives.

The reference paper's communicator hierarchy (PAPER.md §0) is two-level:
a fast node-local plane (SHM) and a compressed cross-node plane (MPI).
Its TPU-native form is *slice* topology: devices inside one TPU slice
talk over ICI (fast, XLA-schedulable), devices in different slices talk
over DCN (slow, the role the host bridge's shm/store plane plays for the
torch path). This module is the router that re-introduces that
distinction per collective:

* classify each group a collective runs over — the devices varying along
  the reduction axes of the mesh — as **intra-slice** (all devices share
  one slice), **cross-slice** (one device per slice) or **mixed**
  (spanning slices with more than one device in some slice), from the
  device attributes alone (``slice_index`` on multi-slice TPU;
  ``process_index`` is the host-granularity fallback that makes a
  multi-host CPU/GPU mesh classify sensibly);
* route intra-slice traffic to the in-XLA single-program quantized
  allreduce (``parallel/xla_allreduce.py`` — no ``io_callback``, no
  bridge hop), cross-slice traffic to the existing compressed DCN/bridge
  path, and mixed groups to the reference's two-level scheme:
  **uncompressed ICI reduce inside the slice, compressed exchange across
  slices** (:func:`two_level_config` — ``hierarchical_allreduce``'s
  leader scheme with ``intra_compress`` off lowers the intra stage to a
  plain ``lax.psum_scatter``/``all_gather`` pair).

Routing is gated by ``CGX_XLA_ALLREDUCE`` (see ``config.xla_allreduce``);
with the knob unset every decision is :data:`ROUTE_UNROUTED` on non-TPU
backends, so the default CPU/CI path is bit-identical to the pre-router
code. This module is **staged-pure** (listed in
``xla_allreduce.STAGED_PURE``): it must never import host-callback
machinery — ``tools/lint.py`` enforces that.
"""

from __future__ import annotations

import dataclasses
from collections import Counter
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import config as cfg_mod
from ..ops import dispatch

# Group topology classes.
TOPO_SINGLE = "single"  # ws == 1: nothing travels
TOPO_INTRA = "intra_slice"  # all devices share one slice — ICI only
TOPO_CROSS = "cross_slice"  # one device per slice — DCN only
TOPO_MIXED = "mixed"  # spans slices, >1 device in some slice

# Routing decisions.
ROUTE_STAGED = "staged"  # in-XLA single program (xla_allreduce.py)
ROUTE_BRIDGE = "bridge"  # cross-slice: the compressed DCN/bridge path
ROUTE_TWO_LEVEL = "two_level"  # uncompressed ICI + compressed cross
ROUTE_UNROUTED = "unrouted"  # knob off / ineligible: existing path


def device_slice_id(dev) -> int:
    """The slice a device belongs to: ``slice_index`` where the platform
    exposes it (multi-slice TPU), else ``process_index`` (host
    granularity — the bridge's shm/store plane is per-host, so host
    boundaries are the right fallback notion of "crossing the slow
    fabric"), else 0 (a single-process CPU/GPU mesh is one slice)."""
    s = getattr(dev, "slice_index", None)
    if s is not None:
        try:
            return int(s)
        except (TypeError, ValueError):
            pass
    p = getattr(dev, "process_index", None)
    try:
        return int(p) if p is not None else 0
    except (TypeError, ValueError):
        return 0


@dataclasses.dataclass(frozen=True)
class GroupTopology:
    """Classification of one collective group (hashable — rides the
    layout-LRU and trace-cache keys)."""

    kind: str
    ws: int
    n_slices: int
    max_per_slice: int


@dataclasses.dataclass(frozen=True)
class RouteDecision:
    route: str
    topo: GroupTopology
    reason: str


def classify_slice_ids(ids: Sequence[int]) -> GroupTopology:
    """Classify a group from its members' slice ids (the shared kernel of
    the mesh- and host-based classifiers)."""
    ids = list(ids)
    ws = len(ids)
    counts = Counter(ids)
    n_slices = len(counts)
    max_per = max(counts.values()) if counts else 1
    if ws <= 1:
        kind = TOPO_SINGLE
    elif n_slices == 1:
        kind = TOPO_INTRA
    elif n_slices == ws:
        kind = TOPO_CROSS
    else:
        kind = TOPO_MIXED
    return GroupTopology(
        kind=kind, ws=ws, n_slices=n_slices, max_per_slice=max_per
    )


def classify_hosts(hosts: Sequence) -> GroupTopology:
    """Bridge-side classification: a torch process group's per-rank host
    fingerprints (``ProcessGroupCGX._host_by_rank``) map to slice ids by
    first-seen order. Same taxonomy as the mesh classifier, so the bridge
    and the JAX router agree on what "mixed" means.

    Must always be fed the CURRENT membership's host list — after a PR 5
    eviction that is the survivor-filtered map at the bumped generation,
    whose slice ids are re-derived from scratch by the first-seen walk
    (non-contiguous pre-eviction ids collapse back to 0..n_slices-1).
    Deriving from a cached pre-eviction classification is exactly the bug
    :func:`slice_leaders` + ``invalidate_classification_cache`` close: a
    stale map can name an evicted rank as a cross-slice leader."""
    seen: dict = {}
    ids = []
    for h in hosts:
        if h not in seen:
            seen[h] = len(seen)
        ids.append(seen[h])
    return classify_slice_ids(ids)


def slice_leaders(hosts: Sequence) -> list:
    """Group-local leader ranks, one per slice, derived from the CURRENT
    per-rank host map: the lowest group-local rank of each distinct host,
    ordered by first appearance (the slice-id order
    :func:`classify_hosts` assigns). The canonical re-derivation for the
    two-level cross stage and the async plane's membership — after an
    eviction the caller passes the survivor-filtered map at the bumped
    generation, so an evicted rank can never be named leader
    (regression-pinned in tests/test_async_plane.py).
    ``torch_backend.backend._slice_leaders`` keeps the sanctioned
    dependency-light duplicate, pinned equal by the same test."""
    seen: dict = {}
    for i, h in enumerate(hosts):
        if h not in seen:
            seen[h] = i
    return list(seen.values())


# Classification of a fixed (mesh, axes) pair never changes, but the scan
# is O(devices) Python work — too hot for per-train-step cache keys on big
# meshes. Memoized keyed on the mesh object, the axes, AND the live
# ``device_slice_id`` function (tests monkeypatch it to fake slice ids —
# a patched function is a different key, so the memo can't serve stale
# classifications across patches).
_CLASSIFY_CACHE: dict = {}
_CLASSIFY_CACHE_MAX = 64


def invalidate_classification_cache(reason: str = "reconfigure") -> None:
    """Drop every memoized group classification. Cascaded from
    ``supervisor.invalidate_trace_caches``: the memo key is (mesh, axes,
    classifier fn), none of which change when a PR 5 eviction shrinks the
    world underneath an unchanged mesh object — a stale hit could then
    route a group as MIXED against a slice map whose leader was just
    evicted (the cached-classification bug this PR's regression test
    pins). Route/cache_key callers re-scan on the next call."""
    if _CLASSIFY_CACHE:
        _CLASSIFY_CACHE.clear()
        from ..utils.logging import get_logger, metrics

        metrics.add("cgx.xla.topo_cache_invalidations")
        get_logger().info("topology classification cache dropped (%s)", reason)


def classify_mesh_axes(mesh, axes: Sequence[str]) -> GroupTopology:
    """Classify the groups a collective over ``axes`` runs on: devices
    varying along ``axes`` with every other mesh coordinate fixed. All
    groups of a grid mesh normally classify identically; if slices are
    not axis-aligned (groups disagree), the conservative answer is MIXED
    — the two-level scheme degrades gracefully, the staged fast path must
    not engage on a group that secretly crosses DCN."""
    try:
        memo_key = (mesh, tuple(axes), device_slice_id)
        hit = _CLASSIFY_CACHE.get(memo_key)
    except TypeError:  # unhashable mesh stand-in
        memo_key, hit = None, None
    if hit is not None:
        return hit
    out = _classify_mesh_axes_scan(mesh, axes)
    if memo_key is not None:
        _CLASSIFY_CACHE[memo_key] = out
        while len(_CLASSIFY_CACHE) > _CLASSIFY_CACHE_MAX:
            _CLASSIFY_CACHE.pop(next(iter(_CLASSIFY_CACHE)))
    return out


def _classify_mesh_axes_scan(mesh, axes: Sequence[str]) -> GroupTopology:
    arr = np.asarray(mesh.devices)
    names = list(mesh.axis_names)
    idxs = [names.index(a) for a in axes]
    moved = np.moveaxis(arr, idxs, range(len(idxs)))
    group_size = int(np.prod([arr.shape[i] for i in idxs])) if idxs else 1
    cols = moved.reshape(group_size, -1)
    topo: Optional[GroupTopology] = None
    kinds = set()
    worst: Optional[GroupTopology] = None
    for c in range(cols.shape[1]):
        t = classify_slice_ids([device_slice_id(d) for d in cols[:, c]])
        kinds.add(t.kind)
        topo = t
        if worst is None or t.n_slices > worst.n_slices:
            worst = t
    assert topo is not None and worst is not None  # meshes are non-empty
    if len(kinds) > 1:
        return GroupTopology(
            kind=TOPO_MIXED,
            ws=topo.ws,
            n_slices=worst.n_slices,
            max_per_slice=worst.max_per_slice,
        )
    return topo


def route(
    mesh, axes: Sequence[str], *, allow_remesh: bool = False
) -> RouteDecision:
    """The per-collective routing decision:

    * intra-slice single-axis groups -> :data:`ROUTE_STAGED` when the
      capability gate (``dispatch.staged_allreduce_capable`` — the
      ``CGX_XLA_ALLREDUCE`` knob + backend) allows;
    * cross-slice groups -> :data:`ROUTE_BRIDGE` (the existing compressed
      DCN/bridge path keeps them — its end-state role);
    * mixed groups -> :data:`ROUTE_TWO_LEVEL` (reference two-level:
      uncompressed ICI intra, compressed cross) — only under the explicit
      ``on`` mode, because the override changes wire bytes and ``auto``
      promises bit-identity with the knob unset. The scheme needs a
      (cross, intra) grid: a 2-axis call can run it in-program, a 1-axis
      caller only when it can re-mesh (``allow_remesh=True`` — the eager
      ``staged_allreduce`` builds the grid from slice ids; shard_map
      callers cannot, and get UNROUTED so telemetry and cache keys report
      the path that actually runs);
    * everything else -> :data:`ROUTE_UNROUTED` (existing paths, byte-
      identical).
    """
    axes = tuple(axes)
    topo = classify_mesh_axes(mesh, axes)
    mode = cfg_mod.xla_allreduce()
    if topo.kind == TOPO_SINGLE:
        return RouteDecision(ROUTE_UNROUTED, topo, "ws == 1: nothing travels")
    if not dispatch.staged_allreduce_capable():
        return RouteDecision(
            ROUTE_UNROUTED, topo,
            "knob off" if mode == "off" else "auto: non-TPU backend",
        )
    if topo.kind == TOPO_INTRA and len(axes) == 1:
        return RouteDecision(
            ROUTE_STAGED, topo, "intra-slice: one staged XLA program"
        )
    if topo.kind == TOPO_CROSS:
        return RouteDecision(
            ROUTE_BRIDGE, topo, "cross-slice: compressed DCN/bridge path"
        )
    if topo.kind == TOPO_MIXED and mode == "on":
        if len(axes) == 2 or allow_remesh:
            return RouteDecision(
                ROUTE_TWO_LEVEL, topo,
                "mixed: uncompressed ICI intra + compressed cross "
                "(two-level)",
            )
        return RouteDecision(
            ROUTE_UNROUTED, topo,
            "mixed 1-axis group: two-level needs a (cross, intra) mesh "
            "(only the eager staged_allreduce can re-mesh)",
        )
    return RouteDecision(
        ROUTE_UNROUTED, topo,
        "intra-slice hierarchical mesh" if topo.kind == TOPO_INTRA
        else "mixed group without CGX_XLA_ALLREDUCE=on",
    )


def cache_key(mesh, axes: Sequence[str]) -> Tuple[str, str]:
    """The routing component of layout-LRU / trace-cache keys: (route,
    topology class). Cheap (a device-attribute scan), re-read per call
    like every CGX_* knob — flipping ``CGX_XLA_ALLREDUCE`` between calls
    must produce a fresh plan, never hit a stale one."""
    d = route(mesh, axes)
    return (d.route, d.topo.kind)


def two_level_config(
    base: Optional[cfg_mod.TopologyConfig] = None,
) -> cfg_mod.TopologyConfig:
    """The reference's two-level scheme as a ``TopologyConfig`` override
    (PAPER.md §0 in TPU-native form): the intra stage rides ICI
    uncompressed — ``hierarchical_allreduce``'s leader scheme lowers it
    to a plain ``lax.psum_scatter`` + ``all_gather`` — and only the
    cross-slice exchange carries the quantized wire."""
    base = base or cfg_mod.topology_from_env()
    return dataclasses.replace(
        base, intra_compress=False, intra_broadcast=True
    )


def two_level_mesh(devices: Optional[Sequence] = None):
    """A (cross, intra) mesh grouped by slice id, for callers holding a
    flat device list that classifies MIXED: row ``s`` holds slice ``s``'s
    devices. Requires a uniform per-slice device count (TPU slices of one
    topology always are); raises otherwise."""
    import jax
    from jax.sharding import Mesh

    from . import mesh as mesh_mod

    devices = list(devices) if devices is not None else jax.devices()
    by_slice: dict = {}
    for d in devices:
        by_slice.setdefault(device_slice_id(d), []).append(d)
    sizes = {len(v) for v in by_slice.values()}
    if len(sizes) != 1:
        raise ValueError(
            "two_level_mesh: non-uniform devices per slice "
            f"({ {k: len(v) for k, v in by_slice.items()} })"
        )
    rows = [by_slice[k] for k in sorted(by_slice)]
    return Mesh(
        np.asarray(rows, dtype=object),
        (mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS),
    )
