"""Top-K gradient sparsification (Deep Gradient Compression lineage).

Beyond the reference: its compressor hierarchy is max-min quantization
plus a debug pass-through (compressor.h:130,145). Together with PowerSGD
(:mod:`.powersgd`, low-rank) and adaptive per-layer bits
(:mod:`.adaptive`), this module completes the standard gradient-
compression taxonomy — quantization / low-rank / sparsification — behind
the same optax-transform surface.

Per eligible leaf g (flattened to n values, per-device EF residual e):

    M    = g + e                  # error feedback (mandatory: top-k drops
                                  # almost everything; the complement must
                                  # be carried, not lost)
    idx  = top_k(|M|, k)          # this device's k largest coordinates
    val  = M[idx]                 # signed values at those coordinates
    # sparse allreduce: all_gather the (idx, val) pairs over the sync
    # axes and scatter-add into a dense buffer — every device sees every
    # pair, so the scatter runs on identical data and the output is
    # bit-identical across devices by construction.
    S    = scatter_add(all pairs) # sum over devices of their sparse picks
    out  = S / ws                 # (average=True)
    e'   = M - densify(idx, val)  # keep everything THIS device didn't ship

TPU-first shape discipline: ``k`` is static at trace time (a ratio of
``n``), ``lax.top_k`` and one ``.at[].add`` scatter are the only
non-matmul ops, and the gathered ``(ws, k)`` index/value blocks ride the
ordinary all_gather path (no sparse formats on the wire).

Traffic per step and rank: ``k * 8`` bytes sent / ``ws * k * 8``
received (int32 index + f32 value) instead of ``4n`` dense — e.g. at
ratio 1% the wire is ~50x smaller than fp32, ~6x smaller than 4-bit
max-min quantization (which keeps every coordinate at low precision;
top-k keeps few coordinates at full precision — complementary regimes:
quantization for dense-information gradients, sparsification for
peaky ones).

Ineligible leaves (tiny, or k would not shrink the wire) ride an exact
``lax.psum``.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from .. import config as cfg_mod
from ..utils.logging import metrics
from . import mesh as mesh_mod


class TopKState(NamedTuple):
    """es: per-device EF residuals, one flat f32 buffer per eligible leaf
    (``None`` for psum leaves). Same placement hazard as
    :class:`ErrorFeedbackState`: NEVER declare them replicated under
    shard_map — each device must keep its own residual."""

    es: tuple


def _k_for(n: int, ratio: float) -> int:
    return max(1, int(np.ceil(ratio * n)))


def sparsify(flat: jax.Array, k: int):
    """(indices, values) of the ``k`` largest-magnitude coordinates of a
    flat buffer — the top-k wire payload. Shared with the edge
    dispatcher (``wire/dispatch.py``), which ships top-k as a peer
    compressor for point-to-point edges."""
    _, idx = lax.top_k(jnp.abs(flat), k)
    return idx.astype(jnp.int32), jnp.take(flat, idx)


def densify(n: int, idx: jax.Array, val: jax.Array) -> jax.Array:
    """Scatter one device's (idx, val) pairs into a dense zero buffer —
    the receive-side reconstruction of :func:`sparsify` (indices from a
    single sender are unique, so ``set`` suffices; the transform's
    multi-sender fold uses ``add``)."""
    return jnp.zeros((n,), val.dtype).at[idx].set(val)


def eligible(leaf, ratio: float, ws: int = 1) -> bool:
    """Sparsification pays off: float, above the minimal size, and the
    (index, value) pairs are smaller IN BYTES than the dense leaf — a
    pair costs 8 bytes (int32 + f32) regardless of the leaf's dtype, so
    bf16 leaves need a smaller ratio than f32 ones to qualify.

    ``ws`` adds the receive side of the documented traffic model (the
    send-bytes-only gate was world-size-blind — advisor r5 low #1): the
    all_gather delivers ``ws * k`` pairs (``8 * k * ws`` bytes) to every
    rank, where a dense ring/SRA allreduce receives about
    ``2 * n * itemsize * (ws - 1) / ws`` bytes — at large world sizes a
    leaf can pass the send gate yet move MORE total traffic sparse than
    dense, so the receive gate tightens with ``ws``."""
    if not jnp.issubdtype(leaf.dtype, jnp.floating):
        return False
    n = int(leaf.size)
    if n < cfg_mod.minimal_size():
        return False
    k = _k_for(n, ratio)
    itemsize = jnp.dtype(leaf.dtype).itemsize
    if 8 * k >= n * itemsize:
        return False
    if ws > 1 and 8 * k * ws >= 2 * n * itemsize * (ws - 1) / ws:
        return False
    return True


def init_topk(params, ratio: float, ws: int = 1) -> TopKState:
    """Zero EF residuals per eligible leaf (``ws`` feeds the
    world-size-aware traffic gate — pass the product of the sync-axis
    sizes so init and transform agree). Placement under ``jax.jit`` +
    ``shard_map``: give each ``es`` leaf a leading device axis sharded
    over the sync axes (the :func:`init_error_feedback` pattern) and
    strip it inside the mapped function, or use :func:`init_topk_state`."""
    return TopKState(
        es=tuple(
            jnp.zeros((leaf.size,), jnp.float32)
            if eligible(leaf, ratio, ws)
            else None
            for leaf in jax.tree_util.tree_leaves(params)
        )
    )


def topk_transform(
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    ratio: float = 0.01,
    average: bool = True,
    placement_warning: bool = True,
) -> optax.GradientTransformation:
    """optax transformation: top-k-sparsified gradient allreduce.

    Prepend to an optimizer chain running inside ``shard_map``::

        tx = optax.chain(
            cgx.topk_transform(mesh=mesh, ratio=0.01), optax.adam(1e-3)
        )

    The state (:class:`TopKState`) carries per-device EF residuals —
    under shard_map, shard the ``es`` leaves or manage placement via
    :func:`init_topk_state`. Ineligible leaves take an exact ``psum``.
    Outputs are bit-identical across devices (the dense reconstruction
    is computed from all_gathered pairs every device sees identically).
    """
    if not 0.0 < ratio < 1.0:
        raise ValueError(f"topk ratio must be in (0, 1), got {ratio!r}")
    axes = tuple(axes)
    ws = int(np.prod([mesh.shape[a] for a in axes]))

    def _psum(x):
        for a in axes:
            if mesh.shape[a] > 1:
                x = lax.psum(x, a)
        return x

    def _gather(x):
        for a in axes:
            if mesh.shape[a] > 1:
                x = lax.all_gather(x, a, axis=0, tiled=True)
        return x

    def init_fn(params):
        return init_topk(params, ratio, ws)

    def update_fn(updates, state, params=None):
        del params
        if placement_warning:  # es is per-device, like EF state;
            # make_train_step(topk_ratio=...) wires placement itself
            # and passes False
            from .grad_sync import _warn_ef_placement_once

            _warn_ef_placement_once("topk")
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if len(leaves) != len(state.es):
            raise ValueError(
                "TopK state was initialised from a different parameter "
                f"tree: got {len(leaves)} gradient leaves but state holds "
                f"{len(state.es)} residuals. Re-run init_topk on the tree "
                "actually being optimised."
            )
        out_scale = np.float32(1.0 / ws if average else 1.0)
        out, es_new = [], []
        for leaf, e in zip(leaves, state.es):
            if e is None:
                g = leaf.astype(jnp.float32)
                red = _psum(g) * out_scale
                metrics.add("cgx.trace.topk.raw_elems", float(leaf.size))
                out.append(red.astype(leaf.dtype))
                es_new.append(None)
                continue
            n = leaf.size
            k = _k_for(n, ratio)
            m = leaf.astype(jnp.float32).reshape(-1) + e
            idx, val = sparsify(m, k)
            # (ws*k,) after tiled gathers; identical on every device.
            all_idx = _gather(idx)
            all_val = _gather(val)
            dense = (
                jnp.zeros((n,), jnp.float32).at[all_idx].add(all_val)
            )
            metrics.add("cgx.trace.topk.wire_elems", float(2 * k))
            metrics.add("cgx.trace.topk.grad_elems", float(n))
            out.append(
                (dense * out_scale).reshape(leaf.shape).astype(leaf.dtype)
            )
            # residual = m minus what this device shipped; m[i] - m[i] is
            # exactly 0.0 in float, so one in-place scatter replaces the
            # dense own_dense buffer + subtraction bit-identically.
            es_new.append(m.at[idx].set(0.0))
        return (
            jax.tree_util.tree_unflatten(treedef, out),
            TopKState(es=tuple(es_new)),
        )

    return optax.GradientTransformation(init_fn, update_fn)


def init_topk_state(
    params,
    mesh,
    ratio: float,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis=None,
) -> TopKState:
    """Placement-ready state for ``make_train_step(topk_ratio=...)``:
    each ``es`` leaf stacked to ``(ws, n)`` and sharded over the sync
    axes on the leading device dim (the :func:`init_error_feedback`
    pattern), so every device owns exactly its own residual row."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sync_axes = tuple(axes) if sp_axis is None else tuple(axes) + (sp_axis,)
    ws = int(np.prod([mesh.shape[a] for a in sync_axes]))
    es = tuple(
        jnp.zeros((ws, leaf.size), jnp.float32)
        if eligible(leaf, ratio, ws)
        else None
        for leaf in jax.tree_util.tree_leaves(params)
    )
    return TopKState(es=jax.device_put(es, NamedSharding(mesh, P(sync_axes))))
