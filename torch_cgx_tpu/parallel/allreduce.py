"""Tree-level gradient allreduce: layer registry + tensor fusion + dispatch.

TPU-native re-design of ``MPIAllReduce_Operation``
(/root/reference/src/mpi_allreduce_operations.cc — SURVEY.md §2.1): the
reference slices DDP buckets into per-layer views (``extractLayers``,
.cc:257-285), partitions them by compression eligibility (.cc:240-247),
fuses them into <=64 MB wire slices (.cc:201-227), and runs each slice
through the reducers. Here the "bucket" is a gradient pytree: leaves are
resolved to per-layer configs (name-pattern registry, falling back to the
``CGX_*`` env defaults re-read on every call), grouped by (config, dtype),
concatenated, split into fusion slices, reduced, and scattered back.

Fixes deliberately not inherited (SURVEY.md §8.5): every fusion batch is
flushed — the reference silently drops trailing layers after an oversized
one.

All grouping/slicing decisions are static Python (shapes + configs), so jit
caches one program per (tree structure, config) — the registry doubles as
the static-shape cache key exactly as planned in SURVEY.md §7.4.
"""

from __future__ import annotations

import dataclasses
import functools
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import config as cfg_mod
from ..config import CompressionConfig, TopologyConfig
from ..utils.logging import metrics
from ..utils.tracing import named_scope
from ..utils.tree import path_str
from . import mesh as mesh_mod
from . import planner as planner_mod
from . import schedule as sched_mod
from . import topology as topo_router
from .reducers import (
    hierarchical_allreduce,
    quantized_allreduce,
    quantized_allreduce_with_wire,
)

_FLOAT_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)


def _is_float(leaf) -> bool:
    return any(leaf.dtype == d for d in _FLOAT_DTYPES)


def is_compressible(leaf, *, compress_small: bool = False) -> bool:
    """Structural eligibility, independent of whether compression is
    currently enabled: float, large enough, and (unless ``compress_small``)
    rank > 1 — the hook's ``should_compress_`` gates
    (allreduce_hooks.py:42-45) plus the size floor."""
    if not _is_float(leaf):
        return False
    if leaf.size < cfg_mod.minimal_size():
        return False
    if not compress_small and leaf.ndim <= 1:
        return False
    return True


def resolve_leaf_config(
    path: str, leaf, *, compress_small: bool = False
) -> CompressionConfig:
    """Per-leaf config resolution.

    Mirrors the two-stage eligibility decision (SURVEY.md §8.7): the Python
    hook's ``should_compress_`` (dim<=1 or tiny tensors -> uncompressed,
    allreduce_hooks.py:42-45) and the compressor's ``isEnabled``
    (numel > minimal and bits <= 8, compressor.cc:421-425).

    Resolution order: a registered ``dp_grad`` EDGE config matching the
    leaf path (``wire.edges`` — the generalized per-edge registry, which
    the closed-loop controller writes into), then the legacy name-pattern
    registry, then the env default. With no edge registered the edge
    lookup is a no-op (bit-identical resolution).
    """
    from ..wire import edges as wire_edges

    cc = (
        wire_edges.resolve_dp_grad(path)
        or cfg_mod.resolve_pattern_config(path)
        or cfg_mod.default_compression_config()
    )
    if not is_compressible(leaf, compress_small=compress_small):
        return dataclasses.replace(cc, bits=32)
    return cc


def _runtime_count(name: str, n: int) -> None:
    """Execution-time counter bump (CGX_METRICS_RUNTIME): an effectful host
    callback baked into the traced program, so `metrics` reflects steps
    actually run, not programs traced (reference gap §5.5, VERDICT r3 weak
    #5). No-op (nothing staged) when the knob is off at trace time."""
    if not cfg_mod.runtime_metrics():
        return
    from jax.experimental import io_callback

    io_callback(
        lambda v: metrics.add(name, float(v)),
        None,
        jnp.float32(n),
        ordered=False,
    )


def _report_qerr(path: str, leaf, rt) -> None:
    """CGX_QERR_STATS: stage a relative-L2 quantization-error measurement
    of this layer — this device's contribution vs its own wire decode
    (the same stage-1 round trip error feedback consumes) — delivered at
    execution time into the ``cgx.qerr.<path>`` histogram and the flight
    recorder. One observation per device program per step; relative
    error is scale-invariant, so the pre-divided averaging does not skew
    it. Nothing is staged when the knob is off (the clean program stays
    bit-identical)."""
    from jax.experimental import io_callback

    from ..ops.codec import relative_l2_error

    err = relative_l2_error(leaf, rt)

    def _sink(v, path=path):
        from ..observability import flightrec

        metrics.observe(f"cgx.qerr.{path}", float(v))
        # The histogram keeps every observation; the flight-recorder event
        # is subsampled (first, then every 32nd per layer) so a long run's
        # qerr stream cannot flood rare events (trace structure, failures)
        # out of the bounded ring.
        n = _QERR_SEEN.get(path, 0)
        _QERR_SEEN[path] = n + 1
        if n % 32 == 0:
            flightrec.record("qerr", layer=path, rel_l2=float(v), sample=n)

    io_callback(_sink, None, err.astype(jnp.float32), ordered=False)


_QERR_SEEN: Dict[str, int] = {}

# Trace-time (numel, bits) per qerr-reporting layer: the closed-loop
# controller (wire/controller.py) rebuilds the bit-allocation solver's
# LayerStats from the live cgx.qerr.* histograms, which carry only the
# relative error — the payload size and the width it was measured at are
# static facts recorded here when the program stages the measurement.
# Plain host-side Python at trace time: nothing staged changes.
_QERR_INFO: Dict[str, Dict[str, int]] = {}


def qerr_layer_info() -> Dict[str, Dict[str, int]]:
    """Copy of the per-layer {numel, bits} side table (controller)."""
    return {k: dict(v) for k, v in _QERR_INFO.items()}


def reset_qerr_sampling() -> None:
    """Restart the flight-recorder qerr subsample cadence (the per-layer
    every-32nd counters above) and the controller's (numel, bits) side
    table. Called alongside the registry-version bump
    (``supervisor.invalidate_trace_caches``): after a recovery
    reconfiguration the retraced programs are a new qerr stream, and
    keeping the dead generation's counters would subsample it on a stale
    phase — the first post-recovery observation per layer must land in
    the flight recorder, not be silently skipped."""
    _QERR_SEEN.clear()
    _QERR_INFO.clear()


@dataclasses.dataclass(frozen=True)
class _Group:
    cc: CompressionConfig
    dtype: np.dtype
    indices: Tuple[int, ...]  # leaf positions in flattened tree


def _group_leaves(paths_leaves, compress_small: bool) -> List[_Group]:
    """Group leaves by (config, dtype) for fusion — except large leaves,
    which become standalone groups: their flat view needs no gather-concat
    or scatter-back pass (measured as the dominant codec-adjacent cost in
    the single-chip proxy, BASELINE.md). The fusion threshold inside
    allreduce_flat still chunks any oversized buffer."""
    standalone = cfg_mod.standalone_layer_elems()
    groups: Dict[Tuple, List[int]] = {}
    order: List[Tuple] = []
    out: List[_Group] = []
    for i, (path, leaf) in enumerate(paths_leaves):
        cc = resolve_leaf_config(path, leaf, compress_small=compress_small)
        if not cc.enabled:
            cc = CompressionConfig(bits=32)
        if leaf.size >= standalone:
            out.append(_Group(cc=cc, dtype=np.dtype(leaf.dtype), indices=(i,)))
            continue
        k = (cc, np.dtype(leaf.dtype))
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(i)
    out.extend(
        _Group(cc=k[0], dtype=k[1], indices=tuple(groups[k])) for k in order
    )
    return out


def _fusion_slices(n: int, elem_size: int) -> List[Tuple[int, int]]:
    """(offset, length) slices bounded by the fusion threshold
    (CGX_FUSION_BUFFER_SIZE_MB, 64 MB default — common.h:40). Every slice is
    emitted (reference bug §8.5 not reproduced)."""
    cap = cfg_mod.fusion_threshold_elems(elem_size)
    out = []
    off = 0
    while off < n:
        ln = min(cap, n - off)
        out.append((off, ln))
        off += ln
    return out


# ---------------------------------------------------------------------------
# Trace-time layout cache. allreduce_tree used to re-derive the whole
# group/concat/split/slice plan — per-leaf path rendering, pattern-registry
# resolution, grouping and fusion arithmetic — on every call, ~4 ms of pure-
# Python glue per trace of the 473 MB GPT-2 tree (PERF_NOTES.md round 5).
# The plan is a pure function of (tree structure, leaf shapes/dtypes,
# config state), so it is computed once and memoized behind a bounded LRU;
# the registry version in the key plays the same role as make_train_step's
# trace-cache key (a re-registration must produce a fresh layout, never hit
# a stale one).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _GroupLayout:
    """One fused group's precomputed plan: member leaves, their offsets in
    the fused flat buffer, and the fusion slices of that buffer."""

    cc: CompressionConfig
    dtype: np.dtype
    indices: Tuple[int, ...]
    offsets: Tuple[int, ...]
    fused_n: int
    slices: Tuple[Tuple[int, int], ...]


@dataclasses.dataclass(frozen=True)
class _TreeLayout:
    groups: Tuple[_GroupLayout, ...]


_LAYOUT_CACHE: "OrderedDict" = OrderedDict()
_LAYOUT_CACHE_MAX = 64
_LAYOUT_STATS = {"hits": 0, "misses": 0}


def layout_cache_stats() -> Dict[str, int]:
    """Copy of the {hits, misses} counters (tests, diagnostics)."""
    return dict(_LAYOUT_STATS)


def layout_cache_clear() -> None:
    _LAYOUT_CACHE.clear()
    _LAYOUT_STATS.update(hits=0, misses=0)


def invalidate_layout_cache(reason: str = "reconfigure") -> None:
    """World-shrink invalidation entry point (recovery supervisor): every
    cached plan keyed on the dead world's registry version can never hit
    again (``survivor_mesh``/``reconfigure`` bump the version), so drop
    them outright instead of letting them age out of the LRU while
    holding their layouts live. Counted so a chaos run's report shows the
    cache was actually cycled."""
    layout_cache_clear()
    metrics.add("cgx.trace.layout_cache_invalidations")
    # Compiled schedules derive their chunk tables from the same world
    # the layouts did — a stale chunk plan after a reconfigure would
    # wedge the bridge's in-flight window against peers on the fresh
    # plan, so the two caches cycle together.
    sched_mod.invalidate_schedule_cache(reason)
    # Step plans sit above both: they were solved for this world's
    # layouts, so they cycle with them (the planner's single
    # invalidation path — docs/PERF_NOTES.md "Whole-step mega-schedule").
    planner_mod.invalidate_plan_cache(reason)
    # Staged programs compiled for the dead world's meshes: each cache
    # entry pins a compiled executable, so they drop with the layouts
    # they were traced from. Lazy via sys.modules — a process using the
    # tree-allreduce layer without the eager staged plane must not
    # import it here.
    import sys as _sys

    xla_mod = _sys.modules.get("torch_cgx_tpu.parallel.xla_allreduce")
    if xla_mod is not None:
        xla_mod.invalidate_program_cache(reason)
    from ..utils.logging import get_logger

    get_logger().info("allreduce layout cache invalidated (%s)", reason)


def _layout_key(paths_leaves, treedef, compress_small: bool, route_key):
    """Everything the layout is a function of: tree structure + leaf
    shapes/dtypes, plus every config input the grouping reads (the pattern
    registry via its version; the env-derived default config and
    thresholds re-read per call — cheap to read, included so an env flip
    between calls can never hit a stale plan). ``route_key`` is the
    topology router's (route, class) pair: a ``CGX_XLA_ALLREDUCE`` flip
    or a mesh whose groups classify differently must derive a fresh plan,
    never hit one cached for another routing era."""
    from ..wire import edges as wire_edges

    return (
        treedef,
        tuple(
            (tuple(l.shape), np.dtype(l.dtype).str) for _, l in paths_leaves
        ),
        bool(compress_small),
        route_key,
        cfg_mod.registry_version(),
        cfg_mod.default_compression_config(),
        cfg_mod.minimal_size(),
        cfg_mod.standalone_layer_elems(),
        cfg_mod.fusion_threshold_elems(1),
        # dp_grad edge entries resolve under the CGX_WIRE engagement gate,
        # so a mode/bits flip must derive a fresh plan, never hit one
        # cached for another wire era.
        wire_edges.cache_key_component(),
    )


def _tree_layout(
    paths_leaves, treedef, compress_small: bool, route_key=None
) -> _TreeLayout:
    key = _layout_key(paths_leaves, treedef, compress_small, route_key)
    hit = _LAYOUT_CACHE.get(key)
    if hit is not None:
        _LAYOUT_CACHE.move_to_end(key)
        _LAYOUT_STATS["hits"] += 1
        metrics.add("cgx.trace.layout_cache_hits")
        return hit
    _LAYOUT_STATS["misses"] += 1
    metrics.add("cgx.trace.layout_cache_misses")
    groups: List[_GroupLayout] = []
    for g in _group_leaves(paths_leaves, compress_small):
        offsets: List[int] = []
        off = 0
        for i in g.indices:
            offsets.append(off)
            off += int(paths_leaves[i][1].size)
        groups.append(
            _GroupLayout(
                cc=g.cc,
                dtype=g.dtype,
                indices=g.indices,
                offsets=tuple(offsets),
                fused_n=off,
                slices=tuple(
                    _fusion_slices(off, np.dtype(g.dtype).itemsize)
                ),
            )
        )
    layout = _TreeLayout(groups=tuple(groups))
    _LAYOUT_CACHE[key] = layout
    if len(_LAYOUT_CACHE) > _LAYOUT_CACHE_MAX:
        _LAYOUT_CACHE.popitem(last=False)
    return layout


def allreduce_flat(
    flat: jax.Array,
    cc: CompressionConfig,
    *,
    mesh,
    axes: Sequence[str],
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    return_roundtrip: bool = False,
    slices: Optional[Sequence[Tuple[int, int]]] = None,
    decision: Optional[topo_router.RouteDecision] = None,
    pre=None,
    plan: Optional[Sequence] = None,
):
    """Allreduce one fused flat buffer over 1 or 2 mesh axes (inside
    shard_map). Slicing by the fusion threshold happens here so oversized
    buffers are chunked like performOperationSingle (.cc:187-199);
    ``slices`` lets allreduce_tree hand in the layout-cache's precomputed
    plan instead of re-deriving it per call.

    ``pre``: a producer-staged stage-1 payload
    (``ops.fused_producer.Produced``) for a single-slice single-axis SRA
    buffer — consumed only when the compiled schedule (or its absence)
    matches the plan the producer quantized against; any mismatch is
    counted (``cgx.codec.producer_fallback_*``) and the plain quantize
    runs, never a silently wrong wire.

    ``return_roundtrip=True`` also returns this device's wire decode (the
    error-feedback residual base) as a second array. On the single-axis
    SRA/all-to-all paths it is computed from the SAME stage-1 payload the
    wire sends (``reducers.quantized_allreduce_with_wire`` — quantize-once
    by construction); Ring uses the hop-0 mirror, the hierarchical paths
    the per-level mirror (:func:`_stage1_roundtrip_piece`), and exact
    wires (PSUM / compression off / fake-ratio tail) round-trip unchanged.

    Topology routing (``topology.route(mesh, axes)``, computed per call
    like every CGX_* knob): intra-slice single-axis slices go through the
    staged-program wrappers (``xla_allreduce`` — same math and wire
    bytes, plus the ``cgx.xla.*`` trace accounting the bridge spans no
    longer cover), and a MIXED two-axis group under
    ``CGX_XLA_ALLREDUCE=on`` gets the reference two-level override
    (uncompressed ICI intra + compressed cross). With the knob unset on
    non-TPU backends every decision is UNROUTED and the staged program is
    bit-identical to the pre-router code. ``decision`` lets allreduce_tree
    hand in its one-per-call routing decision — it cannot differ between
    fusion groups of the same (mesh, axes) call, so per-group
    re-classification would only re-scan the mesh for the same answer.

    ``plan``: this group's per-fusion-slice ``planner.SliceDecision``
    sequence (aligned with ``slices``) when the step planner is engaged
    — each decision overrides the pipeline depth handed to the schedule
    compiler (and, under a ``CGX_PLANNER_AVG_BITS`` budget, the slice's
    wire width). None (planner disengaged) keeps every static-knob path
    bit-identical."""
    from . import xla_allreduce as xla_mod

    if decision is None:
        decision = topo_router.route(mesh, axes)
    topo = topology or cfg_mod.topology_from_env()
    if decision.route == topo_router.ROUTE_TWO_LEVEL and len(axes) == 2:
        # Reference two-level scheme for a mixed (cross x intra) group:
        # the intra stage rides ICI uncompressed (psum_scatter/all_gather
        # under the leader scheme), only the cross exchange is quantized.
        topo = topo_router.two_level_config(topo)
        metrics.add("cgx.xla.routed_two_level")
    staged = decision.route == topo_router.ROUTE_STAGED and len(axes) == 1
    n = flat.shape[0]
    ratio = cfg_mod.fake_ratio()
    if pre is not None and (
        len(axes) != 1
        or ratio is not None
        or (slices is not None and len(slices) != 1)
    ):
        metrics.add("cgx.codec.producer_fallbacks")
        metrics.add("cgx.codec.producer_fallback_routing")
        pre = None
    tail = None
    if ratio is not None and cc.enabled and n > 1:
        # Debug traffic shaping (mpi_allreduce_operations.cc:130-144): only
        # the leading ratio*n elements travel; the tail stays un-reduced.
        # The cached plan covered the full buffer — recompute for the
        # shaped prefix.
        m = max(1, int(np.ceil(ratio * n)))
        tail = lax.slice(flat, (m,), (n,))
        flat, n = lax.slice(flat, (0,), (m,)), m
        slices = None
        plan = None  # the plan was solved for the unshaped slice list
    if slices is None:
        slices = _fusion_slices(n, np.dtype(flat.dtype).itemsize)
    pieces = []
    rt_pieces = []
    for si, (off, ln) in enumerate(slices):
        piece = lax.slice(flat, (off,), (off + ln,))
        k = jax.random.fold_in(key, off) if key is not None else None
        # Step-plan decision for this fusion slice (None = legacy knobs).
        dec = plan[si] if plan is not None and si < len(plan) else None
        if len(axes) == 1:
            ws = mesh.shape[axes[0]]
            red = (
                topo.intra_reduction
                if axes[0] != mesh_mod.CROSS_AXIS
                else topo.cross_reduction
            )
            # Planner bit override (CGX_PLANNER_AVG_BITS joint solve):
            # the slice ships at the plan's width. With no budget the
            # decision carries the resolved bits and this is a no-op.
            cc_s = cc
            if (
                dec is not None
                and cc.enabled
                and 1 <= dec.bits <= cfg_mod.MAX_BITS
                and dec.bits != cc.bits
            ):
                cc_s = dataclasses.replace(cc, bits=dec.bits)
            # Schedule compiler (CGX_SCHEDULE, parallel/schedule.py): a
            # multi-chunk plan pipelines this fusion slice — chunk k+1
            # quantizes while chunk k is on the wire and chunk k-1 runs
            # the fused epilogue, all inside the same staged program.
            # None (the default everywhere off-TPU with the knob unset)
            # keeps the monolithic path bit-identical. A step-plan
            # decision replaces the static depth knob.
            sched = sched_mod.compiled_schedule(
                ln, ws, cc_s, reduction=red,
                dtype=np.dtype(flat.dtype).str, route=decision.route,
                route_staged=staged,
                chunks=dec.chunks if dec is not None else None,
            )
            # Producer-staged payload: usable only when the producer's
            # block plan matches what THIS call stages (monolithic <->
            # no schedule, per-block <-> identical table) and the slice
            # rides the multi-rank SRA transport.
            use_pre = None
            if pre is not None:
                compatible = (
                    ws > 1
                    and red == cfg_mod.REDUCTION_SRA
                    and not cfg_mod.dummy_compression()
                    # A planner bit override un-matches the producer's
                    # payload (it was quantized at the resolved width).
                    and cc_s is cc
                    and pre.n == ln
                    and (
                        (sched is None and pre.q is not None)
                        or (
                            sched is not None
                            and pre.q_blocks is not None
                            and pre.table == sched.table
                        )
                    )
                )
                if compatible:
                    use_pre = pre
                    pre.consumed = True
                    metrics.add("cgx.codec.producer_consumed_slices")
                    metrics.add(
                        "cgx.codec.producer_consumed_elems", float(ln)
                    )
                else:
                    metrics.add("cgx.codec.producer_fallbacks")
                    metrics.add("cgx.codec.producer_fallback_plan")
            if sched is not None:
                ar = functools.partial(
                    xla_mod.staged_pipelined_allreduce
                    if staged
                    else sched_mod.pipelined_quantized_allreduce,
                    sched=sched, pre=use_pre,
                )
                ar_wire = (
                    functools.partial(
                        xla_mod.staged_pipelined_allreduce_with_wire,
                        sched=sched, pre=use_pre,
                    )
                    if staged
                    else functools.partial(
                        sched_mod.pipelined_quantized_allreduce,
                        sched=sched, with_wire=True, pre=use_pre,
                    )
                )
            else:
                ar = functools.partial(
                    xla_mod.staged_quantized_allreduce
                    if staged
                    else quantized_allreduce,
                    pre=use_pre,
                )
                ar_wire = functools.partial(
                    xla_mod.staged_quantized_allreduce_with_wire
                    if staged
                    else quantized_allreduce_with_wire,
                    pre=use_pre,
                )
            if return_roundtrip:
                red_piece, rt_piece = ar_wire(piece, axes[0], ws, cc_s, red, k)
                pieces.append(red_piece)
                rt_pieces.append(rt_piece)
            else:
                pieces.append(ar(piece, axes[0], ws, cc_s, red, k))
        elif len(axes) == 2:
            cross_axis, intra_axis = axes
            pieces.append(
                hierarchical_allreduce(
                    piece,
                    intra_axis=intra_axis,
                    cross_axis=cross_axis,
                    ws_intra=mesh.shape[intra_axis],
                    ws_cross=mesh.shape[cross_axis],
                    cc=cc,
                    topology=topo,
                    key=k,
                )
            )
            if return_roundtrip:
                rt_pieces.append(
                    _stage1_roundtrip_piece(
                        piece, cc, mesh=mesh, axes=axes, topo=topo, key=k
                    )
                )
        else:
            raise ValueError(f"axes must have 1 or 2 names, got {axes!r}")
    if tail is not None:
        pieces.append(tail)
        rt_pieces.append(tail)  # never travels: exact
    out = pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)
    if not return_roundtrip:
        return out
    rt = rt_pieces[0] if len(rt_pieces) == 1 else jnp.concatenate(rt_pieces)
    return out, rt


def _roundtrip_wire_1axis(
    piece: jax.Array,
    cc: CompressionConfig,
    *,
    axis: str,
    ws: int,
    red: str,
    key: Optional[jax.Array],
    leader_rs: bool = False,
) -> jax.Array:
    """What this device's contribution to one single-axis reduction decodes
    to on the wire — per-algorithm mirror of ``quantized_allreduce``'s (or,
    with ``leader_rs``, ``reduce_scatter_quantized``'s) stage-1 layout AND
    stochastic key derivation, so the EF residual measures the same random
    draw the wire used. Only reachable from the hierarchical EF path
    (:func:`_stage1_roundtrip_piece`) — the wire itself runs inside
    ``hierarchical_allreduce`` where its payload cannot be threaded out;
    single-axis EF shares the payload via
    ``reducers.quantized_allreduce_with_wire`` instead. The per-algorithm
    mirror bodies live in ``reducers`` next to the wires they mirror."""
    from ..ops import dispatch
    from .reducers import _ring_hop0_wire, quantized_allreduce, sra_stage1_wire

    if ws == 1:
        # ws==1 runs no collective: identity, or the force-codec proxy
        # round trip — quantized_allreduce's own ws==1 branch IS the
        # wire, so reuse it verbatim.
        return quantized_allreduce(piece, axis, 1, cc, red, key)
    if not cc.enabled:
        return piece
    if leader_rs:
        # hierarchical leader scheme: stage 1 is reduce_scatter_quantized
        # regardless of the configured reduction type
        # (reducers.hierarchical_allreduce gates on intra_cc.enabled only)
        # — the SRA stage-1 layout and key.
        red = cfg_mod.REDUCTION_SRA
    if red == cfg_mod.REDUCTION_PSUM:
        return piece
    if red == cfg_mod.REDUCTION_ALLTOALL:
        # alltoall_allreduce quantizes the whole buffer as ONE row keyed
        # fold_in(key, axis_index), and every peer decodes exactly those
        # bytes — a fully mirrorable wire.
        k = (
            jax.random.fold_in(key, lax.axis_index(axis))
            if key is not None and cc.stochastic
            else None
        )
        q = dispatch.quantize_batch(piece[None], cc, k)
        return dispatch.dequantize_batch(q, out_dtype=piece.dtype)[0]
    if red == cfg_mod.REDUCTION_RING:
        return _ring_hop0_wire(piece, axis, ws, cc, key)
    # SRA: stage-1 quantizes the (ws, chunk) rows with the phase-1 key
    # (reduce_scatter_quantized) — except the own row, whose quantized copy
    # the reducer discards in favor of the raw chunk (exact round trip).
    # The allgather-phase requantization acts on the reduced chunk — not
    # per-device-attributable, treated exact.
    return sra_stage1_wire(piece, axis, ws, cc, key)


def _stage1_roundtrip_piece(
    piece: jax.Array,
    cc: CompressionConfig,
    *,
    mesh,
    axes: Sequence[str],
    topo: TopologyConfig,
    key: Optional[jax.Array],
) -> jax.Array:
    """One HIERARCHICAL fusion slice's wire decode, mirroring
    ``hierarchical_allreduce``'s prologue decision tree: exact wires
    (PSUM reduction, compression off for the stage, dummy codec, ws == 1
    without the force-codec knob) round-trip unchanged — zero residual.
    Single-axis slices never come here (``allreduce_flat`` shares their
    wire payload via ``quantized_allreduce_with_wire``)."""
    if cfg_mod.dummy_compression():
        return piece  # pass-through codec decodes exactly

    if len(axes) == 2:
        # hierarchical_allreduce prologue (reducers.py): per-level keys,
        # per-level configs and ws==1 routing must match or the residual
        # measures a different quantization than the wire's.
        cross_axis, intra_axis = axes
        ws_intra = mesh.shape[intra_axis]
        ws_cross = mesh.shape[cross_axis]
        key_intra = jax.random.fold_in(key, 3) if key is not None else None
        key_cross = jax.random.fold_in(key, 5) if key is not None else None
        intra_cc = cc if topo.intra_compress else CompressionConfig(bits=32)
        cross_cc = cc if topo.cross_compress else CompressionConfig(bits=32)
        if ws_intra == 1 and ws_cross == 1:
            return piece
        if ws_intra == 1:
            return _roundtrip_wire_1axis(
                piece, cross_cc, axis=cross_axis, ws=ws_cross,
                red=topo.cross_reduction, key=key_cross,
            )
        if ws_cross == 1 or not topo.intra_broadcast:
            # Stage 1 = a full intra allreduce via quantized_allreduce
            # (the non-leader scheme, or the degenerate single-node mesh).
            return _roundtrip_wire_1axis(
                piece, intra_cc, axis=intra_axis, ws=ws_intra,
                red=topo.intra_reduction, key=key_intra,
            )
        # Leader scheme: stage 1 is the quantized intra reduce-scatter iff
        # intra compression is on — otherwise an exact psum_scatter, and
        # the later cross-stage quantization acts on the *shared* reduced
        # chunk, which per-device EF cannot attribute (treated exact).
        if not intra_cc.enabled:
            return piece
        return _roundtrip_wire_1axis(
            piece, intra_cc, axis=intra_axis, ws=ws_intra,
            red=topo.intra_reduction, key=key_intra, leader_rs=True,
        )
    raise AssertionError(
        f"_stage1_roundtrip_piece is the hierarchical mirror; got axes={axes!r}"
    )


def allreduce_tree(
    tree,
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    average: bool = False,
    compress_small: bool = False,
    return_roundtrip: bool = False,
):
    """Quantized allreduce of a gradient pytree (call inside shard_map).

    ``average=True`` divides by the total axis world size *before*
    quantization — the reference hook's semantics (grads pre-divided in
    Python, backend sums; allreduce_hooks.py:53-54, SURVEY.md §8.12).

    ``return_roundtrip=True`` additionally returns a tree of this device's
    contribution as it decodes on the wire (``allreduce_flat(...,
    return_roundtrip=True)`` over the same fused layout — the single-axis
    SRA/all-to-all decode shares the wire's own stage-1 payload,
    quantize-once) — the error-feedback residual base. Uncompressed leaves
    round-trip unchanged (zero residual).
    """
    axes = tuple(axes)
    ws_total = int(np.prod([mesh.shape[a] for a in axes]))
    qerr = cfg_mod.qerr_stats()
    with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths_leaves = [(path_str(p), l) for p, l in with_path]
    flat_leaves = [l for _, l in paths_leaves]

    if average and ws_total > 1:
        flat_leaves = [
            (l / ws_total if _is_float(l) else l) for l in flat_leaves
        ]

    # One routing decision per call: it is a function of (mesh, axes) and
    # the CGX_* knobs only, so every fusion group below shares it (and the
    # layout key derives from the same scan instead of a second one).
    decision = topo_router.route(mesh, axes)
    groups = _tree_layout(
        paths_leaves, treedef, compress_small,
        route_key=(decision.route, decision.topo.kind),
    ).groups
    # Whole-step plan (CGX_PLANNER, parallel/planner.py): when engaged,
    # the planner sees ALL fusion slices of this layout at once and
    # jointly picks (pipeline depth, bits, emission order) against its
    # trace-calibrated cost model. Disengaged (the default everywhere
    # off-TPU) it returns None and every legacy path below is
    # bit-identical — the jaxpr pin in tests/test_planner.py.
    plan = None
    if len(axes) == 1 and planner_mod.engaged(
        decision.route == topo_router.ROUTE_STAGED
    ):
        topo_p = topology or cfg_mod.topology_from_env()
        red_p = (
            topo_p.intra_reduction
            if axes[0] != mesh_mod.CROSS_AXIS
            else topo_p.cross_reduction
        )
        plan = planner_mod.plan_for_layout(
            groups, mesh.shape[axes[0]], route=decision.route,
            reduction=red_p,
        )
    out: List[Optional[jax.Array]] = [None] * len(flat_leaves)
    rt_out: List[Optional[jax.Array]] = [None] * len(flat_leaves)
    # Emission order of the fused groups: with the schedule compiler
    # engaged (CGX_SCHEDULE), groups are STAGED in reverse-layer order —
    # backward produces the tail layers' gradients first, so their
    # collectives can start while earlier layers' gradients are still
    # being computed (the reference's DDP-hook bucket ordering as
    # emission order for the latency-hiding scheduler). Values are
    # order-invariant: each group keeps its ORIGINAL fold index ``gi``,
    # so bytes never change — only the schedule does. With the knob
    # unset off-TPU the order (and the whole staged program) is
    # unchanged.
    order = (
        plan.order
        if plan is not None
        else sched_mod.dispatch_order(len(groups))
        if sched_mod.engaged()
        else range(len(groups))
    )
    # Producer-fused stash (ops/fused_producer.py): standalone groups whose
    # leaf IS a stashed cotangent (identity match — any transformation of
    # the gradient between backward and here unmatches it) can consume the
    # backward-staged wire payload; the group's f32 quantize input then
    # goes dead and XLA DCEs the producing matmul. Lazy import: the module
    # pulls reducers/schedule at call time.
    fp_mod = None
    if len(axes) == 1:
        from ..ops import fused_producer as fp_mod

        if not (fp_mod.engaged() and fp_mod.stash_size()):
            fp_mod = None
    div_expected = ws_total if (average and ws_total > 1) else 1
    for gi in order:
        g = groups[gi]
        # distinct stochastic-rounding stream per fused group (groups would
        # otherwise share fold sequences and thus random fields)
        g_key = jax.random.fold_in(key, gi) if key is not None else None
        leaves = [flat_leaves[i] for i in g.indices]
        fused = (
            jnp.concatenate([l.reshape(-1) for l in leaves])
            if len(leaves) > 1
            else leaves[0].reshape(-1)
        )
        pre_ent = None
        if fp_mod is not None and len(g.indices) == 1 and g.cc.enabled:
            ent = fp_mod.lookup(paths_leaves[g.indices[0]][1])
            if ent is not None:
                if (
                    ent.cc == g.cc
                    and ent.ws == mesh.shape[axes[0]]
                    and ent.divisor == div_expected
                    and ent.n == g.fused_n
                    and len(g.slices) == 1
                ):
                    pre_ent = ent
                else:
                    metrics.add("cgx.codec.producer_fallbacks")
                    metrics.add("cgx.codec.producer_fallback_group")
        with named_scope(
            f"cgx_allreduce_b{g.cc.bits}_{np.dtype(g.dtype).name}"
        ):
            # NOTE: the trace.* counters increment at *trace* time (once per
            # compiled program); with CGX_METRICS_RUNTIME=1 the runtime.*
            # counters additionally bump per EXECUTION through a host
            # callback (per device program — divide by the device count for
            # per-step totals).
            if g.cc.enabled:
                metrics.add("cgx.trace.allreduce.compressed_elems", float(fused.shape[0]))
                _runtime_count("cgx.runtime.allreduce.compressed_elems", fused.shape[0])
                # Trace-time structure event (once per compiled program):
                # what this fused group ships and at what static ratio.
                from ..observability import flightrec, timeline

                topo_rec = topology or cfg_mod.topology_from_env()
                n_f = int(fused.shape[0])
                nb = -(-n_f // g.cc.bucket_size)
                wire_b = n_f * g.cc.bits / 8 + nb * 8
                group_rec = dict(
                    algo=(
                        topo_rec.cross_reduction
                        if len(axes) == 2
                        else topo_rec.intra_reduction
                    ),
                    axes=list(axes),
                    elems=n_f,
                    layers=len(g.indices),
                    bits=g.cc.bits,
                    bucket=g.cc.bucket_size,
                    wire_ratio=round(n_f * 4 / wire_b, 3),
                )
                flightrec.record("allreduce_group", **group_rec)
                timeline.instant("allreduce_group", **group_rec)
                # qerr stats need this device's wire decode even when the
                # caller (no error feedback) didn't ask for it.
                g_plan = plan.decisions[gi] if plan is not None else None
                if return_roundtrip or qerr:
                    reduced, rt_flat = allreduce_flat(
                        fused, g.cc, mesh=mesh, axes=axes, topology=topology,
                        key=g_key, return_roundtrip=True, slices=g.slices,
                        decision=decision, pre=pre_ent, plan=g_plan,
                    )
                else:
                    reduced = allreduce_flat(
                        fused, g.cc, mesh=mesh, axes=axes, topology=topology,
                        key=g_key, slices=g.slices, decision=decision,
                        pre=pre_ent, plan=g_plan,
                    )
                if pre_ent is not None and pre_ent.consumed:
                    # One payload, one spend: a second allreduce of the
                    # same tree in this trace re-quantizes normally.
                    fp_mod.claim(pre_ent.cotangent)
            else:
                metrics.add("cgx.trace.allreduce.raw_elems", float(fused.shape[0]))
                _runtime_count("cgx.runtime.allreduce.raw_elems", fused.shape[0])
                reduced = fused
                if return_roundtrip:
                    rt_flat = fused  # exact wire: zero residual
                for a in axes:
                    if mesh.shape[a] > 1:
                        reduced = lax.psum(reduced, a)
        for i, leaf, off in zip(g.indices, leaves, g.offsets):
            n = leaf.size
            out[i] = lax.slice(reduced, (off,), (off + n,)).reshape(leaf.shape)
            if return_roundtrip or (qerr and g.cc.enabled):
                rt_leaf = lax.slice(rt_flat, (off,), (off + n,)).reshape(
                    leaf.shape
                )
                if return_roundtrip:
                    rt_out[i] = rt_leaf
                if qerr and g.cc.enabled:
                    _QERR_INFO[paths_leaves[i][0]] = {
                        "numel": int(leaf.size),
                        "bits": int(g.cc.bits),
                    }
                    _report_qerr(paths_leaves[i][0], leaf, rt_leaf)
    if fp_mod is not None:
        # Unclaimed payloads would otherwise pin this trace's tracers
        # until the next step's begin_step; claimed ones are already gone.
        fp_mod.drain()
    result = jax.tree_util.tree_unflatten(treedef, out)
    if return_roundtrip:
        return result, jax.tree_util.tree_unflatten(treedef, rt_out)
    return result
