"""Tree-level gradient allreduce: layer registry + tensor fusion + dispatch.

TPU-native re-design of ``MPIAllReduce_Operation``
(/root/reference/src/mpi_allreduce_operations.cc — SURVEY.md §2.1): the
reference slices DDP buckets into per-layer views (``extractLayers``,
.cc:257-285), partitions them by compression eligibility (.cc:240-247),
fuses them into <=64 MB wire slices (.cc:201-227), and runs each slice
through the reducers. Here the "bucket" is a gradient pytree: leaves are
resolved to per-layer configs (name-pattern registry, falling back to the
``CGX_*`` env defaults re-read on every call), grouped by (config, dtype),
concatenated, split into fusion slices, reduced, and scattered back.

Fixes deliberately not inherited (SURVEY.md §8.5): every fusion batch is
flushed — the reference silently drops trailing layers after an oversized
one.

All grouping/slicing decisions are static Python (shapes + configs), so jit
caches one program per (tree structure, config) — the registry doubles as
the static-shape cache key exactly as planned in SURVEY.md §7.4.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import config as cfg_mod
from ..config import CompressionConfig, TopologyConfig
from ..utils.logging import metrics
from ..utils.tracing import named_scope
from ..utils.tree import path_str
from . import mesh as mesh_mod
from .reducers import hierarchical_allreduce, quantized_allreduce

_FLOAT_DTYPES = (jnp.float32, jnp.bfloat16, jnp.float16)


def _is_float(leaf) -> bool:
    return any(leaf.dtype == d for d in _FLOAT_DTYPES)


def resolve_leaf_config(
    path: str, leaf, *, compress_small: bool = False
) -> CompressionConfig:
    """Per-leaf config resolution.

    Mirrors the two-stage eligibility decision (SURVEY.md §8.7): the Python
    hook's ``should_compress_`` (dim<=1 or tiny tensors -> uncompressed,
    allreduce_hooks.py:42-45) and the compressor's ``isEnabled``
    (numel > minimal and bits <= 8, compressor.cc:421-425).
    """
    cc = cfg_mod.resolve_pattern_config(path) or cfg_mod.default_compression_config()
    if not _is_float(leaf):
        return dataclasses.replace(cc, bits=32)
    if leaf.size < cfg_mod.minimal_size():
        return dataclasses.replace(cc, bits=32)
    if not compress_small and leaf.ndim <= 1:
        # biases / layernorms: the hook leaves them uncompressed
        return dataclasses.replace(cc, bits=32)
    return cc


@dataclasses.dataclass(frozen=True)
class _Group:
    cc: CompressionConfig
    dtype: np.dtype
    indices: Tuple[int, ...]  # leaf positions in flattened tree


def _group_leaves(paths_leaves, compress_small: bool) -> List[_Group]:
    """Group leaves by (config, dtype) for fusion — except large leaves,
    which become standalone groups: their flat view needs no gather-concat
    or scatter-back pass (measured as the dominant codec-adjacent cost in
    the single-chip proxy, BASELINE.md). The fusion threshold inside
    allreduce_flat still chunks any oversized buffer."""
    standalone = cfg_mod.standalone_layer_elems()
    groups: Dict[Tuple, List[int]] = {}
    order: List[Tuple] = []
    out: List[_Group] = []
    for i, (path, leaf) in enumerate(paths_leaves):
        cc = resolve_leaf_config(path, leaf, compress_small=compress_small)
        if not cc.enabled:
            cc = CompressionConfig(bits=32)
        if leaf.size >= standalone:
            out.append(_Group(cc=cc, dtype=np.dtype(leaf.dtype), indices=(i,)))
            continue
        k = (cc, np.dtype(leaf.dtype))
        if k not in groups:
            groups[k] = []
            order.append(k)
        groups[k].append(i)
    out.extend(
        _Group(cc=k[0], dtype=k[1], indices=tuple(groups[k])) for k in order
    )
    return out


def _fusion_slices(n: int, elem_size: int) -> List[Tuple[int, int]]:
    """(offset, length) slices bounded by the fusion threshold
    (CGX_FUSION_BUFFER_SIZE_MB, 64 MB default — common.h:40). Every slice is
    emitted (reference bug §8.5 not reproduced)."""
    cap = cfg_mod.fusion_threshold_elems(elem_size)
    out = []
    off = 0
    while off < n:
        ln = min(cap, n - off)
        out.append((off, ln))
        off += ln
    return out


def allreduce_flat(
    flat: jax.Array,
    cc: CompressionConfig,
    *,
    mesh,
    axes: Sequence[str],
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Allreduce one fused flat buffer over 1 or 2 mesh axes (inside
    shard_map). Slicing by the fusion threshold happens here so oversized
    buffers are chunked like performOperationSingle (.cc:187-199)."""
    topo = topology or cfg_mod.topology_from_env()
    n = flat.shape[0]
    ratio = cfg_mod.fake_ratio()
    tail = None
    if ratio is not None and cc.enabled and n > 1:
        # Debug traffic shaping (mpi_allreduce_operations.cc:130-144): only
        # the leading ratio*n elements travel; the tail stays un-reduced.
        m = max(1, int(np.ceil(ratio * n)))
        tail = lax.slice(flat, (m,), (n,))
        flat, n = lax.slice(flat, (0,), (m,)), m
    pieces = []
    for off, ln in _fusion_slices(n, np.dtype(flat.dtype).itemsize):
        piece = lax.slice(flat, (off,), (off + ln,))
        k = jax.random.fold_in(key, off) if key is not None else None
        if len(axes) == 1:
            ws = mesh.shape[axes[0]]
            red = (
                topo.intra_reduction
                if axes[0] != mesh_mod.CROSS_AXIS
                else topo.cross_reduction
            )
            pieces.append(quantized_allreduce(piece, axes[0], ws, cc, red, k))
        elif len(axes) == 2:
            cross_axis, intra_axis = axes
            pieces.append(
                hierarchical_allreduce(
                    piece,
                    intra_axis=intra_axis,
                    cross_axis=cross_axis,
                    ws_intra=mesh.shape[intra_axis],
                    ws_cross=mesh.shape[cross_axis],
                    cc=cc,
                    topology=topo,
                    key=k,
                )
            )
        else:
            raise ValueError(f"axes must have 1 or 2 names, got {axes!r}")
    if tail is not None:
        pieces.append(tail)
    return pieces[0] if len(pieces) == 1 else jnp.concatenate(pieces)


def allreduce_tree(
    tree,
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    average: bool = False,
    compress_small: bool = False,
):
    """Quantized allreduce of a gradient pytree (call inside shard_map).

    ``average=True`` divides by the total axis world size *before*
    quantization — the reference hook's semantics (grads pre-divided in
    Python, backend sums; allreduce_hooks.py:53-54, SURVEY.md §8.12).
    """
    axes = tuple(axes)
    ws_total = int(np.prod([mesh.shape[a] for a in axes]))
    with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    paths_leaves = [(path_str(p), l) for p, l in with_path]
    flat_leaves = [l for _, l in paths_leaves]

    if average and ws_total > 1:
        flat_leaves = [
            (l / ws_total if _is_float(l) else l) for l in flat_leaves
        ]

    groups = _group_leaves(paths_leaves, compress_small)
    out: List[Optional[jax.Array]] = [None] * len(flat_leaves)
    for gi, g in enumerate(groups):
        # distinct stochastic-rounding stream per fused group (groups would
        # otherwise share fold sequences and thus random fields)
        g_key = jax.random.fold_in(key, gi) if key is not None else None
        leaves = [flat_leaves[i] for i in g.indices]
        fused = (
            jnp.concatenate([l.reshape(-1) for l in leaves])
            if len(leaves) > 1
            else leaves[0].reshape(-1)
        )
        with named_scope(
            f"cgx_allreduce_b{g.cc.bits}_{np.dtype(g.dtype).name}"
        ):
            # NOTE: these counters increment at *trace* time (once per
            # compiled program), so they measure elems per traced allreduce
            # program, not per executed step.
            if g.cc.enabled:
                metrics.add("trace.allreduce.compressed_elems", float(fused.shape[0]))
                reduced = allreduce_flat(
                    fused, g.cc, mesh=mesh, axes=axes, topology=topology,
                    key=g_key,
                )
            else:
                metrics.add("trace.allreduce.raw_elems", float(fused.shape[0]))
                reduced = fused
                for a in axes:
                    if mesh.shape[a] > 1:
                        reduced = lax.psum(reduced, a)
        off = 0
        for i, leaf in zip(g.indices, leaves):
            n = leaf.size
            out[i] = lax.slice(reduced, (off,), (off + n,)).reshape(leaf.shape)
            off += n
    return jax.tree_util.tree_unflatten(treedef, out)
