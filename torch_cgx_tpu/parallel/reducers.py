"""Quantized collective reduction algorithms over mesh axes.

TPU-native re-design of the reference reducer layer
(/root/reference/src/common/scatter_reduce_allgather.cc, ring.cc,
reducer.cc — SURVEY.md §2.1, §3.2). The reference moves hand-packed byte
buffers through MPI/SHM point-to-point transports; here every algorithm is a
pure function **inside ``shard_map``** composed from XLA collectives:

* SRA (Scatter-Reduce-AllGather, the flagship,
  scatter_reduce_allgather.cc:94-202)  ->  ``lax.all_to_all`` of quantized
  chunk payloads + a dispatched decompress-accumulate-requantize epilogue
  (``ops.dispatch.reduce_rows_requantize``: ONE fused Pallas HBM pass on
  TPU, staged reference ops elsewhere — wire bytes identical) +
  ``lax.all_gather``.
* Ring (ring.cc:139-226)  ->  ``lax.ppermute`` ring with per-hop
  requantization in the scatter-reduce phase and a circulate-once-quantized
  allgather phase.
* All-to-all (debug, scatter_reduce_allgather.cc:269-306)  ->  quantize once,
  ``all_gather`` everything, decompress-accumulate.
* Uncompressed fallback  ->  plain ``lax.psum`` (the reference's raw SRA/ring
  staging machinery is exactly what XLA's native allreduce already does
  better on ICI).

Error-symmetry invariant (load-bearing for the bit-exactness oracle): after
reduction, every device's final values are decoded from the *same* quantized
payload — the reference achieves this by requantize + self-dequantize of the
owned chunk (scatter_reduce_allgather.cc:157-160, reducer.cc:111-116); here
the owner's final chunk is likewise its own decoded ``all_gather`` row.

Chunking: XLA needs static shapes, so chunks are the equal split of ``n``
over the axis, rounded up to the 32-value packing group (the TPU analogue of
the reference's 4/8-element aligned greedy split,
compressor.cc:265-299); quantization buckets restart per chunk, preserving
the per-bucket error envelope. Padding uses edge values so constant buckets
stay constant (exactness oracle).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import compat

from .. import config as cfg_mod
from ..config import CompressionConfig, TopologyConfig
from ..ops import codec, dispatch
from ..utils.tree import round_up


def _chunk_size(n: int, ws: int) -> int:
    return round_up(-(-n // ws), codec.LANE_GROUP) if n else codec.LANE_GROUP


def chunk_layout(n: int, ws: int) -> Tuple[int, int]:
    """(chunk elements per rank, padded total) of the SRA/Ring wire layout
    for ``n`` fused elements over ``ws`` ranks — a pure function of its
    arguments, which is the survivor-re-derivation contract the recovery
    supervisor relies on: after a world shrink nothing here is cached, so
    the next trace (forced by the bumped registry version) derives the
    ws-1 layout from scratch. Exposed for the shrunk-world tests and for
    tooling that wants to reason about wire bytes without tracing."""
    chunk = _chunk_size(n, ws)
    return chunk, chunk * ws


def _pad_rows(x: jax.Array, ws: int, chunk: int) -> jax.Array:
    """Edge-pad flat x to (ws, chunk)."""
    total = ws * chunk
    pad = total - x.shape[0]
    if pad:
        x = jnp.pad(x, (0, pad), mode="edge")
    return x.reshape(ws, chunk)


def _quantize_rows(xs: jax.Array, cc: CompressionConfig, key=None) -> codec.QTensor:
    """Row-batched quantize via the impl dispatcher (Pallas on TPU)."""
    return dispatch.quantize_batch(xs, cc, key if cc.stochastic else None)


def _quantize_1d(x: jax.Array, cc: CompressionConfig, key=None) -> codec.QTensor:
    """Single-buffer quantize as a rows=1 batch (keeps the Pallas fast path;
    leading dim threads through ppermute/all_gather untouched)."""
    return _quantize_rows(x[None], cc, key)


def _dequantize_rows(q: codec.QTensor) -> jax.Array:
    return dispatch.dequantize_batch(q, out_dtype=jnp.float32)


def _dequantize_1d(q: codec.QTensor, add_to: Optional[jax.Array] = None) -> jax.Array:
    return dispatch.dequantize_batch(
        q, add_to=None if add_to is None else add_to[None], out_dtype=jnp.float32
    )[0]


def _gather_rows(q: codec.QTensor, axis_name: str):
    """all_gather a rows=1 QTensor into a rows=ws QTensor (tiled concat)."""
    return jax.tree.map(
        lambda a: lax.all_gather(a, axis_name, axis=0, tiled=True), q
    )


def _shift_right(q, axis_name: str, ws: int):
    perm = [(i, (i + 1) % ws) for i in range(ws)]
    return jax.tree.map(lambda a: lax.ppermute(a, axis_name, perm), q)


# ---------------------------------------------------------------------------
# SRA building blocks (factored so the hierarchical scheme can compose them).
# ---------------------------------------------------------------------------


def _phase_key(key, salt: int, axis_name: str):
    """Decorrelate stochastic-rounding streams across devices AND phases.

    The reference seeds per-process with time() (compressor.cc:441); here the
    device stream is fold_in(axis_index) and ``salt`` separates the
    reduce-scatter / allgather / hierarchical-level phases so no two
    quantizations of related data share a random field.
    """
    if key is None:
        return None
    return jax.random.fold_in(jax.random.fold_in(key, salt), lax.axis_index(axis_name))


def _sra_exchange(x, axis_name: str, ws: int, cc, key, pre=None):
    """SRA stage-1 wire: quantize the padded (ws, chunk) rows with the
    phase-1 key and exchange via all_to_all. Returns
    ``(q, q_recv, xs, own_idx)`` — the sent payload, the received peer
    payloads (row j = this device's chunk as peer j quantized it), the raw
    padded rows, and this device's axis position. Factored so every SRA
    variant (plain / with-wire / reduce-scatter) shares ONE wire
    implementation and the epilogue can be dispatched fused or staged.

    ``pre``: a producer-staged stage-1 payload
    (``ops.fused_producer.Produced`` — ``pre.q`` the already-quantized
    (ws, chunk) rows, ``pre.raw_row`` the raw own chunk): the quantize is
    skipped entirely and ``xs`` is None — the f32 buffer is never read,
    which is the whole point (callers substitute ``pre.raw_row`` for the
    own-row slice of ``xs``)."""
    if pre is not None:
        q = pre.q
        q_recv = jax.tree.map(
            lambda a: lax.all_to_all(a, axis_name, 0, 0), q
        )
        return q, q_recv, None, lax.axis_index(axis_name)
    xs = _pad_rows(x, ws, _chunk_size(x.shape[0], ws))
    q = _quantize_rows(xs, cc, _phase_key(key, 1, axis_name))
    q_recv = jax.tree.map(lambda a: lax.all_to_all(a, axis_name, 0, 0), q)
    return q, q_recv, xs, lax.axis_index(axis_name)


def _sra_stage1(x, axis_name: str, ws: int, cc, key):
    """Shared SRA stage-1 body: :func:`_sra_exchange` +
    decompress-accumulate into the RAW own chunk (the row arriving from
    oneself is one's own quantized chunk — the raw values are swapped in
    instead, free accuracy the SPMD form doesn't forfeit). The epilogue
    runs through ``dispatch.reduce_rows`` — fused single-pass kernel on
    TPU, the staged decode/select/sum elsewhere. Returns
    ``(reduced_chunk, q, xs, own)`` so the EF variant can decode the SAME
    payload ``q`` the wire sent (one implementation — the reducer and its
    wire mirror cannot drift)."""
    q, q_recv, xs, own_idx = _sra_exchange(x, axis_name, ws, cc, key)
    reduced = dispatch.reduce_rows(q_recv, raw_rows=xs, own_idx=own_idx)
    own = (jnp.arange(ws) == own_idx)[:, None]
    return reduced, q, xs, own


def reduce_scatter_quantized(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """SRA round 1 (scatter_reduce_allgather.cc:116-155): quantize the peers'
    chunks, exchange via all_to_all, decompress-accumulate into the RAW own
    chunk — one's own contribution stays exact during scatter-reduce, like
    the reference (it accumulates peers into the unquantized owned slice,
    .cc:116-155); only the ws-1 peer contributions carry quantization error.

    Returns this device's reduced chunk, float32[chunk_size(n, ws)].
    """
    return _sra_stage1(x, axis_name, ws, cc, key)[0]


def allgather_quantized(
    chunk_f32: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    n: int,
    out_dtype,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """SRA round 2 (scatter_reduce_allgather.cc:161-200): requantize the
    owned chunk, all_gather, decode every row — including one's own, which
    realizes the requant+self-dequant error-symmetry trick
    (scatter_reduce_allgather.cc:157-160)."""
    key = _phase_key(key, 2, axis_name)
    q_own = _quantize_1d(chunk_f32.astype(out_dtype), cc, key if cc.stochastic else None)
    gathered = _gather_rows(q_own, axis_name)
    vals = _dequantize_rows(gathered)  # (ws, chunk)
    return vals.reshape(-1)[:n].astype(out_dtype)


def _sra_epilogue_q(
    q_recv, xs, own_idx, axis_name, cc, key, out_dtype, raw_row=None
):
    """Shared SRA epilogue: the stage-2 wire payload of the reduced chunk,
    via ``dispatch.reduce_rows_requantize`` — ONE fused
    dequant-accumulate-requantize HBM pass on TPU (the (ws, chunk) f32
    intermediate of the staged form never materializes), the staged
    reference ops elsewhere. ``raw_row`` is the pre-sliced own chunk of a
    producer-staged caller (``xs`` is then None). Wire bytes identical
    across lowerings on the default ``div`` encode (jaxpr-guarded in
    test_reducers)."""
    return dispatch.reduce_rows_requantize(
        q_recv,
        cc,
        raw_rows=xs,
        raw_row=raw_row,
        own_idx=own_idx,
        key=_phase_key(key, 2, axis_name) if cc.stochastic else None,
        out_dtype=out_dtype,
    )


def sra_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
    pre=None,
) -> jax.Array:
    """Quantized Scatter-Reduce-AllGather allreduce (the flagship algorithm,
    ``MPI_Allreduce_ScatterReduceAllgather::AllreduceCompressed``).

    Stage 1 quantizes + all_to_alls the peer chunks; the epilogue
    (decompress-accumulate + requantize-reduced,
    scatter_reduce_allgather.cc:116-160) is a single dispatched op; stage 2
    all_gathers the requantized chunk and decodes every row — including
    one's own, realizing the requant+self-dequant error-symmetry trick
    (scatter_reduce_allgather.cc:157-160). ``pre``: producer-staged
    stage-1 payload (see :func:`_sra_exchange`) — ``x`` then contributes
    only its static shape/dtype and its producer is dead code."""
    n = x.shape[0]
    _, q_recv, xs, own_idx = _sra_exchange(x, axis_name, ws, cc, key, pre)
    q_own = _sra_epilogue_q(
        q_recv, xs, own_idx, axis_name, cc, key, x.dtype,
        raw_row=pre.raw_row if pre is not None else None,
    )
    gathered = _gather_rows(q_own, axis_name)
    vals = _dequantize_rows(gathered)  # (ws, chunk)
    return vals.reshape(-1)[:n].astype(x.dtype)


# ---------------------------------------------------------------------------
# Ring (ring.cc:139-226).
# ---------------------------------------------------------------------------


def ring_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Quantized ring allreduce: 2*(ws-1) ppermute steps.

    Scatter-reduce phase requantizes the accumulated outgoing segment each
    hop (compounding quantization like ring.cc:170-188); the allgather phase
    circulates each owner's once-quantized payload so all devices decode
    identical bytes (ring.cc:190-224).

    Both phases are ``lax.scan`` loops — the reference rings are runtime
    loops too (ring.cc:170-224), and an unrolled form would trace a
    quantize/dequantize pair per hop, growing trace+compile cost O(ws)
    (126 codec traces per fusion slice on a v5p-64 cross ring). The scan
    traces each phase's codec exactly once; program size is O(1) in ws
    (regression-guarded in test_reducers.py). Wire bytes and outputs are
    bit-identical to the unrolled form (:func:`_ring_allreduce_unrolled`,
    kept as the suite's oracle): the hop index enters only modular index
    arithmetic and ``fold_in`` salts, both value-deterministic whether the
    index is a Python int or a scan-carried scalar.
    """
    n = x.shape[0]
    dtype = x.dtype
    if ws == 1:
        return x
    seg = _chunk_size(n, ws)
    rank = lax.axis_index(axis_name)
    acc = _pad_rows(x.astype(jnp.float32), ws, seg)
    use_key = key is not None and cc.stochastic

    def row(a, idx):
        return lax.dynamic_slice(a, (idx, 0), (1, seg))[0]

    # Phase 1: scatter-reduce. Device r sends segment (r - step) mod ws and
    # accumulates incoming segment (r - step - 1) mod ws.
    def scatter_step(acc, step):
        send_idx = (rank - step) % ws
        seg_out = row(acc, send_idx).astype(dtype)
        k = jax.random.fold_in(jax.random.fold_in(key, step), rank) if (
            use_key
        ) else None
        q = _quantize_1d(seg_out, cc, k)
        q_in = _shift_right(q, axis_name, ws)
        recv_idx = (rank - step - 1) % ws
        # Per-hop decompress-add through the dispatcher (the rows=1
        # accumulate form — UnpackArray<ADD>): byte-identical to
        # _dequantize_1d(add_to=...) by construction, and the unrolled
        # oracle below keeps the direct spelling so the two stay honest.
        updated = dispatch.reduce_rows(q_in, add_to=row(acc, recv_idx))
        return lax.dynamic_update_slice(acc, updated[None], (recv_idx, 0)), None

    acc, _ = lax.scan(scatter_step, acc, jnp.arange(ws - 1))

    # Phase 2: allgather. Device r owns fully-reduced segment (r + 1) mod ws;
    # quantize once (+ self-decode) and circulate the payload ws-1 times.
    own_idx = (rank + 1) % ws
    k = jax.random.fold_in(jax.random.fold_in(key, ws), rank) if (
        use_key
    ) else None
    q_own = _quantize_1d(row(acc, own_idx).astype(dtype), cc, k)
    out = jnp.zeros((ws, seg), jnp.float32)
    out = lax.dynamic_update_slice(out, _dequantize_1d(q_own)[None], (own_idx, 0))

    def gather_step(carry, step):
        out, cur = carry
        cur = _shift_right(cur, axis_name, ws)
        idx = (rank - step) % ws
        out = lax.dynamic_update_slice(out, _dequantize_1d(cur)[None], (idx, 0))
        return (out, cur), None

    (out, _), _ = lax.scan(gather_step, (out, q_own), jnp.arange(ws - 1))
    return out.reshape(-1)[:n].astype(dtype)


def _ring_allreduce_unrolled(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Python-unrolled ring (the pre-scan form) — the suite's oracle that
    :func:`ring_allreduce`'s scan emits identical bytes hop for hop. Not a
    production path: trace cost grows O(ws)."""
    n = x.shape[0]
    dtype = x.dtype
    if ws == 1:
        return x
    seg = _chunk_size(n, ws)
    rank = lax.axis_index(axis_name)
    acc = _pad_rows(x.astype(jnp.float32), ws, seg)
    use_key = key is not None and cc.stochastic

    def row(a, idx):
        return lax.dynamic_slice(a, (idx, 0), (1, seg))[0]

    for step in range(ws - 1):
        send_idx = (rank - step) % ws
        seg_out = row(acc, send_idx).astype(dtype)
        k = jax.random.fold_in(jax.random.fold_in(key, step), rank) if (
            use_key
        ) else None
        q = _quantize_1d(seg_out, cc, k)
        q_in = _shift_right(q, axis_name, ws)
        recv_idx = (rank - step - 1) % ws
        updated = _dequantize_1d(q_in, add_to=row(acc, recv_idx))
        acc = lax.dynamic_update_slice(acc, updated[None], (recv_idx, 0))

    own_idx = (rank + 1) % ws
    k = jax.random.fold_in(jax.random.fold_in(key, ws), rank) if (
        use_key
    ) else None
    q_own = _quantize_1d(row(acc, own_idx).astype(dtype), cc, k)
    out = jnp.zeros((ws, seg), jnp.float32)
    out = lax.dynamic_update_slice(out, _dequantize_1d(q_own)[None], (own_idx, 0))
    cur = q_own
    for step in range(ws - 1):
        cur = _shift_right(cur, axis_name, ws)
        idx = (rank - step) % ws
        out = lax.dynamic_update_slice(out, _dequantize_1d(cur)[None], (idx, 0))
    return out.reshape(-1)[:n].astype(dtype)


# ---------------------------------------------------------------------------
# All-to-all (debug path) + dispatch.
# ---------------------------------------------------------------------------


def alltoall_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Compress once, broadcast to all, decompress-accumulate everywhere
    (AllReduceAlltoAllCompressed, scatter_reduce_allgather.cc:269-306).
    O(ws * n) traffic — debug/small-tensor path only. (One body with the
    EF variant; XLA dead-code-eliminates the unused wire decode.)"""
    return alltoall_allreduce_with_wire(x, axis_name, ws, cc, key)[0]


def sra_allreduce_with_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
    pre=None,
):
    """SRA allreduce that ALSO returns this device's wire decode (the
    error-feedback residual base): ``(reduced, rt)`` where ``rt`` is what
    the peers decode from this device's stage-1 payload, own chunk raw
    (reduce_scatter discards the own quantized row for the raw slice).

    The decode comes from the SAME stage-1 ``QTensor`` the wire sends —
    quantize-once *by construction*. The previous EF path re-quantized
    the identical rows in a separate mirror (``_roundtrip_wire_1axis``)
    and relied on XLA to CSE the duplicate; plain-XLA codec ops do CSE,
    but Pallas kernels lower to custom calls XLA treats conservatively,
    so on TPU the mirror could cost a full extra quantize pass per step.
    Sharing the tensor also removes the mirror's key-derivation fragility
    (the mirror had to replicate ``_phase_key`` exactly or the residual
    measured a different random draw than the wire's)."""
    n = x.shape[0]
    q, q_recv, xs, own_idx = _sra_exchange(x, axis_name, ws, cc, key, pre)
    own = (jnp.arange(ws) == own_idx)[:, None]
    rt_rows = _dequantize_rows(q)
    raw_b = (
        xs if pre is None else pre.raw_row[None]
    )  # producer path: only the own row is raw, and only it is selected
    rt = (
        jnp.where(own, raw_b.astype(rt_rows.dtype), rt_rows)
        .reshape(-1)[:n]
        .astype(x.dtype)
    )
    q_own = _sra_epilogue_q(
        q_recv, xs, own_idx, axis_name, cc, key, x.dtype,
        raw_row=pre.raw_row if pre is not None else None,
    )
    gathered = _gather_rows(q_own, axis_name)
    out = _dequantize_rows(gathered).reshape(-1)[:n].astype(x.dtype)
    return out, rt


def alltoall_allreduce_with_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
):
    """:func:`alltoall_allreduce` + this device's wire decode from the same
    payload (every peer decodes exactly these bytes — the whole buffer is
    one quantized row)."""
    k = None
    if key is not None and cc.stochastic:
        k = jax.random.fold_in(key, lax.axis_index(axis_name))
    q = _quantize_1d(x, cc, k)
    rt = _dequantize_1d(q).astype(x.dtype)
    gathered = _gather_rows(q, axis_name)
    return dispatch.reduce_rows(gathered).astype(x.dtype), rt


def sra_wire_frames(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
):
    """SRA allreduce with BOTH wire payloads threaded out (introspection
    for the staged-program parity suite and bench byte pre-flights):
    ``(out, q_sent, q_own)`` — the reduced buffer, the stage-1 (ws, chunk)
    ``QTensor`` this device sent into the all_to_all, and the stage-2
    requantized own chunk it all_gathers. One wire implementation
    (:func:`_sra_exchange` / :func:`_sra_epilogue_q`), so the frames can
    never drift from what :func:`sra_allreduce` actually ships."""
    n = x.shape[0]
    q, q_recv, xs, own_idx = _sra_exchange(x, axis_name, ws, cc, key)
    q_own = _sra_epilogue_q(q_recv, xs, own_idx, axis_name, cc, key, x.dtype)
    gathered = _gather_rows(q_own, axis_name)
    out = _dequantize_rows(gathered).reshape(-1)[:n].astype(x.dtype)
    return out, q, q_own


def sra_stage1_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Mirror of SRA's stage-1 wire decode WITHOUT running the collective:
    what the peers decode from this device's payload, own row raw. Used by
    the hierarchical EF path, where the wire itself runs inside
    :func:`hierarchical_allreduce` and the payload cannot be threaded out;
    single-axis callers should prefer :func:`sra_allreduce_with_wire`
    (shares the payload, quantize-once)."""
    n = x.shape[0]
    rows = _pad_rows(x, ws, _chunk_size(n, ws))
    q = _quantize_rows(rows, cc, _phase_key(key, 1, axis_name))
    vals = _dequantize_rows(q)
    own = (jnp.arange(ws) == lax.axis_index(axis_name))[:, None]
    return (
        jnp.where(own, rows.astype(vals.dtype), vals)
        .reshape(-1)[:n]
        .astype(x.dtype)
    )


def _ring_hop0_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    key: Optional[jax.Array],
) -> jax.Array:
    """Ring's EF residual base: the only per-device-attributable
    quantization of RAW data is the step-0 hop of the own outgoing segment
    (row index = rank), keyed ``fold_in(fold_in(key, 0), rank)`` like
    ``ring_allreduce``'s first scatter step. Later hops requantize
    accumulated sums — treated exact for EF purposes (documented
    approximation). This is a mirror (the hop lives inside a ``lax.scan``
    the payload cannot be threaded out of); it re-quantizes 1/ws of the
    buffer."""
    n = x.shape[0]
    chunk = _chunk_size(n, ws)
    rank = lax.axis_index(axis_name)
    rows = _pad_rows(x, ws, chunk)
    own = lax.dynamic_slice(rows, (rank, 0), (1, chunk))
    k = (
        jax.random.fold_in(jax.random.fold_in(key, 0), rank)
        if key is not None and cc.stochastic
        else None
    )
    q = dispatch.quantize_batch(own, cc, k)
    rt_own = dispatch.dequantize_batch(q, out_dtype=x.dtype)
    rows = lax.dynamic_update_slice(rows, rt_own, (rank, 0))
    return rows.reshape(-1)[:n]


def quantized_allreduce_with_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    pre=None,
):
    """:func:`quantized_allreduce` + this device's wire decode ``rt``
    (``(reduced, rt)``) for the error-feedback residual. Exact wires
    (PSUM, compression off, dummy codec, ws == 1 without the force-codec
    knob) round-trip unchanged: ``rt = x``. SRA and all-to-all share the
    wire payload (quantize-once); Ring uses the hop-0 mirror. ``pre``
    (producer-staged stage-1 payload) is SRA-only — any other branch with
    it is a routing bug and raises."""
    if pre is not None and (
        reduction != cfg_mod.REDUCTION_SRA
        or ws == 1
        or not cc.enabled
        or cfg_mod.dummy_compression()
    ):
        raise ValueError(
            "producer-staged payloads route only to the multi-rank SRA "
            f"transport (got reduction={reduction!r}, ws={ws})"
        )
    if ws == 1:
        out = quantized_allreduce(x, axis_name, ws, cc, reduction, key)
        # force-codec proxy: the single-rank "wire" decode IS the output;
        # plain ws==1 is the identity (zero residual) either way.
        return out, out
    if cfg_mod.dummy_compression() or not cc.enabled or (
        reduction == cfg_mod.REDUCTION_PSUM
    ):
        return quantized_allreduce(x, axis_name, ws, cc, reduction, key), x
    if reduction == cfg_mod.REDUCTION_SRA:
        return sra_allreduce_with_wire(x, axis_name, ws, cc, key, pre)
    if reduction == cfg_mod.REDUCTION_ALLTOALL:
        return alltoall_allreduce_with_wire(x, axis_name, ws, cc, key)
    if reduction == cfg_mod.REDUCTION_RING:
        return (
            ring_allreduce(x, axis_name, ws, cc, key),
            _ring_hop0_wire(x, axis_name, ws, cc, key),
        )
    raise ValueError(f"unknown reduction {reduction!r}")


def quantized_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    pre=None,
) -> jax.Array:
    """Dispatch on the reduction algorithm (CGX_*_REDUCTION_TYPE analogue,
    mpi_allreduce_operations.cc:70-115). Flat (non-hierarchical) allreduce
    of a 1-D buffer inside shard_map. ``pre`` (producer-staged stage-1
    payload) is SRA-only."""
    if pre is not None and (
        reduction != cfg_mod.REDUCTION_SRA
        or ws == 1
        or not cc.enabled
        or cfg_mod.dummy_compression()
    ):
        raise ValueError(
            "producer-staged payloads route only to the multi-rank SRA "
            f"transport (got reduction={reduction!r}, ws={ws})"
        )
    if ws == 1:
        if cc.enabled and cfg_mod.force_codec():
            # CGX_DEBUG_FORCE_CODEC: emulate the per-rank codec work of a
            # real SRA step so one chip can measure codec overhead in a
            # real train step.
            q = _quantize_1d(x, cc, key)
            if dispatch.fused_epilogue_would_run(
                q, stochastic=cc.stochastic and key is not None
            ):
                # Fused-epilogue era: a real rank runs stage-1 quantize ->
                # ONE fused dequant-accumulate-requantize pass over the
                # arriving payloads (~n packed values across the ws rows)
                # -> allgather decode. Emulate exactly that kernel
                # sequence (rows=1 epilogue over the full payload) so the
                # train-step probe measures the production shape; the
                # value is the double round trip decode(requant(decode)),
                # still inside 2x the quantization envelope.
                k2 = _phase_key(key, 2, axis_name) if cc.stochastic else None
                q2 = dispatch.reduce_rows_requantize(
                    q, cc, key=k2, out_dtype=x.dtype
                )
                return _dequantize_1d(q2).astype(x.dtype)
            # Staged era. Per rank at world size ws, SRA quantizes
            # ~n*(1+1/ws) values (peer chunks + requantized own chunk) and
            # dequantizes ~n*(2-1/ws) (decompress-add in reduce-scatter,
            # decode in allgather) — so the proxy runs ONE quantize and
            # TWO decodes (one through the add_to accumulate path, like
            # phase 1). Averaging the two identical decodes keeps both
            # live without changing the value beyond float round-off.
            dec_assign = _dequantize_1d(q)
            dec_acc = _dequantize_1d(q, add_to=x) - x.astype(jnp.float32)
            return ((dec_assign + dec_acc) * 0.5).astype(x.dtype)
        return x
    if cfg_mod.dummy_compression():
        # Debug pass-through codec: correctness of the transport alone.
        q = codec.quantize_dummy(x)
        gathered = jax.tree.map(lambda a: lax.all_gather(a, axis_name, axis=0), q)
        vals = jax.vmap(lambda qq: codec.dequantize_dummy(qq, out_dtype=jnp.float32))(
            gathered
        )
        return jnp.sum(vals, axis=0).astype(x.dtype)
    if not cc.enabled or reduction == cfg_mod.REDUCTION_PSUM:
        return lax.psum(x, axis_name)
    if reduction == cfg_mod.REDUCTION_SRA:
        return sra_allreduce(x, axis_name, ws, cc, key, pre)
    if reduction == cfg_mod.REDUCTION_RING:
        return ring_allreduce(x, axis_name, ws, cc, key)
    if reduction == cfg_mod.REDUCTION_ALLTOALL:
        return alltoall_allreduce(x, axis_name, ws, cc, key)
    raise ValueError(f"unknown reduction {reduction!r}")


# ---------------------------------------------------------------------------
# Hierarchical (ICI x DCN) allreduce — mpi_allreduce_operations.cc:139-185.
# ---------------------------------------------------------------------------


def hierarchical_allreduce(
    x: jax.Array,
    *,
    intra_axis: str,
    cross_axis: str,
    ws_intra: int,
    ws_cross: int,
    cc: CompressionConfig,
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
) -> jax.Array:
    """Two-level allreduce over a (cross, intra) mesh.

    ``intra_broadcast`` (default, mpi_allreduce_operations.cc:160-183): the
    reference reduces node-locally, lets only local rank 0 cross-reduce, then
    broadcasts node-locally. The SPMD-native equivalent with identical
    traffic shape and *better* DCN utilization: quantized reduce-scatter on
    ICI -> each intra-position cross-reduces only its owned chunk on DCN ->
    quantized all_gather on ICI. Non-leader mode = full intra allreduce
    followed by full cross allreduce (every rank's copy crosses DCN, like
    intra_broadcast=0 in the reference).
    """
    topo = topology or cfg_mod.topology_from_env()
    n = x.shape[0]
    # Separate the two levels' stochastic streams: a device's intra and cross
    # axis_index can coincide, so phase salts alone don't decorrelate them.
    key_intra = jax.random.fold_in(key, 3) if key is not None else None
    key_cross = jax.random.fold_in(key, 5) if key is not None else None
    if ws_intra == 1 and ws_cross == 1:
        return x
    if ws_intra == 1:
        return quantized_allreduce(
            x, cross_axis, ws_cross,
            cc if topo.cross_compress else CompressionConfig(bits=32),
            topo.cross_reduction, key_cross,
        )
    if ws_cross == 1:
        return quantized_allreduce(
            x, intra_axis, ws_intra,
            cc if topo.intra_compress else CompressionConfig(bits=32),
            topo.intra_reduction, key_intra,
        )

    intra_cc = cc if topo.intra_compress else CompressionConfig(bits=32)
    cross_cc = cc if topo.cross_compress else CompressionConfig(bits=32)

    if not topo.intra_broadcast:
        y = quantized_allreduce(x, intra_axis, ws_intra, intra_cc,
                                topo.intra_reduction, key_intra)
        return quantized_allreduce(y, cross_axis, ws_cross, cross_cc,
                                   topo.cross_reduction, key_cross)

    # Leader scheme, SPMD-style.
    if intra_cc.enabled and not cfg_mod.dummy_compression():
        chunk = reduce_scatter_quantized(x, intra_axis, ws_intra, intra_cc, key_intra)
    else:
        pad_n = ws_intra * _chunk_size(n, ws_intra)
        xp = jnp.pad(x.astype(jnp.float32), (0, pad_n - n), mode="edge")
        chunk = lax.psum_scatter(xp, intra_axis, scatter_dimension=0, tiled=True)
    chunk = quantized_allreduce(
        chunk.astype(x.dtype), cross_axis, ws_cross, cross_cc,
        topo.cross_reduction, key_cross,
    ).astype(jnp.float32)
    if intra_cc.enabled and not cfg_mod.dummy_compression():
        return allgather_quantized(
            chunk, intra_axis, ws_intra, intra_cc, n, x.dtype, key_intra
        )
    full = lax.all_gather(chunk, intra_axis, axis=0).reshape(-1)
    return full[:n].astype(x.dtype)


def quantized_ppermute(
    x: jax.Array,
    axis_name: str,
    perm,
    cc: Optional[CompressionConfig] = None,
    *,
    key: Optional[jax.Array] = None,
):
    """``lax.ppermute`` with the payload quantized on the wire.

    Beyond the reference (which compresses only gradient allreduce): the
    same max-min codec applied to point-to-point activation transport —
    pipeline-stage hops, ring exchanges. The payload travels as packed
    bit-planes + per-bucket meta (``bits/32`` of the fp32 footprint, plus
    meta) and is decoded on arrival.

    Differentiable via a straight-through estimator: the cotangent hop runs
    the same quantized transport over the INVERSE permutation (the
    transpose of a ppermute), so backward traffic is compressed too. The
    codec round trip's jacobian is approximated as identity — standard STE,
    sound for the small per-bucket error the envelope bounds.

    Falls back to a plain ``ppermute`` when compression is off or the
    tensor is below ``CGX_COMPRESSION_MINIMAL_SIZE``.
    """
    cc = cc or cfg_mod.default_compression_config()
    if (
        not cc.enabled
        or cfg_mod.dummy_compression()
        or x.size < cfg_mod.minimal_size()
    ):
        return lax.ppermute(x, axis_name, perm)
    perm = tuple(perm)
    inv_perm = tuple((d, s) for (s, d) in perm)

    def hop(v, p, k):
        flat = v.reshape(1, -1)
        q = dispatch.quantize_batch(flat, cc, key=k)
        q2 = jax.tree.map(lambda a: lax.ppermute(a, axis_name, p), q)
        out = dispatch.dequantize_batch(q2, out_dtype=v.dtype)
        return out.reshape(v.shape)

    @jax.custom_vjp
    def _qp(v):
        return hop(v, perm, key)

    def _fwd(v):
        return hop(v, perm, key), None

    def _bwd(_, ct):
        k2 = jax.random.fold_in(key, 0x9E37) if key is not None else None
        return (hop(ct, inv_perm, k2),)

    _qp.defvjp(_fwd, _bwd)
    return _qp(x)


def quantized_all_to_all(
    x: jax.Array,
    axis_name: str,
    *,
    split_axis: int,
    concat_axis: int,
    cc: Optional[CompressionConfig] = None,
    key: Optional[jax.Array] = None,
):
    """``lax.all_to_all`` with the payload quantized on the wire (the
    Ulysses-reshard analogue of :func:`quantized_ppermute`).

    The local buffer is split into ``ws`` destination slices along
    ``split_axis``; each slice quantizes independently (its own buckets),
    the packed planes + meta ride the all_to_all on the slice axis, and
    every arriving slice decodes before the ``concat_axis`` reassembly —
    so wire traffic shrinks to ~bits/32 of the fp32 footprint in both
    directions. Straight-through backward: the cotangent takes the same
    quantized transport through the inverse reshard (the transpose of an
    all_to_all swaps split and concat axes).

    Falls back to a plain ``all_to_all`` when compression is off or the
    tensor is below ``CGX_COMPRESSION_MINIMAL_SIZE``.
    """
    cc = cc or cfg_mod.default_compression_config()
    ws = compat.axis_size(axis_name)
    if (
        not cc.enabled
        or cfg_mod.dummy_compression()
        or x.size < cfg_mod.minimal_size()
        or x.shape[split_axis] % ws
    ):
        return lax.all_to_all(
            x, axis_name, split_axis=split_axis, concat_axis=concat_axis,
            tiled=True,
        )

    def hop(v, s_ax, c_ax, k):
        # (..., ws*piece, ...) -> ws rows, one flattened destination slice
        # per peer; buckets restart per slice.
        moved = jnp.moveaxis(v, s_ax, 0)
        piece = moved.shape[0] // ws
        rows = moved.reshape(ws, -1)
        q = dispatch.quantize_batch(rows, cc, key=k)
        q2 = jax.tree.map(lambda a: lax.all_to_all(a, axis_name, 0, 0), q)
        out_rows = dispatch.dequantize_batch(q2, out_dtype=v.dtype)
        slices = out_rows.reshape((ws, piece) + moved.shape[1:])
        # undo the moveaxis per arriving slice, then concatenate on c_ax
        # (the tiled all_to_all layout).
        parts = [jnp.moveaxis(slices[j], 0, s_ax) for j in range(ws)]
        return jnp.concatenate(parts, axis=c_ax)

    inv = (concat_axis, split_axis)

    @jax.custom_vjp
    def _qa(v):
        return hop(v, split_axis, concat_axis, key)

    def _fwd(v):
        return hop(v, split_axis, concat_axis, key), None

    def _bwd(_, ct):
        k2 = jax.random.fold_in(key, 0xA2A) if key is not None else None
        return (hop(ct, inv[0], inv[1], k2),)

    _qa.defvjp(_fwd, _bwd)
    return _qa(x)


def psum_tree(tree, axes, mesh=None):
    """Exact (uncompressed) allreduce of a whole pytree over ``axes`` —
    the REDUCTION_PSUM fallback applied tree-wide. The graceful-degradation
    path of the non-finite gradient guard (grad_sync.py) routes a poisoned
    step through this instead of the quantized wire: a single NaN/Inf
    otherwise destroys every max-min bucket range it shares a chunk with.
    Size-1 axes are skipped (a psum there is the identity but still emits
    a collective)."""

    def red(x):
        for a in axes:
            if mesh is None or mesh.shape[a] > 1:
                x = lax.psum(x, a)
        return x

    return jax.tree.map(red, tree)
