"""Pipeline parallelism (PP) over a mesh axis.

Absent from the reference (SURVEY.md §2.3: "TP / PP / EP: absent") — built
fresh the TPU way: an SPMD **GPipe schedule inside** ``shard_map``. Every
device holds one stage's parameters (a stacked leading stage dimension
sharded over the ``pp`` axis) and the microbatch stream flows stage-to-stage
with ``lax.ppermute``; the whole schedule is a single ``lax.scan`` of
``n_micro + n_stages - 1`` ticks, so XLA overlaps each tick's compute with
its neighbor transfer on ICI. Reverse-mode AD differentiates straight
through the scan + ppermute (the transpose of a ppermute is the reverse
ppermute), giving the backward pipeline for free — no hand-written 1F1B
schedule is needed for correctness; the scan's bubble is the standard GPipe
bubble of (S-1)/(M+S-1).

Composes with the rest of the framework: the pipelined step's gradients are
a regular pytree, so :func:`..parallel.grad_sync.gradient_sync` quantizes
and allreduces them over the data-parallel axes of the same mesh.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from ..config import CompressionConfig
from ..wire import dispatch as wire_dispatch
from ..wire.edges import EDGE_PP_ACT


def _hop(y, axis_name, perm, hop_cc, name: str = "pipeline.act"):
    """One inter-stage transfer through the edge dispatcher (`pp_act`):
    an explicit ``hop_cc`` keeps the legacy quantized wire (packed
    bit-planes + meta, STE backward — byte-identical to calling
    ``reducers.quantized_ppermute`` directly); otherwise the hop resolves
    the edge registry and sends raw unless a config is registered."""
    return wire_dispatch.wire_ppermute(
        y, axis_name, perm, kind=EDGE_PP_ACT, name=name, cc=hop_cc
    )


def _squeeze_stage_axis(local_params):
    """Drop the leading stage axis shard_map leaves on each device's slice
    of the stacked per-stage params (size 1 after sharding over pp)."""
    return jax.tree.map(
        lambda x: jnp.squeeze(x, 0) if x.ndim and x.shape[0] == 1 else x,
        local_params,
    )


def stack_stage_params(stage_params: Sequence):
    """Stack per-stage parameter pytrees along a new leading stage axis
    (shard it over the 'pp' mesh axis with ``PartitionSpec('pp', ...)``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def unstack_stage_params(stacked, n_stages: int) -> list:
    return [
        jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n_stages)
    ]


def spmd_pipeline(
    stage_fn: Callable,
    local_params,
    microbatches: jax.Array,
    *,
    axis_name: str = "pp",
    n_stages: int,
    hop_cc: Optional[CompressionConfig] = None,
):
    """Run a GPipe pipeline **inside shard_map**.

    ``stage_fn(params, x) -> y`` is one stage's computation; ``local_params``
    are this device's stage parameters (shard_map gives each device its
    leading-dim slice of the stacked params — a leading stage axis of size 1
    is squeezed automatically). ``microbatches``: (M, ...) microbatch
    stream, replicated across the pp axis (every device sees the full
    stream; only stage 0 consumes it). Returns (M, ...) outputs, valid on
    every device (the last stage's results are broadcast back through the
    ring as later microbatches drain).

    Requires stage output shape == stage input shape (true for transformer
    blocks; project in/out outside the pipeline).
    """
    m = microbatches.shape[0]
    stage = lax.axis_index(axis_name)
    params = _squeeze_stage_axis(local_params)
    ticks = m + n_stages - 1
    zero = jnp.zeros_like(microbatches[0])
    right = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 injects microbatch t (0 after the stream drains); others
        # consume what arrived from the left neighbor.
        inject = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(stage == 0, inject, recv)
        y = stage_fn(params, x)
        # Last stage finished microbatch t - (S-1) at tick t.
        done_idx = t - (n_stages - 1)
        is_done = jnp.logical_and(done_idx >= 0, stage == n_stages - 1)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(done_idx, 0), 0
            ),
            lambda o: o,
            outputs,
        )
        recv = _hop(y, axis_name, right, hop_cc)
        return (recv, outputs), None

    outputs0 = jnp.zeros((m,) + zero.shape, zero.dtype)
    (_, outputs), _ = lax.scan(
        tick, (zero, outputs0), jnp.arange(ticks)
    )
    # Broadcast the last stage's outputs to every pp member (so downstream
    # loss/metrics are replicated — psum of the single valid copy).
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def stack_interleaved_params(chunk_params: Sequence, n_stages: int,
                             n_virtual: int):
    """Stack D = n_virtual * n_stages chunk pytrees for
    :func:`spmd_pipeline_interleaved`: global chunk ``v * S + s`` lands at
    stacked row ``s * V + v``, so a contiguous ``P('pp')`` shard hands
    device ``s`` exactly its round-robin chunks ``{s, S+s, 2S+s, ...}``
    (local row v = virtual index v)."""
    s_count, v_count = n_stages, n_virtual
    assert len(chunk_params) == s_count * v_count, (
        len(chunk_params), s_count, v_count)
    order = [
        v * s_count + s for s in range(s_count) for v in range(v_count)
    ]
    return jax.tree.map(lambda *xs: jnp.stack(xs),
                        *[chunk_params[j] for j in order])


def spmd_pipeline_interleaved(
    stage_fn: Callable,
    local_params,
    microbatches: jax.Array,
    *,
    axis_name: str = "pp",
    n_stages: int,
    n_virtual: int,
    hop_cc: Optional[CompressionConfig] = None,
):
    """Interleaved virtual-stage pipeline (Megatron-LM style) inside
    ``shard_map``: each device holds ``n_virtual`` model chunks assigned
    round-robin (device s owns global chunks ``v*S + s``), so the pipeline
    fill costs S ticks instead of the V*S a GPipe schedule of the same
    depth pays — the bubble fraction drops from ``(VS-1)/(VM+VS-1)`` to
    ``(S-1)/(VM+S-1)``, a ~V-fold reduction for M >> S.

    Schedule: microbatches run in groups of S. Microbatch k executes chunk
    c at tick ``(k//S)*V*S + c*S + s + (k%S)`` on device ``s = (c*S+s)%S``;
    every inter-chunk hop is the same right-rotation ``lax.ppermute`` (the
    S-1 -> 0 wraparound carries the payload from chunk c on the last device
    to chunk c+1 on device 0), so each device computes exactly one
    (microbatch, chunk) per tick and the single recv slot suffices. At tick
    t device s recovers its work item from ``r = t - s``: ``q = r mod VS``
    decomposes uniquely as ``q = c*S + (k mod S)`` and
    ``k = (r//VS)*S + (k mod S)``.

    Backward comes from AD through the scan (like :func:`spmd_pipeline`,
    whose carrier/stream contracts this shares: stage in/out shapes equal,
    microbatch stream replicated over pp, M % S == 0). Activation memory is
    therefore O(V*M) per device — use :func:`pipeline_1f1b` when memory,
    not bubble, binds.

    ``local_params``: this device's ``(V, ...)`` slice of
    :func:`stack_interleaved_params` output (shard over pp). Returns the
    (M, ...) outputs of the final chunk, replicated over pp.
    """
    s_count, v_count = n_stages, n_virtual
    m = microbatches.shape[0]
    assert m % s_count == 0, (
        f"interleaved schedule needs microbatches % n_stages == 0, got "
        f"{m} % {s_count}")
    p_rows = jax.tree.leaves(local_params)[0].shape[0]
    assert p_rows == v_count, (
        f"local_params leading axis is {p_rows}, expected n_virtual="
        f"{v_count}: pass this device's pp shard of "
        "stack_interleaved_params (in_specs=P('pp')), not the full stack")
    stage = lax.axis_index(axis_name)
    vs = v_count * s_count
    ticks = v_count * m + s_count - 1
    zero = jnp.zeros_like(microbatches[0])
    right = [(i, (i + 1) % s_count) for i in range(s_count)]

    def tick(carry, t):
        recv, outputs = carry
        r = t - stage
        q = jnp.remainder(r, vs)
        c = q // s_count
        u = q % s_count
        k = jnp.maximum(r, 0) // vs * s_count + u
        active = jnp.logical_and(r >= 0, k < m)

        # chunk c's params: dynamic slice on the local virtual axis
        p_c = jax.tree.map(
            lambda x: lax.dynamic_index_in_dim(x, c, 0, keepdims=False),
            local_params,
        )
        inject = microbatches[jnp.minimum(k, m - 1)]
        x = jnp.where(jnp.logical_and(stage == 0, c == 0), inject, recv)
        y = stage_fn(p_c, x)
        y = jnp.where(active, y, jnp.zeros_like(y))

        is_final = jnp.logical_and(
            jnp.logical_and(stage == s_count - 1, c == v_count - 1), active
        )
        outputs = lax.cond(
            is_final,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.minimum(k, m - 1), 0
            ),
            lambda o: o,
            outputs,
        )
        recv = _hop(y, axis_name, right, hop_cc)
        return (recv, outputs), None

    outputs0 = jnp.zeros((m,) + zero.shape, zero.dtype)
    (_, outputs), _ = lax.scan(tick, (zero, outputs0), jnp.arange(ticks))
    outputs = lax.psum(
        jnp.where(stage == s_count - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def live_stash_microbatches(n_stages: int) -> int:
    """Per-stage activation-stash bound of the 1F1B schedule: microbatch k's
    input is stashed at its forward tick (k + s) and freed at its backward
    tick (k + 2(S-1) - s), a lifetime of 2(S-1-s) ticks — so a ring of
    2(S-1)+1 slots suffices on every stage. GPipe differentiated through the
    scan instead checkpoints every tick's carry: O(M + S) microbatches. The
    1F1B bound is independent of the microbatch count M — the entire point
    of the schedule (Narayanan et al., PipeDream-Flush)."""
    return 2 * (n_stages - 1) + 1


def pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    local_params,
    local_microbatches: jax.Array,
    targets: jax.Array,
    *,
    axis_name: str = "pp",
    n_stages: int,
    hop_cc: Optional[CompressionConfig] = None,
):
    """One-forward-one-backward (1F1B / PipeDream-flush) pipelined training
    step **inside shard_map** — forward AND backward are scheduled
    explicitly, so live activation memory is O(S) microbatches per stage
    instead of the GPipe-through-AD O(M) (:func:`live_stash_microbatches`).

    Schedule (uniform SPMD program; S = n_stages, M = microbatches): at tick
    ``t`` stage ``s`` runs the forward of microbatch ``f = t - s`` and the
    backward of ``b = t - (2(S-1) - s)`` when those indices are in range; in
    steady state every tick is one fwd + one bwd — the 1F1B interleave. The
    backward *recomputes* the stage forward from the stashed input
    (rematerialization), seeds from the local loss gradient on the last
    stage, and flows cotangents leftward with ``lax.ppermute``; total ticks
    = M + 2(S-1).

    Memory-scalable feed: ``local_microbatches`` is this device's
    ``(M/S, ...)`` shard of the stream (shard the leading microbatch dim
    over the pp axis — ``in_specs=P(axis_name)``). Each tick the owning
    stage contributes microbatch ``f`` through a single-microbatch ``psum``,
    so no device ever holds the full stream — fixing the GPipe helper's
    O(global batch) per-stage feed. ``targets`` stays replicated (labels are
    small).

    ``stage_fn(params, x) -> y`` with ``y.shape == x.shape`` (the SPMD
    carrier; for stages with differing natural shapes, pad into a common
    carrier — embeddings/logits never travel: stage 0 consumes raw
    microbatches and the last stage feeds ``loss_fn(y, tgt) -> scalar``
    locally).

    Returns ``(loss, grads)``: the mean per-microbatch loss (replicated) and
    this stage's parameter cotangents of that mean (leading stage axis of
    size 1 — ``out_specs=P(axis_name)`` reassembles the stacked layout).
    """
    s_count = n_stages
    m_local = local_microbatches.shape[0]
    m = m_local * s_count
    stage = lax.axis_index(axis_name)
    params = _squeeze_stage_axis(local_params)
    k_slots = live_stash_microbatches(s_count)
    zero = jnp.zeros_like(local_microbatches[0])
    right = [(i, (i + 1) % s_count) for i in range(s_count)]
    left = [(i, (i - 1) % s_count) for i in range(s_count)]
    ticks = m + 2 * (s_count - 1)

    g_zero = jax.tree.map(lambda p: jnp.zeros(p.shape, jnp.float32), params)

    def tick(carry, t):
        recv_x, recv_cot, stash, gacc, lacc = carry
        f = t - stage
        b = t - (2 * (s_count - 1) - stage)
        do_f = jnp.logical_and(f >= 0, f < m)
        do_b = jnp.logical_and(b >= 0, b < m)
        fc = jnp.clip(f, 0, m - 1)
        bc = jnp.clip(b, 0, m - 1)

        # Feed: the owner of the microbatch STAGE 0 consumes this tick
        # (f at stage 0 = t — a mesh-uniform index; using the local f here
        # would make devices disagree about the owner and psum to zero)
        # contributes it; one microbatch-sized psum delivers it. shard_map's
        # P(axis) sharding is contiguous: device d holds microbatches
        # [d*m_local, (d+1)*m_local).
        feed_idx = jnp.clip(t, 0, m - 1)
        own = lax.dynamic_index_in_dim(
            local_microbatches, feed_idx % m_local, 0, keepdims=False
        )
        feed = lax.psum(
            jnp.where(stage == feed_idx // m_local, own, jnp.zeros_like(own)),
            axis_name,
        )
        x_in = jnp.where(stage == 0, feed, recv_x)

        # Forward; stash the input for the rematerialized backward.
        y = stage_fn(params, x_in)
        stash = jnp.where(
            do_f,
            lax.dynamic_update_index_in_dim(
                stash, x_in, fc % k_slots, 0
            ),
            stash,
        )

        # Backward of microbatch b: recompute from the stash. (When f == b —
        # last stage, same tick — the slot was just written above, so the
        # recompute sees this tick's input.)
        x_b = lax.dynamic_index_in_dim(stash, bc % k_slots, 0, keepdims=False)
        tgt_b = lax.dynamic_index_in_dim(targets, bc, 0, keepdims=False)

        def fwd_and_loss(p, x):
            y2 = stage_fn(p, x)
            return y2, loss_fn(y2, tgt_b)

        (_, l_b), vjp_fn = jax.vjp(fwd_and_loss, params, x_b)
        is_last = stage == s_count - 1
        seed_y = jnp.where(is_last, jnp.zeros_like(recv_cot), recv_cot)
        seed_l = jnp.where(is_last, jnp.float32(1), jnp.float32(0))
        cot_p, cot_x = vjp_fn((seed_y, seed_l))
        gacc = jax.tree.map(
            lambda g, c: g + jnp.where(do_b, c.astype(jnp.float32), 0),
            gacc,
            cot_p,
        )
        # Loss of microbatch b, observed on the last stage during backward.
        lacc = lacc + jnp.where(
            jnp.logical_and(do_b, is_last), l_b.astype(jnp.float32), 0.0
        )

        recv_x = _hop(y, axis_name, right, hop_cc)
        recv_cot = _hop(cot_x, axis_name, left, hop_cc, name="pipeline.cot")
        return (recv_x, recv_cot, stash, gacc, lacc), None

    stash0 = jnp.zeros((k_slots,) + zero.shape, zero.dtype)
    (_, _, _, gacc, lacc), _ = lax.scan(
        tick,
        (zero, jnp.zeros_like(zero), stash0, g_zero, jnp.float32(0)),
        jnp.arange(ticks),
    )
    loss = (
        lax.psum(
            jnp.where(stage == s_count - 1, lacc, jnp.float32(0)), axis_name
        )
        / m
    )
    grads = jax.tree.map(lambda g: (g / m)[None], gacc)
    return loss, grads


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) -> (M, B/M, ...) microbatch stream."""
    b = x.shape[0]
    assert b % n_micro == 0, (b, n_micro)
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def merge_microbatches(y: jax.Array) -> jax.Array:
    return y.reshape((-1,) + y.shape[2:])
