"""Pipeline parallelism (PP) over a mesh axis.

Absent from the reference (SURVEY.md §2.3: "TP / PP / EP: absent") — built
fresh the TPU way: an SPMD **GPipe schedule inside** ``shard_map``. Every
device holds one stage's parameters (a stacked leading stage dimension
sharded over the ``pp`` axis) and the microbatch stream flows stage-to-stage
with ``lax.ppermute``; the whole schedule is a single ``lax.scan`` of
``n_micro + n_stages - 1`` ticks, so XLA overlaps each tick's compute with
its neighbor transfer on ICI. Reverse-mode AD differentiates straight
through the scan + ppermute (the transpose of a ppermute is the reverse
ppermute), giving the backward pipeline for free — no hand-written 1F1B
schedule is needed for correctness; the scan's bubble is the standard GPipe
bubble of (S-1)/(M+S-1).

Composes with the rest of the framework: the pipelined step's gradients are
a regular pytree, so :func:`..parallel.grad_sync.gradient_sync` quantizes
and allreduces them over the data-parallel axes of the same mesh.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax


def stack_stage_params(stage_params: Sequence):
    """Stack per-stage parameter pytrees along a new leading stage axis
    (shard it over the 'pp' mesh axis with ``PartitionSpec('pp', ...)``)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stage_params)


def unstack_stage_params(stacked, n_stages: int) -> list:
    return [
        jax.tree.map(lambda x, i=i: x[i], stacked) for i in range(n_stages)
    ]


def spmd_pipeline(
    stage_fn: Callable,
    local_params,
    microbatches: jax.Array,
    *,
    axis_name: str = "pp",
    n_stages: int,
):
    """Run a GPipe pipeline **inside shard_map**.

    ``stage_fn(params, x) -> y`` is one stage's computation; ``local_params``
    are this device's stage parameters (shard_map gives each device its
    leading-dim slice of the stacked params — a leading stage axis of size 1
    is squeezed automatically). ``microbatches``: (M, ...) microbatch
    stream, replicated across the pp axis (every device sees the full
    stream; only stage 0 consumes it). Returns (M, ...) outputs, valid on
    every device (the last stage's results are broadcast back through the
    ring as later microbatches drain).

    Requires stage output shape == stage input shape (true for transformer
    blocks; project in/out outside the pipeline).
    """
    m = microbatches.shape[0]
    stage = lax.axis_index(axis_name)
    params = jax.tree.map(
        lambda x: jnp.squeeze(x, 0) if x.ndim and x.shape[0] == 1 else x,
        local_params,
    )
    ticks = m + n_stages - 1
    zero = jnp.zeros_like(microbatches[0])
    right = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def tick(carry, t):
        recv, outputs = carry
        # Stage 0 injects microbatch t (0 after the stream drains); others
        # consume what arrived from the left neighbor.
        inject = microbatches[jnp.minimum(t, m - 1)]
        x = jnp.where(stage == 0, inject, recv)
        y = stage_fn(params, x)
        # Last stage finished microbatch t - (S-1) at tick t.
        done_idx = t - (n_stages - 1)
        is_done = jnp.logical_and(done_idx >= 0, stage == n_stages - 1)
        outputs = lax.cond(
            is_done,
            lambda o: lax.dynamic_update_index_in_dim(
                o, y, jnp.maximum(done_idx, 0), 0
            ),
            lambda o: o,
            outputs,
        )
        recv = lax.ppermute(y, axis_name, right)
        return (recv, outputs), None

    outputs0 = jnp.zeros((m,) + zero.shape, zero.dtype)
    (_, outputs), _ = lax.scan(
        tick, (zero, outputs0), jnp.arange(ticks)
    )
    # Broadcast the last stage's outputs to every pp member (so downstream
    # loss/metrics are replicated — psum of the single valid copy).
    outputs = lax.psum(
        jnp.where(stage == n_stages - 1, outputs, jnp.zeros_like(outputs)),
        axis_name,
    )
    return outputs


def split_microbatches(x: jax.Array, n_micro: int) -> jax.Array:
    """(B, ...) -> (M, B/M, ...) microbatch stream."""
    b = x.shape[0]
    assert b % n_micro == 0, (b, n_micro)
    return x.reshape((n_micro, b // n_micro) + x.shape[1:])


def merge_microbatches(y: jax.Array) -> jax.Array:
    return y.reshape((-1,) + y.shape[2:])
