"""In-XLA single-program quantized allreduce (EQuARX-style) with a
compiled-program cache (GC3-style).

The production compressed-allreduce path used to stage every gradient
through the host bridge (shm/store) even when all ranks live on the same
slice — host round-trips XLA can neither schedule nor overlap. For
intra-slice traffic this module compiles the WHOLE compressed allreduce

    Pallas quantize  ->  ``lax.all_to_all`` chunk exchange (SRA; the
    ``ppermute``-ring for the RING variant)  ->  fused
    dequant-accumulate-requantize epilogue (PR 4)  ->  ``lax.all_gather``
    + decode

into **one staged XLA program** under ``shard_map`` on the ICI mesh: no
``io_callback``, no bridge hop, nothing the XLA scheduler cannot see.
EQuARX (arxiv 2506.17615) measures a quantized allreduce expressed
natively inside XLA at ~2x at no quality loss; GC3 (arxiv 2201.11840)
motivates treating the result as a compiled, cacheable program — hence
the bounded program LRU here, keyed on (payload, dtype, config, mesh,
route), mirroring the layout LRU of ``allreduce.py``.

Which traffic comes here is decided by the topology router
(``parallel/topology.py``): intra-slice groups -> the staged program;
cross-slice groups -> the existing compressed DCN/bridge path (the
bridge's end-state role); mixed groups -> the reference's two-level
scheme (uncompressed ICI intra via ``lax.psum_scatter``/``all_gather``,
compressed cross-slice exchange via the slice leaders —
``reducers.hierarchical_allreduce`` + ``topology.two_level_config``).

Observability: staged calls never cross the host, so the bridge's
timeline spans vanish for them. The module instead emits a trace-time
``CAT_COLLECTIVE`` instant per compiled program plus ``cgx.xla.*``
counters (programs built, cache hits/misses, eager calls, routed slices)
so ``cgx_trace``/``cgx_top`` attribution stays truthful.

**Staged purity contract**: this module and everything it lists in
:data:`STAGED_PURE` must never import ``io_callback``/``pure_callback``
— a host callback inside the staged program would silently reintroduce
the host hop this module exists to remove. ``tools/lint.py`` enforces
the list; ``tests/test_xla_allreduce.py`` additionally walks the built
jaxpr asserting zero callback primitives and exactly one
quantize/epilogue kernel pair per shard.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Dict, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import config as cfg_mod
from ..config import CompressionConfig
from ..observability import timeline
from ..utils.compat import shard_map as _compat_shard_map
from ..utils.logging import metrics
from . import mesh as mesh_mod
from . import reducers, topology

# Modules that must stay free of host-callback machinery (tools/lint.py
# parses this list — do not rename). Paths are repo-relative; the linter
# matches by trailing path components so tmp-tree test fixtures work.
STAGED_PURE = (
    "torch_cgx_tpu/parallel/xla_allreduce.py",
    "torch_cgx_tpu/parallel/topology.py",
    "torch_cgx_tpu/parallel/schedule.py",
)


# ---------------------------------------------------------------------------
# Shard-level staged bodies (usable inside any caller's shard_map — this is
# what allreduce.py routes intra-slice fusion slices to).
# ---------------------------------------------------------------------------


def _note_staged_slice(
    n: int, ws: int, cc: CompressionConfig, reduction: str, route: str
) -> None:
    """Trace-time accounting for one staged slice: counters + a
    CAT_COLLECTIVE instant. Runs while the program is being TRACED (once
    per compiled program), never at execution time — runtime hooks would
    need a host callback, which the staged program must not contain."""
    metrics.add("cgx.xla.staged_slices")
    metrics.add("cgx.xla.staged_elems", float(n))
    timeline.instant(
        "xla_allreduce",
        cat=timeline.CAT_COLLECTIVE,
        route=route,
        elems=int(n),
        ws=int(ws),
        bits=int(cc.bits),
        bucket=int(cc.bucket_size),
        reduction=reduction,
    )


def staged_quantized_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    pre=None,
) -> jax.Array:
    """The staged single-program body for one intra-slice fusion slice
    (inside shard_map): the same quantize -> exchange -> fused epilogue ->
    all_gather composition as ``reducers.quantized_allreduce`` — wire
    bytes and results are bit-identical, which is what lets the router
    flip traffic onto this path without changing a single gradient — plus
    the trace-time ``cgx.xla.*`` accounting the bridge spans no longer
    cover."""
    _note_staged_slice(x.shape[0], ws, cc, reduction, topology.ROUTE_STAGED)
    return reducers.quantized_allreduce(
        x, axis_name, ws, cc, reduction, key, pre
    )


def staged_quantized_allreduce_with_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    pre=None,
):
    """Error-feedback sibling of :func:`staged_quantized_allreduce`:
    ``(reduced, wire_decode)`` from one staged program (the wire decode
    shares the stage-1 payload — quantize-once, like the reducer it
    wraps)."""
    _note_staged_slice(x.shape[0], ws, cc, reduction, topology.ROUTE_STAGED)
    return reducers.quantized_allreduce_with_wire(
        x, axis_name, ws, cc, reduction, key, pre
    )


def staged_pipelined_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    sched=None,
    pre=None,
):
    """Schedule-compiled sibling of :func:`staged_quantized_allreduce`:
    the fusion slice runs as a chunked software pipeline compiled into
    the same single staged program (``parallel/schedule.py`` — chunk k+1
    quantizes while chunk k is on the wire and chunk k-1 runs the fused
    epilogue). Same ``cgx.xla.*`` trace accounting plus the schedule's
    own ``cgx.sched.*`` counters. ``pre``: producer-staged per-block
    payloads (table pre-verified by the consumer)."""
    from . import schedule as sched_mod

    _note_staged_slice(x.shape[0], ws, cc, reduction, topology.ROUTE_STAGED)
    return sched_mod.pipelined_quantized_allreduce(
        x, axis_name, ws, cc, reduction, key, sched, pre=pre
    )


def staged_pipelined_allreduce_with_wire(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str = cfg_mod.REDUCTION_SRA,
    key: Optional[jax.Array] = None,
    sched=None,
    pre=None,
):
    """Error-feedback sibling of :func:`staged_pipelined_allreduce`:
    ``(reduced, wire_decode)``, the per-chunk wire decodes concatenated
    (quantize-once — each chunk's decode shares its stage-1 payload)."""
    from . import schedule as sched_mod

    _note_staged_slice(x.shape[0], ws, cc, reduction, topology.ROUTE_STAGED)
    return sched_mod.pipelined_quantized_allreduce(
        x, axis_name, ws, cc, reduction, key, sched, with_wire=True, pre=pre
    )


# ---------------------------------------------------------------------------
# The compiled-program cache + eager entry point.
# ---------------------------------------------------------------------------

_PROGRAM_CACHE: "OrderedDict" = OrderedDict()
_PROGRAM_CACHE_MAX = 32
_PROGRAM_STATS = {"hits": 0, "misses": 0}


def program_cache_stats() -> Dict[str, int]:
    return dict(_PROGRAM_STATS)


def program_cache_clear() -> None:
    _PROGRAM_CACHE.clear()
    _PROGRAM_STATS.update(hits=0, misses=0)


def invalidate_program_cache(reason: str = "reconfigure") -> None:
    """World-shrink invalidation entry point, cascaded from
    ``allreduce.invalidate_layout_cache`` (and therefore
    ``supervisor.invalidate_trace_caches``). Entries keyed on the dead
    world's registry version can never hit again — but each holds a
    fully COMPILED executable, the most expensive artifact any of the
    staged caches pins, so they are dropped outright instead of aging
    out of the LRU while holding device programs live (ISSUE 14's
    invalidation-cascade pass caught this cache missing from the
    ladder its layout/schedule/plan siblings already ride)."""
    program_cache_clear()
    metrics.add("cgx.xla.program_cache_invalidations")


def _mesh_fingerprint(mesh) -> tuple:
    devs = np.asarray(mesh.devices)
    # Grid shape is part of the identity: transposed meshes over the same
    # raveled device list have different per-axis world sizes, and a
    # program compiled for one must not serve the other.
    return (
        tuple(mesh.axis_names),
        devs.shape,
        tuple(getattr(d, "id", i) for i, d in enumerate(devs.ravel())),
    )


def _trace_env_fingerprint() -> tuple:
    """Every env knob the staged body bakes in at TRACE time (codec
    lowering, encode strategy, epilogue selection, accumulation domain,
    kernel tiling/packing, autotune engagement, debug modes): a flip of
    any of these between eager calls must compile a fresh program, never
    serve a stale one — the same discipline as allreduce's layout LRU.
    The PR 11 kernel knobs (``CGX_PALLAS_DB``/``CGX_SRA_ACCUM``/
    ``CGX_AUTOTUNE``/``CGX_PALLAS_PACK``/``CGX_PALLAS_TILE_CHUNKS``)
    joined with ISSUE 14's knob→cache-key pass, which caught them
    lowering into the program body without re-keying it."""
    from ..ops import codec_pallas
    from ..utils import env as _env

    return (
        cfg_mod.codec_impl(),
        codec_pallas._encode_strategy(),
        cfg_mod.sra_epilogue(),
        cfg_mod.sra_epilogue_min_elems(),
        cfg_mod.dummy_compression(),
        cfg_mod.force_codec(),
        cfg_mod.minimal_size(),
        cfg_mod.sra_accum(),
        cfg_mod.pallas_db(),
        cfg_mod.autotune_mode(),
        _env.get_optional_str_env(cfg_mod.PALLAS_PACK),
        _env.get_optional_str_env(cfg_mod.PALLAS_TILE_CHUNKS),
    )


def _program_key(
    mesh, axis, n, dtype, cc, reduction, route, with_key, kind, topo=None
):
    # ``topo``: the env-derived TopologyConfig a two-level program bakes
    # in at build time — keyed alongside the shared trace-time knobs of
    # ``_trace_env_fingerprint``.
    return (
        kind,
        _mesh_fingerprint(mesh),
        axis,
        int(n),
        np.dtype(dtype).str,
        cc,
        reduction,
        route,
        bool(with_key),
        topo,
        _trace_env_fingerprint(),
        cfg_mod.registry_version(),
    )


def _cache_get(key):
    hit = _PROGRAM_CACHE.get(key)
    if hit is not None:
        _PROGRAM_CACHE.move_to_end(key)
        _PROGRAM_STATS["hits"] += 1
        metrics.add("cgx.xla.program_cache_hits")
    return hit


def _cache_put(key, fn) -> None:
    _PROGRAM_STATS["misses"] += 1
    metrics.add("cgx.xla.program_cache_misses")
    metrics.add("cgx.xla.staged_programs")
    _PROGRAM_CACHE[key] = fn
    if len(_PROGRAM_CACHE) > _PROGRAM_CACHE_MAX:
        _PROGRAM_CACHE.popitem(last=False)


def _build_flat_program(
    mesh, axis, ws, cc, reduction, with_key, route, sched=None, donate=False
):
    """One staged program: shard_map over ``axis``, body = the staged
    quantize -> exchange -> epilogue -> all_gather composition — the
    schedule-pipelined body when ``sched`` is given (the planner plane).
    ``donate=True`` donates the input stack (the planner's donated-buffer
    contract: the plan owns its step buffer, so the reduced output reuses
    it instead of double-buffering ws*n floats)."""

    def body(x, key):
        _note_staged_slice(x.shape[1], ws, cc, reduction, route)
        if sched is not None:
            from . import schedule as sched_mod

            return sched_mod.pipelined_quantized_allreduce(
                x[0], axis, ws, cc, reduction, key, sched
            )[None]
        return reducers.quantized_allreduce(
            x[0], axis, ws, cc, reduction, key
        )[None]

    sharded = _compat_shard_map(
        body,
        mesh=mesh,
        in_specs=(P(axis), P()),
        out_specs=P(axis),
        check_vma=False,  # pallas_call has no shard_map replication rule
    )
    donate_args = (0,) if donate else ()
    if not with_key:
        return jax.jit(lambda x: sharded(x, None), donate_argnums=donate_args)
    return jax.jit(sharded, donate_argnums=donate_args)


def _two_level_permutation(flat_devices, tl_mesh) -> np.ndarray:
    """Row permutation mapping the caller's flat device order into the
    (cross, intra) grid of ``tl_mesh`` (and back via argsort)."""
    pos = {d: i for i, d in enumerate(flat_devices)}
    grid = np.asarray(tl_mesh.devices)
    return np.asarray(
        [[pos[d] for d in row] for row in grid], dtype=np.int64
    )


def _build_two_level_program(tl_mesh, ws_cross, ws_intra, cc, with_key, topo):
    """The reference two-level program for a MIXED group: uncompressed
    ICI reduce inside each slice (``lax.psum_scatter`` under the leader
    scheme), compressed cross-slice exchange between the slice leaders,
    ICI ``all_gather`` back — ``hierarchical_allreduce`` with
    ``topology.two_level_config``'s override (``topo``, resolved by the
    caller and part of the program-cache key)."""

    def body(x, key):
        n = x.shape[-1]
        metrics.add("cgx.xla.two_level_slices")
        timeline.instant(
            "xla_allreduce",
            cat=timeline.CAT_COLLECTIVE,
            route=topology.ROUTE_TWO_LEVEL,
            elems=int(n),
            ws=int(ws_cross * ws_intra),
            bits=int(cc.bits),
            bucket=int(cc.bucket_size),
            reduction=topo.cross_reduction,
        )
        out = reducers.hierarchical_allreduce(
            x[0, 0],
            intra_axis=mesh_mod.INTRA_AXIS,
            cross_axis=mesh_mod.CROSS_AXIS,
            ws_intra=ws_intra,
            ws_cross=ws_cross,
            cc=cc,
            topology=topo,
            key=key,
        )
        return out[None, None]

    sharded = _compat_shard_map(
        body,
        mesh=tl_mesh,
        in_specs=(P(mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS), P()),
        out_specs=P(mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS),
        check_vma=False,
    )
    if not with_key:
        return jax.jit(lambda x: sharded(x, None))
    return jax.jit(sharded)


def staged_allreduce(
    per_rank,
    *,
    mesh=None,
    axis: Optional[str] = None,
    cc: Optional[CompressionConfig] = None,
    reduction: Optional[str] = None,
    key: Optional[jax.Array] = None,
):
    """Eager entry point: quantized-allreduce ``per_rank`` — a
    ``(ws, n)`` stack, row r = device r's local contribution in the
    mesh's device order — through ONE compiled staged XLA program, and
    return the ``(ws, n)`` reduced stack (rows identical, the reducers'
    error-symmetry invariant).

    The topology router picks the program shape per group:

    * intra-slice -> the flat staged program (quantize -> exchange ->
      fused epilogue -> all_gather, one ``jit``);
    * mixed -> the two-level program over a (cross, intra) mesh derived
      from the devices' slice ids (uncompressed ICI + compressed cross);
    * cross-slice -> in a bridge deployment this traffic stays on the
      host bridge; a pure-JAX caller has no bridge, so the flat staged
      program runs as the fallback (counted ``cgx.xla.routed_bridge`` so
      the misrouting is visible, never silent).

    Programs are cached in a bounded LRU keyed on (payload, dtype,
    config, mesh, route) — the GC3 compiled-collective discipline; reuse
    is visible in ``cgx.xla.program_cache_hits``.
    """
    mesh = mesh if mesh is not None else mesh_mod.flat_mesh()
    axis = axis or mesh.axis_names[0]
    cc = cc or cfg_mod.default_compression_config()
    reduction = reduction or cfg_mod.topology_from_env().intra_reduction
    decision = topology.route(mesh, (axis,), allow_remesh=True)
    metrics.add("cgx.xla.staged_calls")
    metrics.add(f"cgx.xla.routed_{decision.route}")
    per_rank = jnp.asarray(per_rank)
    ws = mesh.shape[axis]
    n = per_rank.shape[-1]

    if decision.route == topology.ROUTE_TWO_LEVEL:
        flat_devices = list(np.asarray(mesh.devices).ravel())
        tl_mesh = topology.two_level_mesh(flat_devices)
        perm = _two_level_permutation(flat_devices, tl_mesh)
        tl_topo = topology.two_level_config()
        kp = _program_key(
            tl_mesh, mesh_mod.INTRA_AXIS, n, per_rank.dtype, cc,
            reduction, decision.route, key is not None, "two_level",
            topo=tl_topo,
        )
        fn = _cache_get(kp)
        if fn is None:
            fn = _build_two_level_program(
                tl_mesh, perm.shape[0], perm.shape[1], cc, key is not None,
                tl_topo,
            )
            _cache_put(kp, fn)
        arr = jnp.asarray(per_rank)[perm.reshape(-1)].reshape(
            perm.shape + (n,)
        )
        arr = jax.device_put(
            arr,
            NamedSharding(
                tl_mesh, P(mesh_mod.CROSS_AXIS, mesh_mod.INTRA_AXIS)
            ),
        )
        out = fn(arr, key) if key is not None else fn(arr)
        inv = np.argsort(perm.reshape(-1))
        return jnp.asarray(out).reshape(ws, n)[inv]

    kp = _program_key(
        mesh, axis, n, per_rank.dtype, cc, reduction, decision.route,
        key is not None, "flat",
    )
    fn = _cache_get(kp)
    if fn is None:
        fn = _build_flat_program(
            mesh, axis, ws, cc, reduction, key is not None, decision.route
        )
        _cache_put(kp, fn)
    arr = jax.device_put(per_rank, NamedSharding(mesh, P(axis)))
    return fn(arr, key) if key is not None else fn(arr)


def staged_allreduce_planned(
    per_rank,
    *,
    mesh=None,
    axis: Optional[str] = None,
    cc: Optional[CompressionConfig] = None,
    reduction: Optional[str] = None,
    key: Optional[jax.Array] = None,
):
    """Planner-staged sibling of :func:`staged_allreduce` (the
    ``planner.planned_allreduce`` entry point): the step plan's
    (chunks, bits) decision for the whole ``(ws, n)`` payload applied as
    ONE donated-buffer XLA program — the schedule-pipelined staged body
    at the plan's depth, input stack donated. Falls back to
    :func:`staged_allreduce` whenever nothing plans (planner disengaged,
    raw config, non-SRA reduction, a payload too small to split), so the
    call is always answerable. Programs ride the same bounded LRU under
    a ``"planned"`` key kind that folds in the planner's cache-key
    component — an adopted re-plan compiles a fresh program, an
    unchanged one hits."""
    from . import planner as planner_mod
    from . import schedule as sched_mod

    mesh = mesh if mesh is not None else mesh_mod.flat_mesh()
    axis = axis or mesh.axis_names[0]
    cc = cc or cfg_mod.default_compression_config()
    reduction = reduction or cfg_mod.topology_from_env().intra_reduction
    per_rank = jnp.asarray(per_rank)
    ws = mesh.shape[axis]
    n = per_rank.shape[-1]
    decision = topology.route(mesh, (axis,), allow_remesh=True)
    dec = planner_mod.decide_slice(
        n, ws, cc, reduction, route=decision.route
    )
    if dec is None:
        return staged_allreduce(
            per_rank, mesh=mesh, axis=axis, cc=cc, reduction=reduction,
            key=key,
        )
    cc_s = cc
    if cc.enabled and 1 <= dec.bits <= cfg_mod.MAX_BITS and dec.bits != cc.bits:
        cc_s = dataclasses.replace(cc, bits=dec.bits)
    sched = sched_mod.compiled_schedule(
        n, ws, cc_s, reduction=reduction,
        dtype=np.dtype(per_rank.dtype).str, route=decision.route,
        chunks=dec.chunks,
    )
    metrics.add("cgx.plan.staged_calls")
    kp = _program_key(
        mesh, axis, n, per_rank.dtype, cc_s, reduction, decision.route,
        key is not None, "planned",
        topo=(dec.chunks, planner_mod.cache_key_component()),
    )
    fn = _cache_get(kp)
    if fn is None:
        fn = _build_flat_program(
            mesh, axis, ws, cc_s, reduction, key is not None,
            decision.route, sched=sched, donate=True,
        )
        _cache_put(kp, fn)
        metrics.add("cgx.plan.staged_programs")
    arr = jax.device_put(per_rank, NamedSharding(mesh, P(axis)))
    return fn(arr, key) if key is not None else fn(arr)


def staged_wire_frames(
    per_rank,
    *,
    mesh=None,
    axis: Optional[str] = None,
    cc: Optional[CompressionConfig] = None,
    key: Optional[jax.Array] = None,
):
    """Introspection sibling of :func:`staged_allreduce` (SRA only): run
    the staged program with its wire payloads threaded out. Returns
    ``(out, q1_packed, q1_meta, q2_packed, q2_meta)`` stacked per rank —
    ``q1_*`` the (ws, chunk) stage-1 exchange payload each rank SENT,
    ``q2_*`` its requantized stage-2 allgather chunk. The parity suite
    compares these bytes against the host bridge's SRA frames
    (bit-identical on the deterministic ``div`` encode — the
    staged<->bridge wire contract, docs/COMPRESSION_GUIDE.md)."""
    mesh = mesh if mesh is not None else mesh_mod.flat_mesh()
    axis = axis or mesh.axis_names[0]
    cc = cc or cfg_mod.default_compression_config()
    per_rank = jnp.asarray(per_rank)
    ws = mesh.shape[axis]

    # Same bounded cache as the staged programs: jax.jit caches by
    # function identity, so a fresh closure per call would retrace and
    # recompile on every invocation (the parity suite and bench byte
    # pre-flights call this repeatedly on the same shapes).
    kp = _program_key(
        mesh, axis, per_rank.shape[-1], per_rank.dtype, cc,
        cfg_mod.REDUCTION_SRA, "wire", key is not None, "wire",
    )
    fn = _cache_get(kp)
    if fn is None:

        def body(x, k):
            out, q1, q2 = reducers.sra_wire_frames(x[0], axis, ws, cc, k)
            return (
                out[None], q1.packed[None], q1.meta[None],
                q2.packed[None], q2.meta[None],
            )

        sharded = _compat_shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis), P()),
            out_specs=(P(axis),) * 5,
            check_vma=False,
        )
        fn = jax.jit(sharded)
        _cache_put(kp, fn)
    arr = jax.device_put(per_rank, NamedSharding(mesh, P(axis)))
    return fn(arr, key)
