"""PowerSGD low-rank gradient compression (Vogels et al., 2019).

Beyond the reference: its compressor hierarchy has exactly two members —
the max-min Quantizer and a debug pass-through (compressor.h:130,145).
PowerSGD is the other major gradient-compression family in the DDP world
(torch ships ``powerSGD_hook`` for it), and it is uncommonly TPU-friendly:
compress/decompress are plain matmuls (MXU work, not VPU bit-twiddling),
and the wire payloads P (n x r) and Q (m x r) are *linear* in the
gradient, so a raw ``lax.psum`` of the factors IS the exact mean of the
per-device low-rank projections — no per-hop requantization, no error
asymmetry across replicas.

Per eligible leaf M (reshaped to (n, m), warm-started Q carried in state):

    M  = grad + e              # error feedback (per-device)
    P  = psum(M @ Q)           # (n, r) on the wire; scale washes out below
    P  = orthonormalize(P)     # identical on every device
    Q' = psum(M.T @ P) / ws    # (m, r) on the wire — the MEAN projection
    M^ = P @ Q'.T              # shared rank-r approximation of mean(M_i)
    e' = M - M^                # this device's deviation + truncation loss

The Q' division is load-bearing: M^ must approximate the MEAN of the
EF-corrected gradients so each device's residual subtracts it exactly
once — mean(e') = mean(M) - M^, the true aggregate truncation loss,
re-fed next step. (Approximating the SUM instead overcorrects by ws x
per step and diverges.)

Traffic per step: (n + m) * r values instead of n * m — e.g. a
768 x 3072 GPT-2 MLP kernel at rank 4 ships 15 360 values instead of
2.36 M (153x). Ineligible leaves (rank < 2, tiny, or (n+m)r >= nm) ride
an exact ``lax.psum``.

The warm start is load-bearing: Q persists across steps, so the power
iteration converges onto the gradient's dominant subspace over time.
Error feedback is NOT optional here (rank-r truncation loses far more
than quantization); the state is therefore baked into the transform.
"""

from __future__ import annotations

from typing import NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax

from .. import config as cfg_mod
from ..utils.logging import metrics
from . import mesh as mesh_mod


class PowerSGDState(NamedTuple):
    """qs: per-leaf warm-start Q factors (replicated — identical on every
    device after each factor allreduce). es: per-device EF residuals (the
    same placement hazard as :class:`ErrorFeedbackState` — NEVER declare
    them replicated under shard_map)."""

    qs: tuple
    es: tuple


def _matrix_shape(shape) -> Tuple[int, int]:
    """(n, m) view: leading dim x flattened rest (torch hook convention)."""
    return int(shape[0]), int(np.prod(shape[1:]))


def eligible(leaf, rank: int) -> bool:
    """Low-rank compression pays off: float, >= 2-D, above the minimal
    size, and the factors are smaller than the matrix."""
    if leaf.ndim < 2 or not jnp.issubdtype(leaf.dtype, jnp.floating):
        return False
    if leaf.size < cfg_mod.minimal_size():
        return False
    n, m = _matrix_shape(leaf.shape)
    r = min(rank, n, m)
    return (n + m) * r < n * m


def _orthonormalize(p: jax.Array) -> jax.Array:
    """Economic QR of (n, r) — deterministic, so every device (running on
    identical psum'd input) produces identical factors."""
    q, _ = jnp.linalg.qr(p)
    return q


def init_powersgd(params, rank: int, *, seed: int = 0) -> PowerSGDState:
    """Deterministic gaussian Q warm-start per eligible leaf + zero EF
    residuals. Placement under ``jax.jit`` + ``shard_map``: replicate
    ``qs``; give each ``es`` leaf a leading device axis sharded over the
    sync axes (the :func:`init_error_feedback` pattern) and strip it
    inside the mapped function."""
    leaves = jax.tree_util.tree_leaves(params)
    qs, es = [], []
    for i, leaf in enumerate(leaves):
        if eligible(leaf, rank):
            n, m = _matrix_shape(leaf.shape)
            r = min(rank, n, m)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
            qs.append(
                jax.random.normal(key, (m, r), jnp.float32)
                / np.float32(np.sqrt(m))
            )
            es.append(jnp.zeros((n, m), jnp.float32))
        else:
            qs.append(None)
            es.append(None)
    return PowerSGDState(qs=tuple(qs), es=tuple(es))


def powersgd_transform(
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    rank: int = 4,
    average: bool = True,
    placement_warning: bool = True,
) -> optax.GradientTransformation:
    """optax transformation: PowerSGD-compressed gradient allreduce.

    Prepend to an optimizer chain running inside ``shard_map``::

        tx = optax.chain(
            cgx.powersgd_transform(mesh=mesh, rank=4), optax.adam(1e-3)
        )

    The state (``PowerSGDState``) carries the warm-start factors
    (replicated) and per-device EF residuals — under shard_map, shard the
    ``es`` leaves or manage placement via :func:`init_powersgd`'s
    docstring. Ineligible leaves take an exact ``psum``. Outputs are
    bit-identical across devices (the decompressed M^ is computed from
    psum'd factors only).
    """
    axes = tuple(axes)
    ws = int(np.prod([mesh.shape[a] for a in axes]))

    def _psum(x):
        for a in axes:
            if mesh.shape[a] > 1:
                x = lax.psum(x, a)
        return x

    def _factor_psum(x, name):
        # Factor traffic is a wire edge (`powersgd_factor`): plain exact
        # psum unless an edge config resolves, in which case the factors
        # ride the quantized allreduce — error-symmetric, so every device
        # still decodes identical factors (the orthonormalization input
        # stays replicated).
        from ..wire import dispatch as wire_dispatch

        return wire_dispatch.wire_factor_allreduce(x, axes, mesh, name=name)

    def init_fn(params):
        return init_powersgd(params, rank)

    def update_fn(updates, state, params=None):
        del params
        if placement_warning:  # es is per-device, like EF state;
            # make_train_step(powersgd_rank=...) wires placement itself
            # and passes False
            from .grad_sync import _warn_ef_placement_once

            _warn_ef_placement_once("powersgd")
        leaves, treedef = jax.tree_util.tree_flatten(updates)
        if len(leaves) != len(state.qs):
            raise ValueError(
                "PowerSGD state was initialised from a different "
                f"parameter tree: got {len(leaves)} gradient leaves but "
                f"state holds {len(state.qs)} factors. Re-run "
                "init_powersgd on the tree actually being optimised."
            )
        out_scale = np.float32(1 if average else ws)
        out, qs_new, es_new = [], [], []
        for leaf, q, e in zip(leaves, state.qs, state.es):
            if q is None:
                g = leaf.astype(jnp.float32) / np.float32(
                    ws if average else 1
                )
                red = _psum(g)
                metrics.add("cgx.trace.powersgd.raw_elems", float(leaf.size))
                out.append(red.astype(leaf.dtype))
                qs_new.append(None)
                es_new.append(None)
                continue
            n, m = _matrix_shape(leaf.shape)
            mat = leaf.astype(jnp.float32).reshape(n, m) + e
            p = _factor_psum(mat @ q, "powersgd.p")  # scale irrelevant:
            p = _orthonormalize(p)                   # orthonormalized next
            # MEAN projection — see the module docstring on why /ws here.
            q_new = _factor_psum(mat.T @ p, "powersgd.q") / np.float32(ws)
            m_hat = p @ q_new.T
            metrics.add(
                "cgx.trace.powersgd.wire_elems", float((n + m) * q.shape[1])
            )
            metrics.add("cgx.trace.powersgd.grad_elems", float(n * m))
            out.append(
                (m_hat * out_scale).reshape(leaf.shape).astype(leaf.dtype)
            )
            qs_new.append(q_new)
            es_new.append(mat - m_hat)
        return (
            jax.tree_util.tree_unflatten(treedef, out),
            PowerSGDState(qs=tuple(qs_new), es=tuple(es_new)),
        )

    return optax.GradientTransformation(init_fn, update_fn)


def init_powersgd_state(
    params,
    mesh,
    rank: int,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis=None,
    *,
    seed: int = 0,
) -> PowerSGDState:
    """Placement-ready state for ``make_train_step(powersgd_rank=...)``:
    ``qs`` replicated; each ``es`` leaf stacked to ``(ws, n, m)`` and
    sharded over the sync axes on the leading device dim (the
    :func:`init_error_feedback` pattern), so every device owns exactly its
    own residual row."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    sync_axes = tuple(axes) if sp_axis is None else tuple(axes) + (sp_axis,)
    ws = int(np.prod([mesh.shape[a] for a in sync_axes]))
    # Build the factors directly rather than via init_powersgd: its (n, m)
    # zero residuals would be a full-parameter-sized allocation thrown
    # away immediately (the stacked per-device es replaces them).
    leaves = jax.tree_util.tree_leaves(params)
    qs, es = [], []
    for i, leaf in enumerate(leaves):
        if eligible(leaf, rank):
            n, m = _matrix_shape(leaf.shape)
            r = min(rank, n, m)
            key = jax.random.fold_in(jax.random.PRNGKey(seed), i)
            qs.append(
                jax.random.normal(key, (m, r), jnp.float32)
                / np.float32(np.sqrt(m))
            )
            es.append(jnp.zeros((ws, n, m), jnp.float32))
        else:
            qs.append(None)
            es.append(None)
    qs = jax.device_put(tuple(qs), NamedSharding(mesh, P()))
    es = jax.device_put(tuple(es), NamedSharding(mesh, P(sync_axes)))
    return PowerSGDState(qs=qs, es=es)


def compression_ratio(params, rank: int) -> float:
    """Whole-tree wire BYTES / raw BYTES under this rank: eligible leaves
    ship f32 factors regardless of gradient dtype (the power iteration
    runs in f32); the rest ship raw at their own width — so bf16 trees
    compress 2x less in bytes than in elements."""
    raw = wire = 0
    for leaf in jax.tree_util.tree_leaves(params):
        itemsize = np.dtype(leaf.dtype).itemsize
        raw += leaf.size * itemsize
        if eligible(leaf, rank):
            n, m = _matrix_shape(leaf.shape)
            wire += (n + m) * min(rank, n, m) * 4  # f32 factors
        else:
            wire += leaf.size * itemsize
    return wire / raw if raw else 1.0
