"""Device-mesh topology — the TPU replacement for MPIContext.

The reference builds a two-level topology from MPI communicator splits
(/root/reference/src/common/mpi_context.cc:25-35): a node-local "local"
communicator and a per-local-rank "cross" communicator. On TPU the same
hierarchy is a 2-D ``jax.sharding.Mesh`` with a fast intra-slice **ICI** axis
and a cross-slice **DCN** axis; XLA schedules the actual transport
(SURVEY.md §5.8). Axis names used throughout the framework:

* ``"intra"`` — ICI (the reference's local/SHM level)
* ``"cross"`` — DCN (the reference's cross-node MPI level)
* flat data-parallel meshes use a single ``"dp"`` axis.
"""

from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

INTRA_AXIS = "intra"
CROSS_AXIS = "cross"
DP_AXIS = "dp"


def init_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Multi-host bootstrap — the analogue of the reference's once-only
    ``MPI_Init_thread`` (ProcessGroupCGX.cc:242-257), built on
    ``jax.distributed.initialize`` (DCN control plane).

    Call once per process before building meshes. On Cloud TPU pods all
    arguments are auto-detected; elsewhere pass them explicitly or via
    ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` / ``JAX_PROCESS_ID``.
    Returns True if the distributed runtime was (or already is) initialized,
    False when running single-host with no coordinator configured (no-op).
    """
    import os

    # NOT jax.process_count(): that initializes the XLA backend, after which
    # jax.distributed.initialize() unconditionally raises.
    from ..utils.compat import distributed_is_initialized, ensure_cpu_collectives

    if distributed_is_initialized():
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS"
    )
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    num_processes = num_processes or (int(env_np) if env_np else None)
    env_pid = os.environ.get("JAX_PROCESS_ID")
    process_id = process_id if process_id is not None else (
        int(env_pid) if env_pid else None
    )
    on_pod = any(
        k in os.environ for k in ("TPU_WORKER_HOSTNAMES", "MEGASCALE_COORDINATOR_ADDRESS")
    )
    if coordinator_address is None and not on_pod:
        return False  # single host — nothing to bootstrap
    # CPU-pinned multi-process runs (the CI harness, dev boxes) need the
    # Gloo CPU collectives armed BEFORE the backend comes up — jax 0.4.x
    # defaults them off and every cross-process collective then fails.
    # Only HERE, behind the coordinator check: a gloo CPU client without a
    # distributed runtime fails backend init outright, so a single-host
    # process must never arm it.
    ensure_cpu_collectives()
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    return True


def flat_mesh(devices: Optional[Sequence] = None, axis: str = DP_AXIS) -> Mesh:
    """Single-axis data-parallel mesh over all (or given) devices."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (axis,))


def hierarchical_mesh(
    devices: Optional[Sequence] = None,
    intra_size: Optional[int] = None,
) -> Mesh:
    """2-D (cross, intra) mesh.

    ``intra_size`` defaults to the number of devices per process/host (the
    reference's node-local world, MPI_Comm_split_type(SHARED)) or, failing
    that, the largest power-of-two divisor <= 8.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = len(devices)
    if intra_size is None:
        local = jax.local_device_count()
        intra_size = local if (0 < local <= n and n % local == 0) else _pow2_div(n)
    if n % intra_size != 0:
        raise ValueError(f"{n} devices not divisible by intra_size={intra_size}")
    arr = np.asarray(devices).reshape(n // intra_size, intra_size)
    return Mesh(arr, (CROSS_AXIS, INTRA_AXIS))


def _pow2_div(n: int) -> int:
    p = 1
    while p * 2 <= min(n, 8) and n % (p * 2) == 0:
        p *= 2
    return p


def axis_size(mesh: Mesh, axis: str) -> int:
    return mesh.shape[axis]


def survivor_mesh(mesh: Mesh, survivors: Sequence[int], axis: str = DP_AXIS) -> Mesh:
    """Re-derive a mesh after the recovery supervisor evicted ranks: keep
    only the ``survivors`` positions along ``axis`` (sorted — survivor
    order must be identical on every rank or the reassembled meshes
    disagree), preserving every other axis.

    Also bumps the config registry version: the layout LRU
    (``allreduce._tree_layout``) and ``make_train_step``'s trace cache
    both key on it, so every plan derived for the dead world size is
    invalidated rather than silently reused — SRA/Ring chunking is a pure
    function of the axis size (``reducers.chunk_layout``) and re-derives
    at the next trace.
    """
    from .. import config as cfg

    names = list(mesh.axis_names)
    idx = names.index(axis)
    keep = sorted(int(s) for s in survivors)
    extent = mesh.devices.shape[idx]
    bad = [s for s in keep if not 0 <= s < extent]
    if bad:
        raise ValueError(
            f"survivor positions {bad} out of range for axis {axis!r} "
            f"(extent {extent})"
        )
    if not keep:
        raise ValueError("survivor_mesh: empty survivor set")
    arr = np.take(mesh.devices, keep, axis=idx)
    cfg._bump_registry_version()
    return Mesh(arr, tuple(names))


def make_training_mesh(
    n_devices: Optional[int] = None,
    *,
    dp: Optional[int] = None,
    tp: int = 1,
    sp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """General training mesh with (dp, pp, sp, tp, ep-folded-into-dp) axes.

    Axes with size 1 are still present so sharding specs are uniform; expert
    parallelism reuses the ``dp`` axis group by convention (experts sharded
    over dp) unless ``ep > 1`` which adds a dedicated axis.
    """
    devices = list(devices) if devices is not None else jax.devices()
    n = n_devices or len(devices)
    devices = devices[:n]
    used = tp * sp * pp * ep
    if dp is None:
        if n % used:
            raise ValueError(f"{n} devices not divisible by tp*sp*pp*ep={used}")
        dp = n // used
    if dp * used != n:
        raise ValueError(f"dp*tp*sp*pp*ep={dp * used} != {n} devices")
    names = ("dp", "pp", "sp", "tp", "ep")
    shape = (dp, pp, sp, tp, ep)
    arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, names)
