"""JAX-native front ends for compressed data-parallel training.

The reference integrates through a DDP communication hook
(/root/reference/cgx_utils/allreduce_hooks.py — SURVEY.md §2.2); the
TPU-native front door is functional instead:

* :func:`gradient_sync` — drop-in for ``lax.psum`` over gradient pytrees
  inside a user's own ``shard_map``.
* :func:`make_train_step` — wraps a loss function + optax optimizer into a
  jitted SPMD train step: per-device grads -> pre-divide -> quantized
  allreduce -> optimizer update. Replicated outputs are bit-identical across
  devices thanks to the reducers' error-symmetry invariant.
* :func:`compressed_allreduce_transform` — an ``optax`` gradient
  transformation for optimizer chains.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from typing import NamedTuple

from .. import config as cfg_mod
from ..config import TopologyConfig
from . import mesh as mesh_mod
from .allreduce import allreduce_tree


class ErrorFeedbackState(NamedTuple):
    """Per-device residual of the quantized gradient transport.

    HAZARD: this state VARIES across data-parallel devices — under
    shard_map it must be sharded (leading device axis or explicit
    per-device placement), NEVER declared replicated (``in_specs=P()``):
    XLA would then fold the divergent per-device residuals into one
    replica value and silently corrupt the correction. The safe wiring is
    ``make_train_step(..., error_feedback=True)`` +
    :func:`init_error_feedback`, which place the state on the device axis
    for you.
    """

    e: optax.Updates


_EF_PLACEMENT_WARNED = False


def _warn_ef_placement_once():
    """One-time trace-time reminder that EF state is per-device (the
    docstring-only hazard promoted to a runtime signal — advisor r3)."""
    global _EF_PLACEMENT_WARNED
    if _EF_PLACEMENT_WARNED:
        return
    _EF_PLACEMENT_WARNED = True
    import warnings

    warnings.warn(
        "error_feedback=True carries PER-DEVICE residual state: inside "
        "shard_map the ErrorFeedbackState must be sharded over the device "
        "axis, not declared replicated (in_specs=P()), or the residuals "
        "are silently corrupted. Use make_train_step(error_feedback=True) "
        "with init_error_feedback for the safe wiring.",
        stacklevel=3,
    )


def _ef_sync(grads, e, *, mesh, axes, topology, key, divisor):
    """Shared EF recipe (single source for the transform and the train
    step): pre-divide (§8.12 order), add residuals, quantized-sum, and
    measure the new residual against the sync's own stage-1 wire decode.
    Returns ``(reduced_f32, e_new)``."""
    g_eff = jax.tree.map(
        lambda g, ee: g.astype(jnp.float32) / divisor + ee, grads, e
    )
    reduced, rt = allreduce_tree(
        g_eff, mesh=mesh, axes=axes, topology=topology, key=key,
        average=False, return_roundtrip=True,
    )
    e_new = jax.tree.map(lambda g, r: g - r.astype(jnp.float32), g_eff, rt)
    return reduced, e_new


def gradient_sync(
    grads,
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    average: bool = True,
    compress_small: bool = False,
):
    """Quantized gradient allreduce (inside shard_map). Averaging divides
    before quantization, matching the hook order (SURVEY.md §8.12)."""
    return allreduce_tree(
        grads,
        mesh=mesh,
        axes=axes,
        topology=topology,
        key=key,
        average=average,
        compress_small=compress_small,
    )


def compressed_allreduce_transform(
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    average: bool = True,
    error_feedback: bool = False,
) -> optax.GradientTransformation:
    """optax transformation performing the quantized allreduce; prepend to an
    optimizer chain running inside shard_map:

        optax.chain(cgx.compressed_allreduce_transform(mesh=mesh), optax.adam(1e-3))

    ``error_feedback=True`` adds EF-style residual accumulation: the exact
    quantization error of this device's wire contribution (the sync's own
    stage-1 round trip, ``allreduce_tree(return_roundtrip=True)``) is
    carried in the optimizer state and added to the next step's gradient —
    the low-bit bias corrector the reference's kernels stub out but never
    wire (cuda_compression_operations.cu:69-84). It pays off when
    per-bucket outliers bias the quantization of small coordinates (see
    tests); at 1-bit it can HURT with the SRA transport — the residuals
    inflate the dynamic range the second-stage requantization must cover.
    The EF state is PER-DEVICE: inside shard_map, shard it (see
    :func:`make_train_step`'s ``error_feedback`` plumbing or manage the
    state placement yourself); declaring it replicated silently corrupts
    the residuals.

    **Ring-transport caveat** (applies to ``make_train_step`` too): with
    ``CGX_INNER_REDUCTION_TYPE=RING`` the measured residual covers the
    FIRST scatter-reduce hop only — later hops requantize accumulated
    partial sums on other devices and are treated as exact, so Ring EF is
    an approximation (it under-counts compounded hop error). SRA (the
    default) measures its wire residual exactly, byte-for-byte against
    the actual fused/chunked stage-1 layout (tested). Prefer SRA when
    running EF.
    """
    ws_total = int(np.prod([mesh.shape[a] for a in axes]))

    def init_fn(params):
        if not error_feedback:
            return optax.EmptyState()
        return ErrorFeedbackState(
            e=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
        )

    def update_fn(updates, state, params=None):
        del params
        if not error_feedback:
            return (
                gradient_sync(updates, mesh=mesh, axes=axes,
                              topology=topology, average=average),
                state,
            )
        _warn_ef_placement_once()
        reduced, e_new = _ef_sync(
            updates, state.e, mesh=mesh, axes=axes, topology=topology,
            key=None, divisor=ws_total if average else 1,
        )
        reduced = jax.tree.map(
            lambda r, u: r.astype(u.dtype), reduced, updates
        )
        return reduced, ErrorFeedbackState(e=e_new)

    return optax.GradientTransformation(init_fn, update_fn)


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh,
    *,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
    topology: Optional[TopologyConfig] = None,
    stochastic_seed: Optional[int] = None,
    donate: bool = True,
    error_feedback: bool = False,
    powersgd_rank: Optional[int] = None,
    topk_ratio: Optional[float] = None,
):
    """Build a jitted compressed-DP train step.

    ``loss_fn(params, batch) -> scalar loss`` is evaluated per device on its
    batch shard; gradients are synchronized with the quantized allreduce and
    the optimizer update runs replicated. A 3-argument
    ``loss_fn(params, batch, rng)`` also receives a fresh per-step, per-device
    PRNG key (for dropout etc. — pass it to ``model.apply`` as
    ``rngs={"dropout": rng}``); it is derived from ``stochastic_seed`` (or 0)
    folded with the step index and the device's data-parallel position.

    Returns ``step(params, opt_state, batch, step_idx) -> (params, opt_state,
    loss)`` where ``batch`` leaves are sharded on their leading dim over
    ``axes`` and params/opt_state are replicated.

    ``sp_axis``: sequence parallelism — batch leaves of rank >= 2 are
    additionally sharded on their SECOND dim (sequence) over this axis
    (rank-1 leaves such as sample weights have no sequence dim and stay
    replicated over sp), the per-shard
    loss is averaged over it (use a boundary-correct loss such as
    :func:`torch_cgx_tpu.models.gpt2.sp_lm_loss`), and gradients — partial
    sums over sequence shards — join the quantized allreduce over
    ``axes + (sp_axis,)``. Only a single dp axis composes with sp (the
    reducers support at most two allreduce axes).

    ``error_feedback=True`` carries a per-device quantization residual
    (see :func:`compressed_allreduce_transform`): the step signature
    becomes ``step(params, opt_state, ef, batch, step_idx) -> (params,
    opt_state, ef, loss)`` where ``ef`` comes from
    :func:`init_error_feedback` — leaves are ``(ws, *param.shape)``
    f32 sharded over the sync axes on the leading device dim, so every
    device keeps its own residual. NOTE: exact for the default SRA
    transport; with ``CGX_INNER_REDUCTION_TYPE=RING`` the residual
    covers the first scatter-reduce hop only (later hops' compounding
    requantization is treated as exact) — prefer SRA when running EF.

    ``powersgd_rank=r`` replaces the quantized allreduce with PowerSGD
    low-rank compression (:mod:`.powersgd`) at that rank — the SAFE
    wiring of its mixed-placement state: the step signature becomes
    ``step(params, opt_state, psgd, batch, step_idx) -> (params,
    opt_state, psgd, loss)`` with ``psgd`` from
    :func:`.powersgd.init_powersgd_state` (warm-start factors replicated,
    per-device residuals on a leading device axis). Mutually exclusive
    with ``error_feedback`` (PowerSGD carries its own EF).

    ``topk_ratio=r`` replaces the quantized allreduce with top-k
    sparsification (:mod:`.topk`) shipping the ``ceil(r * n)`` largest-
    magnitude coordinates per leaf: ``step(params, opt_state, tk, batch,
    step_idx) -> (params, opt_state, tk, loss)`` with ``tk`` from
    :func:`.topk.init_topk_state`. Mutually exclusive with
    ``error_feedback`` and ``powersgd_rank`` (top-k carries its own EF).
    """
    import inspect

    exclusive = [
        name
        for name, on in (
            ("error_feedback", error_feedback),
            ("powersgd_rank", powersgd_rank is not None),
            ("topk_ratio", topk_ratio is not None),
        )
        if on
    ]
    if len(exclusive) > 1:
        raise ValueError(
            f"make_train_step: {' and '.join(exclusive)} are mutually "
            "exclusive — each compressor carries its own error feedback"
        )
    axes = tuple(axes)
    sync_axes = axes if sp_axis is None else axes + (sp_axis,)
    if len(sync_axes) > 2:
        raise ValueError(
            "make_train_step: at most two gradient-sync axes (got "
            f"{sync_axes!r}); hierarchical dp (cross x intra) cannot also "
            "compose with sp_axis"
        )
    ws_total = int(np.prod([mesh.shape[a] for a in sync_axes]))
    wants_rng = len(inspect.signature(loss_fn).parameters) >= 3

    def _batch_leaf_spec(leaf) -> P:
        # sp shards the SECOND (sequence) dim, which rank-1 leaves (sample
        # weights, per-sequence labels) don't have — they stay replicated
        # over sp and shard only over the dp axes.
        if sp_axis is not None and getattr(leaf, "ndim", 0) >= 2:
            return P(axes, sp_axis)
        return P(axes)

    def _grads_and_key(params, batch, step_idx):
        if wants_rng:
            r = jax.random.fold_in(
                jax.random.PRNGKey(stochastic_seed or 0), step_idx
            )
            # decorrelate dropout masks across data-parallel devices
            for a in sync_axes:
                r = jax.random.fold_in(r, jax.lax.axis_index(a))
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, r)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        key = None
        if stochastic_seed is not None:
            key = jax.random.fold_in(jax.random.PRNGKey(stochastic_seed), step_idx)
        return loss, grads, key

    def _step(params, opt_state, batch, step_idx):
        loss, grads, key = _grads_and_key(params, batch, step_idx)
        grads = gradient_sync(
            grads, mesh=mesh, axes=sync_axes, topology=topology, key=key,
            average=True,
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        return params, opt_state, loss

    if powersgd_rank is not None:
        from .powersgd import PowerSGDState, powersgd_transform

        psgd_tx = powersgd_transform(
            mesh=mesh, axes=sync_axes, rank=powersgd_rank, average=True,
            placement_warning=False,
        )

    if topk_ratio is not None:
        from .topk import TopKState, topk_transform

        topk_tx = topk_transform(
            mesh=mesh, axes=sync_axes, ratio=topk_ratio, average=True,
            placement_warning=False,
        )

    def _step_topk(params, opt_state, tk, batch, step_idx):
        loss, grads, _ = _grads_and_key(params, batch, step_idx)
        local = TopKState(
            es=tuple(None if e is None else jnp.squeeze(e, 0) for e in tk.es)
        )
        reduced, st = topk_tx.update(grads, local)
        updates, opt_state = optimizer.update(reduced, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        out_state = TopKState(
            es=tuple(None if e is None else e[None] for e in st.es)
        )
        return params, opt_state, out_state, loss

    def _step_psgd(params, opt_state, psgd, batch, step_idx):
        loss, grads, _ = _grads_and_key(params, batch, step_idx)
        local = PowerSGDState(
            qs=psgd.qs,
            es=tuple(
                None if e is None else jnp.squeeze(e, 0) for e in psgd.es
            ),
        )
        reduced, st = psgd_tx.update(grads, local)
        updates, opt_state = optimizer.update(reduced, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        out_state = PowerSGDState(
            qs=st.qs,
            es=tuple(None if e is None else e[None] for e in st.es),
        )
        return params, opt_state, out_state, loss

    def _step_ef(params, opt_state, ef, batch, step_idx):
        loss, grads, key = _grads_and_key(params, batch, step_idx)
        e = jax.tree.map(lambda x: jnp.squeeze(x, 0), ef)
        reduced, e_new = _ef_sync(
            grads, e, mesh=mesh, axes=sync_axes, topology=topology,
            key=key, divisor=ws_total,
        )
        grads_out = jax.tree.map(
            lambda r, g: r.astype(g.dtype), reduced, grads
        )
        updates, opt_state = optimizer.update(grads_out, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        return (
            params,
            opt_state,
            jax.tree.map(lambda x: x[None], e_new),
            loss,
        )

    # The batch in_specs depend on per-leaf rank (rank-1 leaves can't carry
    # the sp dim), so the shard_map is built per batch tree-structure and
    # cached — jit retraces on structure change anyway.
    built = {}

    def _build(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        # Registry version in the key: per-layer configs are baked in at
        # trace time, so a re-registration (adapt_bits, new pattern
        # configs) must produce a fresh trace, not hit the stale one.
        version = cfg_mod.registry_version()
        cache_key = (
            treedef,
            tuple(getattr(l, "ndim", 0) for l in leaves),
            version,
        )
        # Evict traces from older registry versions — each holds a full
        # compiled executable and can never be hit again.
        for k in [k for k in built if k[2] != version]:
            del built[k]
        fn = built.get(cache_key)
        if fn is None:
            batch_spec = jax.tree_util.tree_unflatten(
                treedef, [_batch_leaf_spec(l) for l in leaves]
            )
            if powersgd_rank is not None:
                # pytree-prefix spec: replicated warm-start factors,
                # per-device residual rows on the leading device dim
                state_spec = PowerSGDState(qs=P(), es=P(sync_axes))
            elif topk_ratio is not None:
                state_spec = TopKState(es=P(sync_axes))
            else:
                state_spec = P(sync_axes)  # EF residual leaves
            with_state = (
                error_feedback
                or powersgd_rank is not None
                or topk_ratio is not None
            )
            if powersgd_rank is not None:
                body = _step_psgd
            elif topk_ratio is not None:
                body = _step_topk
            elif error_feedback:
                body = _step_ef
            else:
                body = _step
            sharded = jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    (P(), P(), state_spec, batch_spec, P())
                    if with_state
                    else (P(), P(), batch_spec, P())
                ),
                out_specs=(
                    (P(), P(), state_spec, P())
                    if with_state
                    else (P(), P(), P())
                ),
                # Only the gradient-sync (and sp) axes are manual; any other
                # mesh axis — tp, ep — stays under GSPMD control, so
                # tensor-parallel parameter shardings survive the step
                # instead of being gathered to replicated by in_specs=P()
                # (which speaks only of manual axes).
                axis_names=set(sync_axes),
                # Replication of params is guaranteed by construction (all
                # devices decode identical reduced bytes); the static
                # varying-axis analysis cannot see through the quantized
                # collective composition.
                check_vma=False,
            )
            donate_idx = ()
            if donate:
                # params, opt_state — and the EF/PowerSGD state, which is
                # param-sized f32 and would otherwise double-buffer.
                donate_idx = (0, 1, 2) if with_state else (0, 1)
            fn = jax.jit(sharded, donate_argnums=donate_idx)
            built[cache_key] = fn
        return fn

    if error_feedback or powersgd_rank is not None or topk_ratio is not None:

        def step(params, opt_state, state, batch, step_idx):
            return _build(batch)(params, opt_state, state, batch, step_idx)

    else:

        def step(params, opt_state, batch, step_idx):
            return _build(batch)(params, opt_state, batch, step_idx)

    return step


def init_error_feedback(
    params,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
):
    """Zero-initialized per-device EF residuals for
    :func:`make_train_step` ``(error_feedback=True)``: each leaf is
    ``(ws, *param.shape)`` f32, sharded over the sync axes on the leading
    device dim so every device owns exactly its own residual row."""
    from jax.sharding import NamedSharding

    sync_axes = tuple(axes) if sp_axis is None else tuple(axes) + (sp_axis,)
    ws = int(np.prod([mesh.shape[a] for a in sync_axes]))
    z = jax.tree.map(
        lambda p: jnp.zeros((ws,) + p.shape, jnp.float32), params
    )
    return jax.device_put(z, NamedSharding(mesh, P(sync_axes)))


def replicate(tree, mesh):
    """Place a pytree fully-replicated on the mesh."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(
    batch,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
):
    """Shard batch leaves along their leading dimension over ``axes`` (and,
    with ``sp_axis``, the second — sequence — dimension of rank >= 2 leaves
    over that axis; rank-1 leaves have no sequence dim and replicate over
    sp).

    Multi-host: each process passes its *local* slice and JAX assembles the
    global array (``make_array_from_process_local_data``) — no host ever
    materializes the global batch.
    """
    from jax.sharding import NamedSharding

    axes = tuple(axes)
    ws = int(np.prod([mesh.shape[a] for a in axes]))
    # Multi-host: each process contributes only its local slice, so the
    # divisibility requirement is the per-process device count along the dp
    # axes, not the global extent.
    procs = jax.process_count()
    local_ws = ws // procs if procs > 1 and ws % procs == 0 else ws

    def place(x):
        if hasattr(x, "shape") and x.shape and x.shape[0] % local_ws:
            raise ValueError(
                f"local batch leading dim {x.shape[0]} not divisible by the "
                f"per-process data-parallel extent {local_ws} (global mesh "
                f"{ws}, {procs} processes; drop or pad the remainder "
                "batch; see data.iterate_batches(drop_remainder=True))"
            )
        # Rank-1 leaves (sample weights, per-sequence labels) have no
        # sequence dim — they shard over dp only and replicate over sp.
        sp = sp_axis if getattr(x, "ndim", 0) >= 2 else None
        sharding = NamedSharding(mesh, P(axes) if sp is None else P(axes, sp))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            )
        return jax.device_put(x, sharding)

    return jax.tree.map(place, batch)
