"""JAX-native front ends for compressed data-parallel training.

The reference integrates through a DDP communication hook
(/root/reference/cgx_utils/allreduce_hooks.py — SURVEY.md §2.2); the
TPU-native front door is functional instead:

* :func:`gradient_sync` — drop-in for ``lax.psum`` over gradient pytrees
  inside a user's own ``shard_map``.
* :func:`make_train_step` — wraps a loss function + optax optimizer into a
  jitted SPMD train step: per-device grads -> pre-divide -> quantized
  allreduce -> optimizer update. Replicated outputs are bit-identical across
  devices thanks to the reducers' error-symmetry invariant.
* :func:`compressed_allreduce_transform` — an ``optax`` gradient
  transformation for optimizer chains.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from .. import config as cfg_mod
from ..config import TopologyConfig
from . import mesh as mesh_mod
from .allreduce import allreduce_tree


def gradient_sync(
    grads,
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    average: bool = True,
    compress_small: bool = False,
):
    """Quantized gradient allreduce (inside shard_map). Averaging divides
    before quantization, matching the hook order (SURVEY.md §8.12)."""
    return allreduce_tree(
        grads,
        mesh=mesh,
        axes=axes,
        topology=topology,
        key=key,
        average=average,
        compress_small=compress_small,
    )


def compressed_allreduce_transform(
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    average: bool = True,
) -> optax.GradientTransformation:
    """optax transformation performing the quantized allreduce; prepend to an
    optimizer chain running inside shard_map:

        optax.chain(cgx.compressed_allreduce_transform(mesh=mesh), optax.adam(1e-3))
    """

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        return (
            gradient_sync(updates, mesh=mesh, axes=axes, topology=topology,
                          average=average),
            state,
        )

    return optax.GradientTransformation(init_fn, update_fn)


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh,
    *,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
    topology: Optional[TopologyConfig] = None,
    stochastic_seed: Optional[int] = None,
    donate: bool = True,
):
    """Build a jitted compressed-DP train step.

    ``loss_fn(params, batch) -> scalar loss`` is evaluated per device on its
    batch shard; gradients are synchronized with the quantized allreduce and
    the optimizer update runs replicated. A 3-argument
    ``loss_fn(params, batch, rng)`` also receives a fresh per-step, per-device
    PRNG key (for dropout etc. — pass it to ``model.apply`` as
    ``rngs={"dropout": rng}``); it is derived from ``stochastic_seed`` (or 0)
    folded with the step index and the device's data-parallel position.

    Returns ``step(params, opt_state, batch, step_idx) -> (params, opt_state,
    loss)`` where ``batch`` leaves are sharded on their leading dim over
    ``axes`` and params/opt_state are replicated.

    ``sp_axis``: sequence parallelism — batch leaves of rank >= 2 are
    additionally sharded on their SECOND dim (sequence) over this axis
    (rank-1 leaves such as sample weights have no sequence dim and stay
    replicated over sp), the per-shard
    loss is averaged over it (use a boundary-correct loss such as
    :func:`torch_cgx_tpu.models.gpt2.sp_lm_loss`), and gradients — partial
    sums over sequence shards — join the quantized allreduce over
    ``axes + (sp_axis,)``. Only a single dp axis composes with sp (the
    reducers support at most two allreduce axes).
    """
    import inspect

    axes = tuple(axes)
    sync_axes = axes if sp_axis is None else axes + (sp_axis,)
    if len(sync_axes) > 2:
        raise ValueError(
            "make_train_step: at most two gradient-sync axes (got "
            f"{sync_axes!r}); hierarchical dp (cross x intra) cannot also "
            "compose with sp_axis"
        )
    ws_total = int(np.prod([mesh.shape[a] for a in sync_axes]))
    wants_rng = len(inspect.signature(loss_fn).parameters) >= 3

    def _batch_leaf_spec(leaf) -> P:
        # sp shards the SECOND (sequence) dim, which rank-1 leaves (sample
        # weights, per-sequence labels) don't have — they stay replicated
        # over sp and shard only over the dp axes.
        if sp_axis is not None and getattr(leaf, "ndim", 0) >= 2:
            return P(axes, sp_axis)
        return P(axes)

    def _step(params, opt_state, batch, step_idx):
        if wants_rng:
            r = jax.random.fold_in(
                jax.random.PRNGKey(stochastic_seed or 0), step_idx
            )
            # decorrelate dropout masks across data-parallel devices
            for a in sync_axes:
                r = jax.random.fold_in(r, jax.lax.axis_index(a))
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, r)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        key = None
        if stochastic_seed is not None:
            key = jax.random.fold_in(jax.random.PRNGKey(stochastic_seed), step_idx)
        grads = gradient_sync(
            grads, mesh=mesh, axes=sync_axes, topology=topology, key=key,
            average=True,
        )
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        return params, opt_state, loss

    # The batch in_specs depend on per-leaf rank (rank-1 leaves can't carry
    # the sp dim), so the shard_map is built per batch tree-structure and
    # cached — jit retraces on structure change anyway.
    built = {}

    def _build(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        cache_key = (treedef, tuple(getattr(l, "ndim", 0) for l in leaves))
        fn = built.get(cache_key)
        if fn is None:
            batch_spec = jax.tree_util.tree_unflatten(
                treedef, [_batch_leaf_spec(l) for l in leaves]
            )
            sharded = jax.shard_map(
                _step,
                mesh=mesh,
                in_specs=(P(), P(), batch_spec, P()),
                out_specs=(P(), P(), P()),
                # Only the gradient-sync (and sp) axes are manual; any other
                # mesh axis — tp, ep — stays under GSPMD control, so
                # tensor-parallel parameter shardings survive the step
                # instead of being gathered to replicated by in_specs=P()
                # (which speaks only of manual axes).
                axis_names=set(sync_axes),
                # Replication of params is guaranteed by construction (all
                # devices decode identical reduced bytes); the static
                # varying-axis analysis cannot see through the quantized
                # collective composition.
                check_vma=False,
            )
            fn = jax.jit(sharded, donate_argnums=(0, 1) if donate else ())
            built[cache_key] = fn
        return fn

    def step(params, opt_state, batch, step_idx):
        return _build(batch)(params, opt_state, batch, step_idx)

    return step


def replicate(tree, mesh):
    """Place a pytree fully-replicated on the mesh."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(
    batch,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
):
    """Shard batch leaves along their leading dimension over ``axes`` (and,
    with ``sp_axis``, the second — sequence — dimension of rank >= 2 leaves
    over that axis; rank-1 leaves have no sequence dim and replicate over
    sp).

    Multi-host: each process passes its *local* slice and JAX assembles the
    global array (``make_array_from_process_local_data``) — no host ever
    materializes the global batch.
    """
    from jax.sharding import NamedSharding

    axes = tuple(axes)
    ws = int(np.prod([mesh.shape[a] for a in axes]))
    # Multi-host: each process contributes only its local slice, so the
    # divisibility requirement is the per-process device count along the dp
    # axes, not the global extent.
    procs = jax.process_count()
    local_ws = ws // procs if procs > 1 and ws % procs == 0 else ws

    def place(x):
        if hasattr(x, "shape") and x.shape and x.shape[0] % local_ws:
            raise ValueError(
                f"local batch leading dim {x.shape[0]} not divisible by the "
                f"per-process data-parallel extent {local_ws} (global mesh "
                f"{ws}, {procs} processes; drop or pad the remainder "
                "batch; see data.iterate_batches(drop_remainder=True))"
            )
        # Rank-1 leaves (sample weights, per-sequence labels) have no
        # sequence dim — they shard over dp only and replicate over sp.
        sp = sp_axis if getattr(x, "ndim", 0) >= 2 else None
        sharding = NamedSharding(mesh, P(axes) if sp is None else P(axes, sp))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            )
        return jax.device_put(x, sharding)

    return jax.tree.map(place, batch)
