"""JAX-native front ends for compressed data-parallel training.

The reference integrates through a DDP communication hook
(/root/reference/cgx_utils/allreduce_hooks.py — SURVEY.md §2.2); the
TPU-native front door is functional instead:

* :func:`gradient_sync` — drop-in for ``lax.psum`` over gradient pytrees
  inside a user's own ``shard_map``.
* :func:`make_train_step` — wraps a loss function + optax optimizer into a
  jitted SPMD train step: per-device grads -> pre-divide -> quantized
  allreduce -> optimizer update. Replicated outputs are bit-identical across
  devices thanks to the reducers' error-symmetry invariant.
* :func:`compressed_allreduce_transform` — an ``optax`` gradient
  transformation for optimizer chains.
"""

from __future__ import annotations

import functools
import time
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from typing import NamedTuple

from .. import config as cfg_mod
from ..config import TopologyConfig
from ..utils.compat import shard_map as _compat_shard_map
from ..utils.logging import metrics
from . import mesh as mesh_mod
from . import reducers
from . import topology as topo_router
from .allreduce import allreduce_tree


class ErrorFeedbackState(NamedTuple):
    """Per-device residual of the quantized gradient transport.

    HAZARD: this state VARIES across data-parallel devices — under
    shard_map it must be sharded (leading device axis or explicit
    per-device placement), NEVER declared replicated (``in_specs=P()``):
    XLA would then fold the divergent per-device residuals into one
    replica value and silently corrupt the correction. The safe wiring is
    ``make_train_step(..., error_feedback=True)`` +
    :func:`init_error_feedback`, which place the state on the device axis
    for you.
    """

    e: optax.Updates


# cgx-analysis: allow(orphan-memo) — warn-once observability set; staleness only suppresses a duplicate placement warning
_PLACEMENT_WARNED: set = set()

# Per-compressor warning text: each points at ITS OWN safe wiring — the
# EF message told top-k users to call init_error_feedback, a dead end
# (advisor r5 low #2).
_PLACEMENT_MSGS = {
    "ef": (
        "error_feedback=True carries PER-DEVICE residual state: inside "
        "shard_map the ErrorFeedbackState must be sharded over the device "
        "axis, not declared replicated (in_specs=P()), or the residuals "
        "are silently corrupted. Use make_train_step(error_feedback=True) "
        "with init_error_feedback for the safe wiring."
    ),
    "topk": (
        "topk_transform carries PER-DEVICE error-feedback residuals "
        "(TopKState.es): inside shard_map the es leaves must be sharded "
        "over the device axis, not declared replicated, or the residuals "
        "are silently corrupted. Use make_train_step(topk_ratio=...) with "
        "init_topk_state for the safe wiring."
    ),
    "powersgd": (
        "powersgd_transform carries mixed-placement state: the warm-start "
        "factors (qs) are replicated but the residuals (es) are "
        "PER-DEVICE — inside shard_map the es leaves must be sharded over "
        "the device axis or they are silently corrupted. Use "
        "make_train_step(powersgd_rank=...) with init_powersgd_state for "
        "the safe wiring."
    ),
}


def _warn_ef_placement_once(kind: str = "ef"):
    """One-time (per compressor) trace-time reminder that the residual
    state is per-device (the docstring-only hazard promoted to a runtime
    signal — advisor r3; text parameterized per compressor — r5 low #2)."""
    if kind in _PLACEMENT_WARNED:
        return
    _PLACEMENT_WARNED.add(kind)
    import warnings

    warnings.warn(_PLACEMENT_MSGS[kind], stacklevel=3)


def _ef_sync(grads, e, *, mesh, axes, topology, key, divisor):
    """Shared EF recipe (single source for the transform and the train
    step): pre-divide (§8.12 order), add residuals, quantized-sum, and
    measure the new residual against the sync's own stage-1 wire decode.
    Returns ``(reduced_f32, e_new)``."""
    g_eff = jax.tree.map(
        lambda g, ee: g.astype(jnp.float32) / divisor + ee, grads, e
    )
    reduced, rt = allreduce_tree(
        g_eff, mesh=mesh, axes=axes, topology=topology, key=key,
        average=False, return_roundtrip=True,
    )
    e_new = jax.tree.map(lambda g, r: g - r.astype(jnp.float32), g_eff, rt)
    return reduced, e_new


# ---------------------------------------------------------------------------
# Non-finite gradient guard (CGX_NONFINITE_GUARD — docs/ROBUSTNESS.md).
#
# One NaN/Inf on ONE device poisons every max-min bucket range it shares a
# wire chunk with, on EVERY rank — compressed collectives amplify a point
# fault into whole-job divergence. The guard detects it pre-quantization,
# agrees globally (a psum'd flag, so all devices branch identically), and
# degrades gracefully: "skip" drops the step, "exact" reroutes the
# sanitized gradients through an uncompressed psum. Everything is built
# from `where`-selects, not `cond`, so the collective structure of the
# traced program is step-invariant (jit/SPMD-safe) and a no-fault step is
# bit-identical to a guard-off step.
# ---------------------------------------------------------------------------


def _global_nonfinite(grads, axes, mesh):
    """Group-global "any gradient is NaN/Inf" flag (bool scalar, identical
    on every device — psum of the per-device any)."""
    flags = [
        jnp.any(~jnp.isfinite(l))
        for l in jax.tree_util.tree_leaves(grads)
        if hasattr(l, "dtype") and jnp.issubdtype(l.dtype, jnp.floating)
    ]
    local = functools.reduce(jnp.logical_or, flags, jnp.asarray(False))
    f = local.astype(jnp.float32)
    for a in axes:
        if mesh.shape[a] > 1:
            f = jax.lax.psum(f, a)
    return f > 0


def _zero_when(bad, tree):
    """Whole tree -> zeros on a bad step (constant-zero buckets quantize
    exactly, so the compressor path stays structurally live but carries
    nothing); bit-identical pass-through otherwise."""
    return jax.tree.map(
        lambda x: jnp.where(bad, jnp.zeros_like(x), x), tree
    )


def _keep_when(bad, old, new):
    """Elementwise select: the pre-step value on a bad step, the computed
    one otherwise. NaNs confined to the untaken branch do not propagate
    (select, not arithmetic)."""
    return jax.tree.map(lambda o, n: jnp.where(bad, o, n), old, new)


def _sanitize(tree):
    """Zero exactly the non-finite coordinates (identity bits on finite
    ones) — what the "exact" fallback ships."""
    return jax.tree.map(
        lambda x: jnp.where(jnp.isfinite(x), x, jnp.zeros_like(x)), tree
    )


def _count_nonfinite(bad, axes):
    """Execution-time `cgx.nonfinite_steps` bump (the _runtime_count
    pattern): one increment per bad step, reported by the device at
    position 0 on every sync axis."""
    from jax.experimental import io_callback

    is0 = functools.reduce(
        jnp.logical_and,
        [jax.lax.axis_index(a) == 0 for a in axes],
        jnp.asarray(True),
    )

    def _sink(v):
        metrics.add("cgx.nonfinite_steps", float(v))
        if v:
            # Guard trip: black-box the evidence (docs/OBSERVABILITY.md).
            # record() is ring-cheap and runs every trip; the full-ring
            # dump is rate-limited (first trip, then every 32nd) so a
            # diverged run that trips EVERY step doesn't rewrite the
            # dump file ~100 KB/step for its remainder.
            from ..observability import flightrec

            flightrec.record("nonfinite_guard", steps=float(v))
            n = int(metrics.get("cgx.nonfinite_steps"))
            if n == 1 or n % 32 == 0:
                flightrec.dump(reason="nonfinite_guard")

    io_callback(
        _sink,
        None,
        jnp.where(jnp.logical_and(bad, is0), 1.0, 0.0).astype(jnp.float32),
        ordered=False,
    )


def _guard_policy(explicit: Optional[str]) -> str:
    p = explicit if explicit is not None else cfg_mod.nonfinite_guard()
    if p not in cfg_mod.NONFINITE_POLICIES:
        raise ValueError(
            f"nonfinite_guard must be one of {cfg_mod.NONFINITE_POLICIES}, "
            f"got {p!r}"
        )
    return p


def gradient_sync(
    grads,
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    key: Optional[jax.Array] = None,
    average: bool = True,
    compress_small: bool = False,
    nonfinite_guard: Optional[str] = None,
):
    """Quantized gradient allreduce (inside shard_map). Averaging divides
    before quantization, matching the hook order (SURVEY.md §8.12).

    ``nonfinite_guard`` (default: ``CGX_NONFINITE_GUARD``, off): with
    "skip" a step whose gradients contain NaN/Inf anywhere in the group
    returns all-zero reduced gradients (the step becomes a no-op for
    SGD-style optimizers; for full parameter/optimizer-state rollback use
    ``make_train_step``, which owns the update); with "exact" the
    sanitized gradients ride an uncompressed psum for that step instead of
    poisoning the quantization buckets. Either way ``cgx.nonfinite_steps``
    counts the event at execution time."""
    policy = _guard_policy(nonfinite_guard)
    if policy == "off":
        return allreduce_tree(
            grads,
            mesh=mesh,
            axes=axes,
            topology=topology,
            key=key,
            average=average,
            compress_small=compress_small,
        )
    axes = tuple(axes)
    bad = _global_nonfinite(grads, axes, mesh)
    _count_nonfinite(bad, axes)
    reduced = allreduce_tree(
        _zero_when(bad, grads),
        mesh=mesh,
        axes=axes,
        topology=topology,
        key=key,
        average=average,
        compress_small=compress_small,
    )
    if policy == "exact":
        ws = int(np.prod([mesh.shape[a] for a in axes]))
        exact = reducers.psum_tree(_sanitize(grads), axes, mesh)
        if average:
            exact = jax.tree.map(lambda x: x / ws, exact)
        reduced = jax.tree.map(
            lambda e, r: jnp.where(bad, e.astype(r.dtype), r), exact, reduced
        )
    return reduced


def compressed_allreduce_transform(
    *,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    topology: Optional[TopologyConfig] = None,
    average: bool = True,
    error_feedback: bool = False,
) -> optax.GradientTransformation:
    """optax transformation performing the quantized allreduce; prepend to an
    optimizer chain running inside shard_map:

        optax.chain(cgx.compressed_allreduce_transform(mesh=mesh), optax.adam(1e-3))

    ``error_feedback=True`` adds EF-style residual accumulation: the exact
    quantization error of this device's wire contribution (the sync's own
    stage-1 round trip, ``allreduce_tree(return_roundtrip=True)``) is
    carried in the optimizer state and added to the next step's gradient —
    the low-bit bias corrector the reference's kernels stub out but never
    wire (cuda_compression_operations.cu:69-84). It pays off when
    per-bucket outliers bias the quantization of small coordinates (see
    tests); at 1-bit it can HURT with the SRA transport — the residuals
    inflate the dynamic range the second-stage requantization must cover.
    The EF state is PER-DEVICE: inside shard_map, shard it (see
    :func:`make_train_step`'s ``error_feedback`` plumbing or manage the
    state placement yourself); declaring it replicated silently corrupts
    the residuals.

    **Ring-transport caveat** (applies to ``make_train_step`` too): with
    ``CGX_INNER_REDUCTION_TYPE=RING`` the measured residual covers the
    FIRST scatter-reduce hop only — later hops requantize accumulated
    partial sums on other devices and are treated as exact, so Ring EF is
    an approximation (it under-counts compounded hop error). SRA (the
    default) measures its wire residual exactly, byte-for-byte against
    the actual fused/chunked stage-1 layout (tested). Prefer SRA when
    running EF.
    """
    ws_total = int(np.prod([mesh.shape[a] for a in axes]))

    def init_fn(params):
        if not error_feedback:
            return optax.EmptyState()
        return ErrorFeedbackState(
            e=jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params
            )
        )

    def update_fn(updates, state, params=None):
        del params
        if not error_feedback:
            return (
                gradient_sync(updates, mesh=mesh, axes=axes,
                              topology=topology, average=average),
                state,
            )
        _warn_ef_placement_once()
        reduced, e_new = _ef_sync(
            updates, state.e, mesh=mesh, axes=axes, topology=topology,
            key=None, divisor=ws_total if average else 1,
        )
        reduced = jax.tree.map(
            lambda r, u: r.astype(u.dtype), reduced, updates
        )
        return reduced, ErrorFeedbackState(e=e_new)

    return optax.GradientTransformation(init_fn, update_fn)


def make_train_step(
    loss_fn: Callable,
    optimizer: optax.GradientTransformation,
    mesh,
    *,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
    topology: Optional[TopologyConfig] = None,
    stochastic_seed: Optional[int] = None,
    donate: bool = True,
    error_feedback: bool = False,
    powersgd_rank: Optional[int] = None,
    topk_ratio: Optional[float] = None,
    nonfinite_guard: Optional[str] = None,
    snapshot_every: Optional[int] = None,
    outer: Optional[Any] = None,
):
    """Build a jitted compressed-DP train step.

    ``outer`` (default None): an
    :class:`~torch_cgx_tpu.parallel.async_plane.AsyncPlane` — the PR 13
    asynchronous cross-slice hook. After the jitted call, the plane runs
    host-side on the updated params: every ``CGX_ASYNC_H``-th step it
    posts this slice's compressed parameter delta to the dedicated
    sender thread (never blocking on DCN) and folds arrived peer deltas
    into the outer anchor, which becomes the returned params. Pure
    Python around the jit boundary — the staged program is UNCHANGED
    (the jaxpr pin in tests/test_async_plane.py), and with ``CGX_ASYNC``
    unset (or ``outer=None``) the hook is an identity.

    ``snapshot_every`` (default: ``CGX_SNAPSHOT_EVERY`` env, 0 = off):
    the recovery supervisor's rollback hook. Every N-th step the wrapper
    host-copies the step's *inputs* (params, opt_state, compressor state
    when present) via ``checkpoint.snapshot_in_memory`` — registry
    snapshot included — BEFORE invoking the compiled program, so a
    recovery can roll back to ``step.last_snapshot()`` / ``step.rollback()``
    and deterministically replay. Pure Python around the jit boundary:
    the staged program is unchanged, and with the knob unset nothing is
    copied (docs/ROBUSTNESS.md Recovery).

    ``nonfinite_guard`` (default: ``CGX_NONFINITE_GUARD`` env, off):
    NaN/Inf gradients anywhere in the group are detected pre-quantization
    and the step degrades gracefully — "skip" keeps params, optimizer
    state AND compressor state (EF/PowerSGD/top-k residuals) at their
    pre-step values; "exact" applies the update from an uncompressed psum
    of the sanitized gradients while still freezing the compressor state
    for that step. Both bump the execution-time ``cgx.nonfinite_steps``
    counter and are bit-identical to "off" on fault-free steps (pure
    `where`-selects; the staged collectives never change across steps).
    Costs when enabled: an isfinite sweep + scalar psum + one host
    callback per step, and for "exact" one full uncompressed psum per
    step (the fallback traffic is staged unconditionally — prefer "skip"
    unless you need every step applied).

    ``loss_fn(params, batch) -> scalar loss`` is evaluated per device on its
    batch shard; gradients are synchronized with the quantized allreduce and
    the optimizer update runs replicated. A 3-argument
    ``loss_fn(params, batch, rng)`` also receives a fresh per-step, per-device
    PRNG key (for dropout etc. — pass it to ``model.apply`` as
    ``rngs={"dropout": rng}``); it is derived from ``stochastic_seed`` (or 0)
    folded with the step index and the device's data-parallel position.

    Returns ``step(params, opt_state, batch, step_idx) -> (params, opt_state,
    loss)`` where ``batch`` leaves are sharded on their leading dim over
    ``axes`` and params/opt_state are replicated.

    ``sp_axis``: sequence parallelism — batch leaves of rank >= 2 are
    additionally sharded on their SECOND dim (sequence) over this axis
    (rank-1 leaves such as sample weights have no sequence dim and stay
    replicated over sp), the per-shard
    loss is averaged over it (use a boundary-correct loss such as
    :func:`torch_cgx_tpu.models.gpt2.sp_lm_loss`), and gradients — partial
    sums over sequence shards — join the quantized allreduce over
    ``axes + (sp_axis,)``. Only a single dp axis composes with sp (the
    reducers support at most two allreduce axes).

    ``error_feedback=True`` carries a per-device quantization residual
    (see :func:`compressed_allreduce_transform`): the step signature
    becomes ``step(params, opt_state, ef, batch, step_idx) -> (params,
    opt_state, ef, loss)`` where ``ef`` comes from
    :func:`init_error_feedback` — leaves are ``(ws, *param.shape)``
    f32 sharded over the sync axes on the leading device dim, so every
    device keeps its own residual. NOTE: exact for the default SRA
    transport; with ``CGX_INNER_REDUCTION_TYPE=RING`` the residual
    covers the first scatter-reduce hop only (later hops' compounding
    requantization is treated as exact) — prefer SRA when running EF.

    ``powersgd_rank=r`` replaces the quantized allreduce with PowerSGD
    low-rank compression (:mod:`.powersgd`) at that rank — the SAFE
    wiring of its mixed-placement state: the step signature becomes
    ``step(params, opt_state, psgd, batch, step_idx) -> (params,
    opt_state, psgd, loss)`` with ``psgd`` from
    :func:`.powersgd.init_powersgd_state` (warm-start factors replicated,
    per-device residuals on a leading device axis). Mutually exclusive
    with ``error_feedback`` (PowerSGD carries its own EF).

    ``topk_ratio=r`` replaces the quantized allreduce with top-k
    sparsification (:mod:`.topk`) shipping the ``ceil(r * n)`` largest-
    magnitude coordinates per leaf: ``step(params, opt_state, tk, batch,
    step_idx) -> (params, opt_state, tk, loss)`` with ``tk`` from
    :func:`.topk.init_topk_state`. Mutually exclusive with
    ``error_feedback`` and ``powersgd_rank`` (top-k carries its own EF).
    """
    import inspect

    exclusive = [
        name
        for name, on in (
            ("error_feedback", error_feedback),
            ("powersgd_rank", powersgd_rank is not None),
            ("topk_ratio", topk_ratio is not None),
        )
        if on
    ]
    if len(exclusive) > 1:
        raise ValueError(
            f"make_train_step: {' and '.join(exclusive)} are mutually "
            "exclusive — each compressor carries its own error feedback"
        )
    axes = tuple(axes)
    sync_axes = axes if sp_axis is None else axes + (sp_axis,)
    if len(sync_axes) > 2:
        raise ValueError(
            "make_train_step: at most two gradient-sync axes (got "
            f"{sync_axes!r}); hierarchical dp (cross x intra) cannot also "
            "compose with sp_axis"
        )
    ws_total = int(np.prod([mesh.shape[a] for a in sync_axes]))
    wants_rng = len(inspect.signature(loss_fn).parameters) >= 3
    guard = _guard_policy(nonfinite_guard)
    # Armed nan_grad fault (CGX_FAULTS) — staged into the trace so the
    # poison originates inside the compiled program, upstream of the
    # quantizer, exactly where a real overflow NaN would.
    from ..robustness import guard as _rguard

    nan_spec = _rguard.nan_grad_spec()

    def _batch_leaf_spec(leaf) -> P:
        # sp shards the SECOND (sequence) dim, which rank-1 leaves (sample
        # weights, per-sequence labels) don't have — they stay replicated
        # over sp and shard only over the dp axes.
        if sp_axis is not None and getattr(leaf, "ndim", 0) >= 2:
            return P(axes, sp_axis)
        return P(axes)

    def _grads_and_key(params, batch, step_idx):
        # Producer-fused stash epoch: entries staged by THIS trace's
        # backward are the only ones its allreduce may claim (trace-time
        # Python — nothing staged changes when the plane is off).
        from ..ops import fused_producer as _fp

        _fp.begin_step()
        if wants_rng:
            r = jax.random.fold_in(
                jax.random.PRNGKey(stochastic_seed or 0), step_idx
            )
            # decorrelate dropout masks across data-parallel devices
            for a in sync_axes:
                r = jax.random.fold_in(r, jax.lax.axis_index(a))
            loss, grads = jax.value_and_grad(loss_fn)(params, batch, r)
        else:
            loss, grads = jax.value_and_grad(loss_fn)(params, batch)
        if nan_spec is not None:
            grads = _rguard.inject_nan(grads, step_idx, sync_axes, nan_spec)
        key = None
        if stochastic_seed is not None:
            key = jax.random.fold_in(jax.random.PRNGKey(stochastic_seed), step_idx)
        return loss, grads, key

    def _guard_pre(grads):
        """(grads-for-the-compressor, bad-flag) — identity/(None) off."""
        if guard == "off":
            return grads, None
        bad = _global_nonfinite(grads, sync_axes, mesh)
        _count_nonfinite(bad, sync_axes)
        return _zero_when(bad, grads), bad

    def _guard_reduced(bad, grads_raw, reduced):
        """"exact" fallback: on a bad step swap in the uncompressed psum
        of the sanitized raw gradients (averaged, like the compressor
        path); `reduced` there is the compressor's output for the zeroed
        tree. Fault-free steps pass `reduced` through bit-identically."""
        if bad is None or guard != "exact":
            return reduced
        exact = reducers.psum_tree(_sanitize(grads_raw), sync_axes, mesh)
        return jax.tree.map(
            lambda e, r: jnp.where(bad, (e / ws_total).astype(r.dtype), r),
            exact,
            reduced,
        )

    def _guard_state(bad, old, new):
        """Compressor state (EF/PowerSGD/top-k residuals) freezes on a bad
        step under BOTH policies: the wire carried zeros, so that step's
        measured residual describes nothing."""
        return new if bad is None else _keep_when(bad, old, new)

    def _guard_update(bad, old_p, old_s, new_p, new_s):
        """"skip": params + optimizer state roll back to pre-step values
        on a bad step. "exact" applies the fallback update as-is."""
        if bad is None or guard != "skip":
            return new_p, new_s
        return _keep_when(bad, old_p, new_p), _keep_when(bad, old_s, new_s)

    def _step(params, opt_state, batch, step_idx):
        loss, grads, key = _grads_and_key(params, batch, step_idx)
        g_c, bad = _guard_pre(grads)
        reduced = gradient_sync(
            g_c, mesh=mesh, axes=sync_axes, topology=topology, key=key,
            average=True, nonfinite_guard="off",
        )
        reduced = _guard_reduced(bad, grads, reduced)
        updates, new_opt = optimizer.update(reduced, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params, new_opt = _guard_update(
            bad, params, opt_state, new_params, new_opt
        )
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        return new_params, new_opt, loss

    if powersgd_rank is not None:
        from .powersgd import PowerSGDState, powersgd_transform

        psgd_tx = powersgd_transform(
            mesh=mesh, axes=sync_axes, rank=powersgd_rank, average=True,
            placement_warning=False,
        )

    if topk_ratio is not None:
        from .topk import TopKState, topk_transform

        topk_tx = topk_transform(
            mesh=mesh, axes=sync_axes, ratio=topk_ratio, average=True,
            placement_warning=False,
        )

    def _step_topk(params, opt_state, tk, batch, step_idx):
        loss, grads, _ = _grads_and_key(params, batch, step_idx)
        local = TopKState(
            es=tuple(None if e is None else jnp.squeeze(e, 0) for e in tk.es)
        )
        g_c, bad = _guard_pre(grads)
        loc_c = local if bad is None else TopKState(
            es=tuple(
                None if e is None else jnp.where(bad, jnp.zeros_like(e), e)
                for e in local.es
            )
        )
        reduced, st = topk_tx.update(g_c, loc_c)
        reduced = _guard_reduced(bad, grads, reduced)
        st = _guard_state(bad, local, st)
        updates, new_opt = optimizer.update(reduced, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params, new_opt = _guard_update(
            bad, params, opt_state, new_params, new_opt
        )
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        out_state = TopKState(
            es=tuple(None if e is None else e[None] for e in st.es)
        )
        return new_params, new_opt, out_state, loss

    def _step_psgd(params, opt_state, psgd, batch, step_idx):
        loss, grads, _ = _grads_and_key(params, batch, step_idx)
        local = PowerSGDState(
            qs=psgd.qs,
            es=tuple(
                None if e is None else jnp.squeeze(e, 0) for e in psgd.es
            ),
        )
        g_c, bad = _guard_pre(grads)
        loc_c = local if bad is None else PowerSGDState(
            qs=local.qs,  # orthonormalization of zeroed grads may NaN; the
            es=tuple(     # whole state is selected back below regardless
                None if e is None else jnp.where(bad, jnp.zeros_like(e), e)
                for e in local.es
            ),
        )
        reduced, st = psgd_tx.update(g_c, loc_c)
        reduced = _guard_reduced(bad, grads, reduced)
        st = _guard_state(bad, local, st)
        updates, new_opt = optimizer.update(reduced, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params, new_opt = _guard_update(
            bad, params, opt_state, new_params, new_opt
        )
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        out_state = PowerSGDState(
            qs=st.qs,
            es=tuple(None if e is None else e[None] for e in st.es),
        )
        return new_params, new_opt, out_state, loss

    def _step_ef(params, opt_state, ef, batch, step_idx):
        loss, grads, key = _grads_and_key(params, batch, step_idx)
        e = jax.tree.map(lambda x: jnp.squeeze(x, 0), ef)
        g_c, bad = _guard_pre(grads)
        e_c = e if bad is None else _zero_when(bad, e)
        reduced, e_new = _ef_sync(
            g_c, e_c, mesh=mesh, axes=sync_axes, topology=topology,
            key=key, divisor=ws_total,
        )
        reduced = _guard_reduced(bad, grads, reduced)
        e_new = _guard_state(bad, e, e_new)
        grads_out = jax.tree.map(
            lambda r, g: r.astype(g.dtype), reduced, grads
        )
        updates, new_opt = optimizer.update(grads_out, opt_state, params)
        new_params = optax.apply_updates(params, updates)
        new_params, new_opt = _guard_update(
            bad, params, opt_state, new_params, new_opt
        )
        loss = jax.lax.psum(loss, sync_axes) / ws_total
        return (
            new_params,
            new_opt,
            jax.tree.map(lambda x: x[None], e_new),
            loss,
        )

    # The batch in_specs depend on per-leaf rank (rank-1 leaves can't carry
    # the sp dim), so the shard_map is built per batch tree-structure and
    # cached — jit retraces on structure change anyway.
    built = {}

    def _build(batch):
        leaves, treedef = jax.tree_util.tree_flatten(batch)
        # Registry version in the key: per-layer configs are baked in at
        # trace time, so a re-registration (adapt_bits, new pattern
        # configs) must produce a fresh trace, not hit the stale one.
        version = cfg_mod.registry_version()
        # Topology-route component: a CGX_XLA_ALLREDUCE flip (or a mesh
        # whose groups reclassify) changes what allreduce_tree stages, so
        # it must produce a fresh trace, never hit one from another
        # routing era — same contract as the registry version.
        xla_route = topo_router.cache_key(mesh, sync_axes)
        # Schedule component: a CGX_SCHEDULE/CGX_SCHED_CHUNKS flip changes
        # the emission (pipelined chunks, reverse-order group dispatch) of
        # the staged program — it must retrace, never serve a trace from
        # another scheduling era.
        from . import schedule as sched_mod

        sched_key = sched_mod.cache_key_component()
        # Step-planner component: a CGX_PLANNER flip or an ADOPTED
        # re-plan (the planner bumps its plan version only when the
        # calibrated model actually moved) must retrace; an unchanged
        # re-plan keeps the key — the no-retrace-storm half of the
        # planner's idempotency contract.
        from . import planner as planner_mod

        planner_key = planner_mod.cache_key_component()
        # Wire-plane component: a CGX_WIRE/CGX_WIRE_BITS flip changes what
        # any routed edge inside loss_fn (ring-attention hops, MoE
        # dispatch) stages — it must retrace, never serve a trace from
        # another wire era. Registered-edge changes ride the registry
        # version above.
        from ..wire import edges as wire_edges

        wire_key = wire_edges.cache_key_component()
        # Producer-fuse component: a CGX_PRODUCER_FUSE flip changes which
        # gradients enter the wire pre-quantized — it must retrace, never
        # serve a program from another producer era. Configuring the
        # producer context happens here too (trace-time state the
        # backward rules read); consumption self-disarms under the
        # nonfinite guard and the stateful compressors because their
        # gradient rewrites break the cotangent-identity match, but the
        # explicit gate keeps the staged payloads from even being built.
        from ..ops import fused_producer as _fp

        _fp.configure(
            mesh, sync_axes, divisor=ws_total,
            active=(
                guard == "off"
                and not error_feedback
                and powersgd_rank is None
                and topk_ratio is None
            ),
        )
        producer_key = _fp.cache_key_component()
        # Env component: every CGX_* knob the traced step bakes in
        # (codec lowering/encode, compression defaults, fusion split,
        # qerr/runtime-metrics staging, the nonfinite guard) — a flip of
        # any of them between calls must retrace, never serve a program
        # from another env era. The registry version above only covers
        # REGISTERED config; this covers the env tier (the analyzer's
        # knob→cache-key pass pins the set — tools/analysis/knobs.py).
        env_key = cfg_mod.trace_knob_fingerprint()
        cache_key = (
            treedef,
            tuple(getattr(l, "ndim", 0) for l in leaves),
            version,
            xla_route,
            sched_key,
            wire_key,
            producer_key,
            planner_key,
            env_key,
        )
        # Evict traces from older registry versions — each holds a full
        # compiled executable and can never be hit again.
        for k in [k for k in built if k[2] != version]:
            del built[k]
        fn = built.get(cache_key)
        if fn is None:
            batch_spec = jax.tree_util.tree_unflatten(
                treedef, [_batch_leaf_spec(l) for l in leaves]
            )
            if powersgd_rank is not None:
                # pytree-prefix spec: replicated warm-start factors,
                # per-device residual rows on the leading device dim
                state_spec = PowerSGDState(qs=P(), es=P(sync_axes))
            elif topk_ratio is not None:
                state_spec = TopKState(es=P(sync_axes))
            else:
                state_spec = P(sync_axes)  # EF residual leaves
            with_state = (
                error_feedback
                or powersgd_rank is not None
                or topk_ratio is not None
            )
            if powersgd_rank is not None:
                body = _step_psgd
                compressor = f"powersgd(rank={powersgd_rank})"
            elif topk_ratio is not None:
                body = _step_topk
                compressor = f"topk(ratio={topk_ratio})"
            elif error_feedback:
                body = _step_ef
                compressor = "quantized+ef"
            else:
                body = _step
                compressor = "quantized"
            # Trace-time event: one per compiled train step (a retrace storm
            # shows up in the flight recorder as a run of these).
            from ..observability import flightrec, timeline

            metrics.add("cgx.trace.train_step_builds")
            if xla_route[0] == topo_router.ROUTE_STAGED:
                metrics.add("cgx.xla.train_steps_staged")
            flightrec.record(
                "train_step_trace",
                compressor=compressor,
                sync_axes=list(sync_axes),
                guard=guard,
                registry_version=version,
                xla_route=list(xla_route),
                schedule=list(sched_key),
                planner=list(planner_key),
            )
            timeline.instant(
                "train_step_trace",
                compressor=compressor,
                guard=guard,
                registry_version=version,
                xla_route=list(xla_route),
                schedule=list(sched_key),
            )
            sharded = _compat_shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    (P(), P(), state_spec, batch_spec, P())
                    if with_state
                    else (P(), P(), batch_spec, P())
                ),
                out_specs=(
                    (P(), P(), state_spec, P())
                    if with_state
                    else (P(), P(), P())
                ),
                # Only the gradient-sync (and sp) axes are manual; any other
                # mesh axis — tp, ep — stays under GSPMD control, so
                # tensor-parallel parameter shardings survive the step
                # instead of being gathered to replicated by in_specs=P()
                # (which speaks only of manual axes).
                axis_names=set(sync_axes),
                # Replication of params is guaranteed by construction (all
                # devices decode identical reduced bytes); the static
                # varying-axis analysis cannot see through the quantized
                # collective composition.
                check_vma=False,
            )
            donate_idx = ()
            if donate:
                # params, opt_state — and the EF/PowerSGD state, which is
                # param-sized f32 and would otherwise double-buffer.
                donate_idx = (0, 1, 2) if with_state else (0, 1)
            fn = jax.jit(sharded, donate_argnums=donate_idx)
            built[cache_key] = fn
        return fn

    # Recovery rollback hook: in-memory snapshots of the step INPUTS at a
    # fixed cadence, taken on the host before the jitted call (donation
    # invalidates the device buffers afterwards, so the copy must happen
    # here). Holder is shared by both signatures below.
    snap_every = (
        snapshot_every if snapshot_every is not None
        else cfg_mod.snapshot_every()
    )
    snap_holder = {"snap": None, "outer": None}

    def _maybe_snapshot(step_idx, tree) -> None:
        if not snap_every:
            return
        idx = int(step_idx)
        if idx % snap_every == 0:
            from .. import checkpoint as ckpt

            snap_holder["snap"] = ckpt.snapshot_in_memory(tree, idx)
            # The async plane's outer state (anchor, EF, momentum,
            # round, generation) is part of the rollback point: a
            # replay against the crash-time anchor would compute wrong
            # deltas and re-post advanced rounds (docs/ROBUSTNESS.md
            # "Async recovery semantics").
            snap_holder["outer"] = (
                outer.export_state() if outer is not None else None
            )
            metrics.add("cgx.recovery.snapshots")

    # Live health plane: step cadence measured host-side, dispatch to
    # dispatch — under steady async pipelining the inter-call gap IS the
    # step time (blocking on the result would serialize the pipeline).
    # The histogram feeds cgx_top's step rate and the health engine's
    # regression detector; pure host bookkeeping, nothing staged changes.
    from ..observability import health as health_mod
    from ..observability import memledger as memledger_mod
    from ..observability import watch as watch_mod

    # process_index, not 0: on the multi-process JAX path this is the
    # authoritative rank, and pinning 0 here would make every process
    # write the same health-rank0 files on a shared metrics dir. A
    # torch-bridge process that builds the step fn before dist init
    # still gets rebound when ProcessGroupCGX passes the real rank.
    _rank_hint = jax.process_index()
    health_mod.maybe_start(_rank_hint)
    memledger_mod.maybe_start(_rank_hint)
    watch_mod.maybe_start_prom(_rank_hint)
    step_clock = {"t": None}

    def _note_step_cadence() -> None:
        t_now = time.perf_counter()
        prev, step_clock["t"] = step_clock["t"], t_now
        if prev is not None:
            dt = t_now - prev
            metrics.observe("cgx.step.time_s", dt)
            health_mod.note_step(dt)
            # Step boundary marker for the critical-path engine: window
            # segmentation prefers these over collective-round ends.
            from ..observability import timeline as timeline_mod

            timeline_mod.instant(
                "step", cat=timeline_mod.CAT_TRACE, dt_s=round(dt, 6)
            )
        metrics.add("cgx.step.count")

    def _apply_outer(step_idx, params):
        """PR 13 outer hook: host-side local-SGD boundary on the updated
        params. The flatten (a full device→host param copy) runs ONLY
        when the plane would actually act this step
        (``AsyncPlane.wants_params`` — knob off, disengaged, and
        non-boundary steps all skip it; non-boundary drains happen
        inside the gate and need no params)."""
        if outer is None or not outer.wants_params(int(step_idx)):
            return params
        from . import async_plane as async_mod

        flat, unflatten = async_mod.flatten_tree(params)
        new_flat = outer.maybe_outer_step(int(step_idx), flat)
        if new_flat is flat:
            return params
        return unflatten(new_flat)

    if error_feedback or powersgd_rank is not None or topk_ratio is not None:

        def step(params, opt_state, state, batch, step_idx):
            _note_step_cadence()
            _maybe_snapshot(step_idx, (params, opt_state, state))
            new_p, new_opt, new_state, loss = _build(batch)(
                params, opt_state, state, batch, step_idx
            )
            return _apply_outer(step_idx, new_p), new_opt, new_state, loss

    else:

        def step(params, opt_state, batch, step_idx):
            _note_step_cadence()
            _maybe_snapshot(step_idx, (params, opt_state))
            new_p, new_opt, loss = _build(batch)(
                params, opt_state, batch, step_idx
            )
            return _apply_outer(step_idx, new_p), new_opt, loss

    def last_snapshot():
        """The most recent in-memory snapshot (``checkpoint.
        MemorySnapshot`` of the step's input tree), or None."""
        return snap_holder["snap"]

    def rollback():
        """(step_idx, input tree) restored from the last snapshot —
        registry snapshot re-installed, and the attached async plane's
        outer state restored alongside (the replay must see the
        snapshot-time anchor/EF/momentum, not the crash-time ones);
        None when no snapshot exists."""
        snap = snap_holder["snap"]
        if snap is None:
            return None
        from .. import checkpoint as ckpt

        if outer is not None:
            outer.restore_state(snap_holder.get("outer"))
        metrics.add("cgx.recovery.rollbacks")
        return snap.step, ckpt.restore_in_memory(snap)

    step.last_snapshot = last_snapshot
    step.rollback = rollback
    return step


def init_error_feedback(
    params,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
):
    """Zero-initialized per-device EF residuals for
    :func:`make_train_step` ``(error_feedback=True)``: each leaf is
    ``(ws, *param.shape)`` f32, sharded over the sync axes on the leading
    device dim so every device owns exactly its own residual row."""
    from jax.sharding import NamedSharding

    sync_axes = tuple(axes) if sp_axis is None else tuple(axes) + (sp_axis,)
    ws = int(np.prod([mesh.shape[a] for a in sync_axes]))
    z = jax.tree.map(
        lambda p: jnp.zeros((ws,) + p.shape, jnp.float32), params
    )
    return jax.device_put(z, NamedSharding(mesh, P(sync_axes)))


def replicate(tree, mesh):
    """Place a pytree fully-replicated on the mesh."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def shard_batch(
    batch,
    mesh,
    axes: Sequence[str] = (mesh_mod.DP_AXIS,),
    sp_axis: Optional[str] = None,
):
    """Shard batch leaves along their leading dimension over ``axes`` (and,
    with ``sp_axis``, the second — sequence — dimension of rank >= 2 leaves
    over that axis; rank-1 leaves have no sequence dim and replicate over
    sp).

    Multi-host: each process passes its *local* slice and JAX assembles the
    global array (``make_array_from_process_local_data``) — no host ever
    materializes the global batch.
    """
    from jax.sharding import NamedSharding

    axes = tuple(axes)
    ws = int(np.prod([mesh.shape[a] for a in axes]))
    # Multi-host: each process contributes only its local slice, so the
    # divisibility requirement is the per-process device count along the dp
    # axes, not the global extent.
    procs = jax.process_count()
    local_ws = ws // procs if procs > 1 and ws % procs == 0 else ws

    def place(x):
        if hasattr(x, "shape") and x.shape and x.shape[0] % local_ws:
            raise ValueError(
                f"local batch leading dim {x.shape[0]} not divisible by the "
                f"per-process data-parallel extent {local_ws} (global mesh "
                f"{ws}, {procs} processes; drop or pad the remainder "
                "batch; see data.iterate_batches(drop_remainder=True))"
            )
        # Rank-1 leaves (sample weights, per-sequence labels) have no
        # sequence dim — they shard over dp only and replicate over sp.
        sp = sp_axis if getattr(x, "ndim", 0) >= 2 else None
        sharding = NamedSharding(mesh, P(axes) if sp is None else P(axes, sp))
        if jax.process_count() > 1:
            return jax.make_array_from_process_local_data(
                sharding, np.asarray(x)
            )
        return jax.device_put(x, sharding)

    return jax.tree.map(place, batch)
