from .mesh import (
    CROSS_AXIS,
    DP_AXIS,
    INTRA_AXIS,
    flat_mesh,
    hierarchical_mesh,
    init_distributed,
    make_training_mesh,
)
from .allreduce import allreduce_flat, allreduce_tree, resolve_leaf_config
from .grad_sync import (
    compressed_allreduce_transform,
    gradient_sync,
    make_train_step,
    replicate,
    shard_batch,
)
from .moe import MoEMlp, aux_loss, moe_param_spec
from .pipeline import (
    merge_microbatches,
    spmd_pipeline,
    split_microbatches,
    stack_stage_params,
    unstack_stage_params,
)
from .ring_attention import make_sp_attention, ring_attention, ulysses_attention
from .reducers import (
    allgather_quantized,
    alltoall_allreduce,
    hierarchical_allreduce,
    quantized_allreduce,
    reduce_scatter_quantized,
    ring_allreduce,
    sra_allreduce,
)

__all__ = [
    "allreduce_flat",
    "allreduce_tree",
    "resolve_leaf_config",
    "compressed_allreduce_transform",
    "gradient_sync",
    "make_train_step",
    "replicate",
    "shard_batch",
    "CROSS_AXIS",
    "DP_AXIS",
    "INTRA_AXIS",
    "flat_mesh",
    "hierarchical_mesh",
    "init_distributed",
    "make_training_mesh",
    "allgather_quantized",
    "alltoall_allreduce",
    "hierarchical_allreduce",
    "quantized_allreduce",
    "reduce_scatter_quantized",
    "ring_allreduce",
    "sra_allreduce",
    "make_sp_attention",
    "ring_attention",
    "ulysses_attention",
    "MoEMlp",
    "aux_loss",
    "moe_param_spec",
    "spmd_pipeline",
    "stack_stage_params",
    "unstack_stage_params",
    "split_microbatches",
    "merge_microbatches",
]
