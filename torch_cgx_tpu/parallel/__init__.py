from .mesh import (
    CROSS_AXIS,
    DP_AXIS,
    INTRA_AXIS,
    flat_mesh,
    hierarchical_mesh,
    make_training_mesh,
)
from .allreduce import allreduce_flat, allreduce_tree, resolve_leaf_config
from .grad_sync import (
    compressed_allreduce_transform,
    gradient_sync,
    make_train_step,
    replicate,
    shard_batch,
)
from .ring_attention import make_sp_attention, ring_attention, ulysses_attention
from .reducers import (
    allgather_quantized,
    alltoall_allreduce,
    hierarchical_allreduce,
    quantized_allreduce,
    reduce_scatter_quantized,
    ring_allreduce,
    sra_allreduce,
)

__all__ = [
    "allreduce_flat",
    "allreduce_tree",
    "resolve_leaf_config",
    "compressed_allreduce_transform",
    "gradient_sync",
    "make_train_step",
    "replicate",
    "shard_batch",
    "CROSS_AXIS",
    "DP_AXIS",
    "INTRA_AXIS",
    "flat_mesh",
    "hierarchical_mesh",
    "make_training_mesh",
    "allgather_quantized",
    "alltoall_allreduce",
    "hierarchical_allreduce",
    "quantized_allreduce",
    "reduce_scatter_quantized",
    "ring_allreduce",
    "sra_allreduce",
    "make_sp_attention",
    "ring_attention",
    "ulysses_attention",
]
