"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

The reference has **no** attention or sequence-dimension code (SURVEY.md
§5.7) — long-context support is a from-scratch TPU-native design, built from
the same ``shard_map`` + collective primitives as the quantized reducers:

* :func:`ring_attention` — blockwise-causal flash attention with the K/V
  blocks rotating around the mesh axis via ``lax.ppermute`` (one hop per
  step, compute overlapping communication under XLA's async scheduling) and
  an online-softmax (running max / normalizer) accumulator, so the full
  S x S score matrix never materializes and sequence length scales linearly
  with the number of devices.
* :func:`ulysses_attention` — DeepSpeed-Ulysses-style: two ``all_to_all``
  reshards (sequence-sharded -> head-sharded and back) around a plain dense
  attention; cheaper than the ring when n_head % ws == 0 and the sequence
  fits per-device memory.

Both match :func:`~torch_cgx_tpu.models.attention.dense_attention` on the
gathered sequence to f32 tolerance and slot into
``MultiHeadAttention(attn_fn=...)`` via :func:`make_sp_attention`.

Inputs are (B, H, S_local, D) inside ``shard_map`` with the sequence
dimension sharded over ``axis_name``.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils import compat
from ..wire import dispatch as wire_dispatch
from ..wire.edges import EDGE_RING_KV

NEG_INF = np.float32(-1e30)


def _rotate_control(t, axis_name, perm):
    """Raw ``ppermute`` for control tensors (the bool padding mask riding
    beside its K/V block): index/mask payloads must never quantize, so
    this is the documented wire-dispatcher exemption (tools/lint.py
    allowlists exactly this function)."""
    return lax.ppermute(t, axis_name, perm)


def _block_scores(q, k, scale):
    return jnp.einsum(
        "bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32
    ) * scale


def _check_sp_mask(mask, q):
    if mask is None:
        return None
    if mask.ndim != 2 or mask.shape != (q.shape[0], q.shape[2]):
        raise NotImplementedError(
            "sequence-parallel attention supports only (B, S_local) "
            f"key-padding masks; got shape {mask.shape} for q {q.shape}"
        )
    return mask


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Blockwise ring attention over a sequence-sharded mesh axis.

    Each device owns one query block; K/V blocks hop around the ring
    (``ppermute``) while a flash-style online softmax folds each block's
    contribution into a running (max, normalizer, weighted-sum) accumulator.
    Returns the attention output for the local query block, same
    shape/dtype as ``q``.

    ``mask``: optional bool (B, S_local) key-padding mask (True = attend),
    the LOCAL slice of the global (B, S) mask — sharded exactly like the
    tokens. It rides the ring alongside its K/V block, so each step masks
    the arriving block's keys with the mask slice of the block's origin.
    """
    ws = compat.axis_size(axis_name)
    mask = _check_sp_mask(mask, q)
    if ws == 1:
        from ..models.attention import dense_attention

        return dense_attention(q, k, v, causal=causal, mask=mask)

    b, h, s_local, d = q.shape
    scale = np.float32(1.0 / np.sqrt(d))
    rank = lax.axis_index(axis_name)
    qf = q.astype(jnp.float32)

    q_pos = rank * s_local + jnp.arange(s_local)  # global query positions

    # Running accumulators (f32): row max m, normalizer l, weighted sum acc.
    m = jnp.full((b, h, s_local), NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, s_local), jnp.float32)
    acc = jnp.zeros((b, h, s_local, d), jnp.float32)

    # kv starts as own block and hops left each step, so at step s the local
    # kv block originated at rank (rank + s) mod ws. The padding-mask slice
    # travels with its block.
    shift_left = [(i, (i - 1) % ws) for i in range(ws)]
    kv = (k, v) if mask is None else (k, v, mask)

    for step in range(ws):
        k_blk, v_blk = kv[0], kv[1]
        src = (rank + step) % ws
        scores = _block_scores(qf, k_blk.astype(jnp.float32), scale)
        if causal:
            k_pos = src * s_local + jnp.arange(s_local)
            cmask = q_pos[:, None] >= k_pos[None, :]  # (s_local, s_local)
            scores = jnp.where(cmask[None, None], scores, NEG_INF)
        if mask is not None:
            # (B, s_local) key mask of the arriving block -> (B, 1, 1, s)
            scores = jnp.where(kv[2][:, None, None, :], scores, NEG_INF)
        blk_max = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m, blk_max)
        # guard: fully-masked block rows keep m_new finite via maximum(m, .)
        p = jnp.exp(scores - m_new[..., None])
        correction = jnp.exp(m - m_new)
        l = l * correction + jnp.sum(p, axis=-1)
        acc = acc * correction[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, v_blk.astype(jnp.float32)
        )
        m = m_new
        if step != ws - 1:
            # K/V hops ride the edge dispatcher (`ring_kv`): raw unless a
            # config resolves — per-hop quantization compounds over the
            # ring, so compression here is strictly opt-in via the edge
            # registry. The mask is a control tensor and always raw.
            k_next = wire_dispatch.wire_ppermute(
                kv[0], axis_name, shift_left,
                kind=EDGE_RING_KV, name="ring_attention.k",
            )
            v_next = wire_dispatch.wire_ppermute(
                kv[1], axis_name, shift_left,
                kind=EDGE_RING_KV, name="ring_attention.v",
            )
            kv = (
                (k_next, v_next)
                if mask is None
                else (
                    k_next,
                    v_next,
                    _rotate_control(kv[2], axis_name, shift_left),
                )
            )

    out = acc / jnp.maximum(l, np.float32(1e-30))[..., None]
    # Fully-masked query rows: the finite NEG_INF sentinel makes every score
    # equal, so p == 1 per key and the row emits the uniform average of v —
    # exactly dense_attention's uniform-softmax convention. Padded queries'
    # outputs are meaningless either way; they just stay finite and match.
    return out.astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str,
    causal: bool = True,
    hop_cc=None,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Ulysses sequence parallelism: all_to_all heads<->sequence reshard.

    (B, H, S/ws, D) -> all_to_all -> (B, H/ws, S, D) -> dense attention ->
    all_to_all back. Requires n_head divisible by the axis size.

    ``hop_cc``: quantize the reshard payloads on the wire
    (:func:`..parallel.reducers.quantized_all_to_all` — packed bit-planes
    + per-slice meta, STE backward through the inverse reshard).

    ``mask``: optional bool (B, S_local) key-padding mask (local slice,
    True = attend); after the reshard keys span the full sequence, so the
    slices are all_gathered into the (B, S) mask the dense kernel needs
    (ws*B*S bools on the wire — negligible next to the q/k/v reshards).
    """
    from ..models.attention import dense_attention

    ws = compat.axis_size(axis_name)
    mask = _check_sp_mask(mask, q)
    if ws == 1:
        return dense_attention(q, k, v, causal=causal, mask=mask)
    h = q.shape[1]
    if h % ws:
        raise ValueError(f"n_head={h} not divisible by sp axis size {ws}")
    if mask is not None:
        mask = lax.all_gather(mask, axis_name, axis=1, tiled=True)  # (B, S)

    def _a2a(t, s_ax, c_ax):
        # One surface for both modes: an explicit hop_cc bypasses the
        # registry (legacy behavior, byte-identical); otherwise the
        # reshard resolves the `ring_kv` edge — raw unless configured.
        return wire_dispatch.wire_all_to_all(
            t, axis_name, split_axis=s_ax, concat_axis=c_ax,
            kind=EDGE_RING_KV, name="ulysses", cc=hop_cc,
        )

    def to_heads(t):  # split heads over axis, gather sequence
        return _a2a(t, 1, 2)

    def to_seq(t):  # inverse
        return _a2a(t, 2, 1)

    qh, kh, vh = to_heads(q), to_heads(k), to_heads(v)
    out = dense_attention(qh, kh, vh, causal=causal, mask=mask)
    return to_seq(out)


def make_sp_attention(axis_name: str, impl: str = "ring", hop_cc=None):
    """Build an ``attn_fn`` for ``MultiHeadAttention`` running under
    ``shard_map`` with the sequence dimension sharded over ``axis_name``.

    ``impl``: "ring" (arbitrary axis size, O(S_local^2) memory) or "ulysses"
    (n_head % ws == 0, lowest traffic on ICI). ``hop_cc``: quantize the
    Ulysses reshard payloads (ulysses only — the ring's loop-carried KV
    hops would compound per-hop error and are not compressed).

    Both impls accept a bool (B, S_local) key-padding mask (the local
    slice, True = attend): the ring rotates it with its K/V block; Ulysses
    all_gathers the slices for the dense kernel.
    """
    if impl == "ring":
        if hop_cc is not None:
            raise ValueError("hop_cc is supported for impl='ulysses' only")
        fn = ring_attention
    elif impl == "ulysses":
        fn = ulysses_attention
    else:
        raise ValueError(f"unknown sequence-parallel impl {impl!r}")

    @functools.wraps(fn)
    def attn_fn(q, k, v, *, causal: bool = True, mask=None):
        kw = {"hop_cc": hop_cc} if impl == "ulysses" else {}
        return fn(q, k, v, axis_name=axis_name, causal=causal, mask=mask, **kw)

    return attn_fn
