"""Compiled collective schedules: chunked quantize->wire->epilogue
pipelining for the compressed allreduce planes.

The reference hides gradient communication behind backward compute via
Horovod-style fusion + DDP hook ordering (PAPER.md §0); our port still ran
each fused bucket as ONE monolithic quantize -> exchange -> epilogue
sequence — zero overlap, confirmed by the ``overlap_frac`` column of
``cgx_trace`` attribution. GC3 (arxiv 2201.11840) treats collective
schedules as compiled, cacheable programs; "Fused Computation-Collective
Operations" (arxiv 2305.06942) shows the remaining step time lives in
chunk-granular fusion of compute with in-flight collectives. This module
is the schedule *compiler*: from a fusion slice's (n, ws, config) it
derives a chunked pipeline —

    chunk k+1 quantizes  WHILE  chunk k is on the wire
                         WHILE  chunk k-1 runs the fused epilogue

— cached in a bounded LRU keyed like ``allreduce``'s layout cache plus
(route, chunking, chip), and executed on both planes:

* **staged XLA plane** (:func:`pipelined_quantized_allreduce`, routed via
  ``parallel/xla_allreduce.py``): the pipelined loop is compiled INTO the
  single staged program — per-chunk ``lax.all_to_all``/``ppermute``
  exchanges interleaved with the PR 4 fused epilogue kernel in software-
  pipeline emission order (chunk k+1's quantize+exchange is staged before
  chunk k's epilogue+allgather), giving XLA's latency-hiding scheduler
  independent collective/compute chains to overlap. Still zero host
  callbacks — this module is listed in ``xla_allreduce.STAGED_PURE`` and
  jaxpr-guarded by tests/test_schedule.py.
* **bridge plane** (``torch_backend/backend.py`` ``_qreduce_sra_pipelined``):
  a double-buffered in-flight window — an encoder thread runs chunk
  encode+put up to ``_BRIDGE_WINDOW`` chunks ahead of the worker thread's
  take/fold/requantize/decode, replacing the strict phase barriers of the
  monolithic path. The bridge keeps a dependency-light duplicate of
  :func:`chunk_table` (it must not import the parallel package);
  tests/test_schedule.py cross-checks the two.

**Bit-equality contract**: chunks are COLUMN blocks of the SRA wire
layout, not contiguous spans of the fused buffer. The monolithic SRA
views the slice as a (ws, chunk) matrix — row r is rank r's owned
span — and the own-chunk-raw rule keys off the row index; a contiguous
split would reassign ownership per element and change every decode sum.
A column block keeps row r owned by rank r in every chunk, and block
widths are rounded to ``lcm(bucket_size, LANE_GROUP)`` so the
quantization bucket grid WITHIN each row is unchanged (buckets restart
per quantize call at multiples of the width — an aligned width puts
every boundary back on the monolithic grid). With the accumulate
association pinned to the dispatcher's ``ordered_rowsum`` fold in both
forms, a deterministic (non-stochastic) pipelined SRA is bit-equal to
the monolithic SRA on ANY payload (``bench.py --schedule`` asserts this
before timing; tests/test_schedule.py pins it on random data).
Stochastic rounding draws per-chunk streams (keys fold in the chunk
index), so stochastic bytes differ between schedules — exactly as they
differ between any two fusion layouts. Only the SRA transport is
pipelined: Ring is already a hop pipeline by construction, and
all-to-all is the debug path — both stay monolithic.

``CGX_SCHEDULE`` unset ("auto") pipelines only on a real TPU backend, so
every CPU/CI path stays bit-identical: staged programs, store keys and
wire bytes unchanged (the grad_sync bit-identity suite pins it).
"""

from __future__ import annotations

import dataclasses
import math
from collections import OrderedDict
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import config as cfg_mod
from ..config import CompressionConfig
from ..observability import timeline
from ..ops import codec
from ..utils.logging import metrics
from . import reducers

# Double-buffered in-flight window of the bridge pipeline: how many chunks
# the encoder thread may run ahead of the worker thread's take/epilogue.
# 2 = classic double buffering — chunk k+1 encodes while chunk k is in
# flight; deeper windows only grow arena residency without adding overlap
# (there is one encoder thread and one epilogue thread to keep busy).
_BRIDGE_WINDOW = 2


def chunk_alignment(bucket_size: int) -> int:
    """Column-width alignment of schedule chunk boundaries:
    ``lcm(bucket_size, LANE_GROUP)``. Quantization buckets restart per
    quantize call, so a column block starting at a multiple of the
    bucket size (within its row) keeps every bucket boundary on the
    monolithic layout's grid — the bit-equality contract (module
    docstring)."""
    return math.lcm(max(1, bucket_size), codec.LANE_GROUP)


def chunk_table(
    width: int, chunks: int, bucket_size: int
) -> Tuple[Tuple[int, int], ...]:
    """(column offset, column width) chunk plan over one rank-chunk of
    ``width`` elements (the per-rank row of the SRA wire layout) at a
    target pipeline depth of ``chunks``.

    Every boundary is a multiple of :func:`chunk_alignment`; the last
    chunk absorbs the remainder. A row too narrow for the requested
    depth degrades to fewer chunks — down to ``((0, width),)``, the
    monolithic plan. Pure integer arithmetic: the bridge keeps a
    dependency-light duplicate (``backend._sched_chunk_table``) pinned to
    this by test."""
    if width <= 0:
        return ((0, max(width, 0)),) if width else ()
    align = chunk_alignment(bucket_size)
    chunks = max(1, int(chunks))
    # Aligned units available; each chunk needs at least one whole unit.
    units = width // align
    depth = min(chunks, units) if units else 1
    if depth <= 1:
        return ((0, width),)
    per = (units // depth) * align
    out = []
    off = 0
    for _ in range(depth - 1):
        out.append((off, per))
        off += per
    out.append((off, width - off))
    return tuple(out)


# ---------------------------------------------------------------------------
# The schedule LRU (GC3's compiled-schedule discipline, sibling of the
# layout LRU in allreduce.py and the program LRU in xla_allreduce.py).
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class CompiledSchedule:
    """One fusion slice's compiled pipeline plan: ``table`` is the
    column-block plan over the slice's per-rank wire row of ``chunk``
    elements (``reducers.chunk_layout(n, ws)[0]``)."""

    table: Tuple[Tuple[int, int], ...]  # (col offset, col width) per chunk
    n: int
    ws: int
    chunk: int  # per-rank row width of the (ws, chunk) wire layout
    cc: CompressionConfig

    @property
    def depth(self) -> int:
        return len(self.table)


_SCHED_CACHE: "OrderedDict" = OrderedDict()
_SCHED_CACHE_MAX = 128
_SCHED_STATS = {"hits": 0, "misses": 0}
# Cached "no pipeline for this key" marker — a stored bare None would be
# indistinguishable from a cache miss and re-derive (and re-count a miss)
# on every call.
_NO_SCHEDULE = object()


def schedule_cache_stats() -> Dict[str, int]:
    return dict(_SCHED_STATS)


def schedule_cache_clear() -> None:
    _SCHED_CACHE.clear()
    _SCHED_STATS.update(hits=0, misses=0)


def invalidate_schedule_cache(reason: str = "reconfigure") -> None:
    """Invalidation entry point — called alongside
    ``allreduce.invalidate_layout_cache`` (a PR 5 recovery reconfigure
    re-derives chunk layouts at the shrunk world size; serving a stale
    chunk table there would wedge the bridge's in-flight window against
    peers running the fresh plan)."""
    schedule_cache_clear()
    metrics.add("cgx.sched.cache_invalidations")
    from ..utils.logging import get_logger

    get_logger().info("schedule cache invalidated (%s)", reason)


def _chip_fingerprint() -> str:
    """The (backend, chip) component of the schedule key: a plan derived
    for one chip's crossover must not serve another's."""
    try:
        dev = jax.devices()[0]
        return f"{jax.default_backend()}/{getattr(dev, 'device_kind', '?')}"
    except RuntimeError:
        return "none"


def cache_key_component() -> Tuple:
    """The schedule component of trace-cache keys (``make_train_step``):
    everything that changes what the pipelined emission stages — resolved
    mode, target depth — so a ``CGX_SCHEDULE`` flip between calls forces
    a retrace, never a stale-schedule hit."""
    return (cfg_mod.schedule_mode(), cfg_mod.sched_chunks())


def _schedule_key(n, ws, dtype, cc, route, chunks) -> Tuple:
    return (
        int(n),
        int(ws),
        str(dtype),
        cc,
        route,
        int(chunks),
        _chip_fingerprint(),
        cfg_mod.registry_version(),
    )


def _engaged(route_staged: bool) -> bool:
    """Whether the schedule compiler may pipeline on the JAX plane under
    the current mode/backend: "on" anywhere, "auto" only on a real TPU
    backend (inert on every CPU/CI path — same discipline as
    ``CGX_XLA_ALLREDUCE=auto``), "off" never. ``route_staged`` is the
    topology router's verdict for the slice — the pipelined program is
    the staged program's sibling and rides the same routing."""
    del route_staged  # pipelining is mode-gated; routing picked the plane
    mode = cfg_mod.schedule_mode()
    if mode == "off":
        return False
    if mode == "on":
        return True
    try:
        return jax.default_backend() == "tpu"
    except RuntimeError:
        return False


def engaged() -> bool:
    """Public mode probe for callers that only need the yes/no (e.g. the
    reverse-order group emission in ``allreduce_tree``): True when the
    current mode/backend would let the compiler pipeline at all."""
    return _engaged(True)


def compiled_schedule(
    n: int,
    ws: int,
    cc: CompressionConfig,
    *,
    reduction: str = cfg_mod.REDUCTION_SRA,
    dtype="float32",
    route: str = "staged",
    route_staged: bool = True,
    chunks: Optional[int] = None,
) -> Optional[CompiledSchedule]:
    """The compiled pipeline plan for one fusion slice, or ``None`` when
    pipelining does not engage (mode off/auto-on-CPU, compression off,
    ws == 1, a non-SRA reduction — Ring already pipelines hop-wise by
    construction, all-to-all is the debug path — or a payload too small
    to split). Plans come from the bounded LRU
    (``cgx.sched.cache_hits``/``cache_misses``).

    ``chunks``: an explicit depth decision from the step planner
    (``parallel/planner.py``). When given it REPLACES both the
    ``CGX_SCHED_CHUNKS`` knob and the mode gate — the planner's own
    engagement gate already decided this slice pipelines (the planner is
    the schedule compiler's front end, not a bypass: depth 1 still
    degrades to None/monolithic and every other gate above holds)."""
    if ws <= 1 or not cc.enabled or cfg_mod.dummy_compression():
        return None
    if reduction != cfg_mod.REDUCTION_SRA:
        return None
    if chunks is None:
        if not _engaged(route_staged):
            return None
        chunks = cfg_mod.sched_chunks()
    key = _schedule_key(n, ws, dtype, cc, route, chunks)
    hit = _SCHED_CACHE.get(key)
    if hit is not None:
        _SCHED_CACHE.move_to_end(key)
        _SCHED_STATS["hits"] += 1
        metrics.add("cgx.sched.cache_hits")
        return None if hit is _NO_SCHEDULE else hit
    _SCHED_STATS["misses"] += 1
    metrics.add("cgx.sched.cache_misses")
    chunk = reducers.chunk_layout(n, ws)[0]
    table = chunk_table(chunk, chunks, cc.bucket_size)
    sched: Optional[CompiledSchedule] = None
    if len(table) >= 2:
        sched = CompiledSchedule(table=table, n=n, ws=ws, chunk=chunk, cc=cc)
        metrics.add("cgx.sched.compiled")
    # Cache the negative result too (single-chunk payloads would re-probe
    # every call otherwise) — as the _NO_SCHEDULE sentinel, since a bare
    # None stored in the cache is indistinguishable from a miss.
    _SCHED_CACHE[key] = sched if sched is not None else _NO_SCHEDULE
    if len(_SCHED_CACHE) > _SCHED_CACHE_MAX:
        _SCHED_CACHE.popitem(last=False)
    return sched


# ---------------------------------------------------------------------------
# Staged-plane executor: the software-pipelined loop, compiled into the
# single XLA program. Staged-pure — no host callbacks, no blocking device
# syncs (tools/lint.py enforces both; the jaxpr guard re-checks at trace
# time).
# ---------------------------------------------------------------------------


def _note_pipeline(sched: CompiledSchedule, reduction: str) -> None:
    """Trace-time accounting (once per compiled program — runtime hooks
    would need a host callback the staged program must not contain)."""
    metrics.add("cgx.sched.pipelined_slices")
    metrics.add("cgx.sched.chunks_staged", float(sched.depth))
    timeline.instant(
        "sched_pipeline",
        cat=timeline.CAT_COLLECTIVE,
        elems=int(sched.n),
        ws=int(sched.ws),
        chunks=int(sched.depth),
        bits=int(sched.cc.bits),
        reduction=reduction,
    )


def pipelined_quantized_allreduce(
    x: jax.Array,
    axis_name: str,
    ws: int,
    cc: CompressionConfig,
    reduction: str,
    key: Optional[jax.Array],
    sched: CompiledSchedule,
    *,
    with_wire: bool = False,
    pre=None,
):
    """Software-pipelined SRA allreduce of one fusion slice (inside
    shard_map): the slice's (ws, chunk) wire layout is split into the
    schedule's column blocks — rank r keeps row r in every block, so the
    own-chunk-raw rule and the bucket grid match the monolithic layout
    exactly (bit-equality contract, module docstring) — and each block
    runs the same quantize -> ``lax.all_to_all`` -> fused epilogue ->
    ``lax.all_gather`` -> decode composition as
    ``reducers.sra_allreduce``, EMITTED in pipeline order: block k+1's
    quantize + exchange is staged before block k's epilogue + allgather +
    decode, so the XLA latency-hiding scheduler sees independent
    collective/compute chains it can overlap (block k+1 on the wire
    while block k's epilogue kernel runs).

    ``with_wire=True`` also returns this device's wire decode (the EF
    residual base — same quantize-once payload sharing as
    ``sra_allreduce_with_wire``), assembled from the per-block stage-1
    payloads.

    ``pre``: a producer-staged payload (``ops.fused_producer.Produced``)
    whose ``q_blocks`` were quantized per column block against THIS
    schedule's table (the consumer verifies the tables match before
    routing here): each block's quantize is skipped and the raw own
    chunk comes from ``pre.raw_row`` slices — the f32 buffer is never
    read."""
    if reduction != cfg_mod.REDUCTION_SRA:
        raise ValueError(
            f"pipelined schedules cover the SRA transport only, got "
            f"{reduction!r} (compiled_schedule should have returned None)"
        )
    if pre is not None and (
        pre.q_blocks is None or len(pre.q_blocks) != sched.depth
    ):
        raise ValueError(
            "producer-staged payload's block plan does not match the "
            "compiled schedule (consumer-side table check missed?)"
        )
    _note_pipeline(sched, reduction)
    depth = sched.depth
    n = x.shape[0]
    xs = (
        reducers._pad_rows(x, ws, sched.chunk) if pre is None else None
    )  # (ws, chunk), monolithic
    own_idx = lax.axis_index(axis_name)
    own = (jnp.arange(ws) == own_idx)[:, None]
    exchanged: list = [None] * depth
    outs: list = [None] * depth
    rts: list = [None] * depth

    def _block_key(c: int):
        # Per-block stochastic stream (the fusion-slice convention):
        # blocks of one slice must not share fold sequences.
        return jax.random.fold_in(key, c) if key is not None else None

    def _raw_c(c: int):
        """Block c's slice of the producer raw own row."""
        off, w = sched.table[c]
        return lax.slice(pre.raw_row, (off,), (off + w,))

    def start(c: int) -> None:
        """Stage 1 of block c: quantize its columns + put on the wire."""
        off, w = sched.table[c]
        kc = _block_key(c)
        if pre is not None:
            q = pre.q_blocks[c]
            xs_c = None
        else:
            xs_c = lax.slice(xs, (0, off), (ws, off + w))
            q = reducers._quantize_rows(
                xs_c, cc, reducers._phase_key(kc, 1, axis_name)
            )
        q_recv = jax.tree.map(
            lambda a: lax.all_to_all(a, axis_name, 0, 0), q
        )
        exchanged[c] = (kc, q, q_recv, xs_c)

    def finish(c: int) -> None:
        """Stages 2+3 of block c: fused epilogue + allgather + decode."""
        kc, q, q_recv, xs_c = exchanged[c]
        q_own = reducers._sra_epilogue_q(
            q_recv, xs_c, own_idx, axis_name, cc, kc, x.dtype,
            raw_row=_raw_c(c) if pre is not None else None,
        )
        gathered = reducers._gather_rows(q_own, axis_name)
        outs[c] = reducers._dequantize_rows(gathered)  # (ws, w)
        if with_wire:
            rt_rows = reducers._dequantize_rows(q)
            raw_b = xs_c if pre is None else _raw_c(c)[None]
            rts[c] = jnp.where(own, raw_b.astype(rt_rows.dtype), rt_rows)
        exchanged[c] = None  # release the traced intermediates

    # The software pipeline: fill one block ahead, then steady-state.
    start(0)
    for c in range(depth):
        if c + 1 < depth:
            start(c + 1)
        finish(c)
    out = jnp.concatenate(outs, axis=1).reshape(-1)[:n].astype(x.dtype)
    if not with_wire:
        return out
    rt = (
        jnp.concatenate(rts, axis=1).reshape(-1)[:n].astype(x.dtype)
    )
    return out, rt


def dispatch_order(n_groups: int) -> Tuple[int, ...]:
    """Emission order of fused gradient groups in ``allreduce_tree`` when
    the schedule is engaged: REVERSED — backward produces the LAST
    layers' gradients first, so emitting tail groups' collectives first
    lets XLA start their exchanges while earlier layers' gradients are
    still being computed (the reference's DDP-hook bucket ordering,
    PAPER.md §0, re-expressed as emission order for the latency-hiding
    scheduler). Values are order-invariant — each group's stochastic key
    folds its ORIGINAL index — so this changes schedule, never bytes."""
    return tuple(reversed(range(n_groups)))
