"""Adaptive per-layer bit allocation for gradient compression.

The reference carries a per-layer config registry but leaves choosing the
bits to the user (SURVEY.md §5.6); its research lineage (L-GreCo) picks
them automatically by solving an error/budget trade-off. TPU-native take:

* :func:`measure_layer_stats` — per-layer bucket-range statistics from a
  gradient pytree (one host pass, run every K steps).
* :func:`solve_bit_allocation` — minimize the summed max-min quantization
  error model  ``E_l(b) = numel_l * mean_range_l^2 / (12 (2^b-1)^2)``
  subject to an average-bits budget, by greedy marginal-gain ascent
  (optimal here: the per-layer error is convex and decreasing in integer
  bits, so marginal gains are monotone).
* :func:`apply_bit_allocation` — write the result into the name-pattern
  registry consumed by :func:`..parallel.allreduce.resolve_leaf_config`.

Changing a layer's bits changes compiled shapes, so re-solving forces a
retrace of the train step (~seconds on TPU): re-solve every few hundred
steps, not every step. Layers the eligibility rules exclude (dim <= 1,
tiny, non-float) are skipped entirely — their wire is exact.
"""

from __future__ import annotations

import dataclasses
import heapq
import re
from typing import Dict, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config as cfg_mod
from ..utils.tree import path_str
from .allreduce import is_compressible, resolve_leaf_config


@dataclasses.dataclass(frozen=True)
class LayerStat:
    """Per-layer quantization-error ingredients: element count, the mean
    squared per-bucket range of the (flattened) gradient, and the resolved
    config the measurement used (bits are overwritten by the solver; every
    other field — bucket size, stochastic, skip mode — is preserved when
    the allocation is applied)."""

    numel: int
    mean_sq_range: float
    cc: Optional["cfg_mod.CompressionConfig"] = None


def measure_layer_stats(
    grads,
    *,
    bucket_size: Optional[int] = None,
    compress_small: bool = False,
) -> Dict[str, LayerStat]:
    """One host pass over a gradient pytree -> per-layer ``LayerStat``.

    Eligibility is structural (float, rank > 1 unless ``compress_small``,
    >= the minimal size) — NOT gated on compression being enabled already:
    turning compression on IS what the allocation does, so it must work
    from a bits=32 default environment. ``bucket_size`` defaults to each
    layer's resolved config.
    """
    with_path, _ = jax.tree_util.tree_flatten_with_path(grads)
    out: Dict[str, LayerStat] = {}
    for p, leaf in with_path:
        path = path_str(p)
        if not is_compressible(leaf, compress_small=compress_small):
            continue
        cc = resolve_leaf_config(path, leaf, compress_small=compress_small)
        b = bucket_size or cc.bucket_size
        x = np.asarray(leaf, np.float64).reshape(-1)
        n = x.size
        nb = -(-n // b)
        pad = nb * b - n
        if pad:
            x = np.concatenate([x, np.repeat(x[-1], pad)])
        rows = x.reshape(nb, b)
        rng = rows.max(axis=1) - rows.min(axis=1)
        out[path] = LayerStat(
            numel=n,
            mean_sq_range=float(np.mean(rng**2)),
            cc=dataclasses.replace(cc, bucket_size=b),
        )
    return out


def _err(stat: LayerStat, bits: int) -> float:
    """Expected max-min quantization MSE at ``bits`` (uniform-error model:
    unit^2/12 per element, unit = range/(2^bits - 1))."""
    return stat.numel * stat.mean_sq_range / (12.0 * (2**bits - 1) ** 2)


def solve_bit_allocation(
    stats: Mapping[str, LayerStat],
    avg_bits: float,
    *,
    bits_range: Tuple[int, int] = (2, 8),
) -> Dict[str, int]:
    """Per-layer bits minimizing summed expected quantization error under
    ``sum(numel * bits) <= avg_bits * sum(numel)``.

    Greedy marginal-gain ascent from the floor: repeatedly give one more
    bit to the layer with the best error reduction per payload bit. Exact
    when layers have equal size (marginal gains shrink monotonically);
    with mixed sizes it is the standard knapsack-greedy approximation.
    """
    lo, hi = bits_range
    if not 1 <= lo <= hi <= 8:
        raise ValueError(f"bits_range must satisfy 1 <= lo <= hi <= 8, got {bits_range}")
    if avg_bits < lo:
        raise ValueError(
            f"avg_bits={avg_bits} is below the bits_range floor {lo}: even "
            "the minimum allocation would exceed the budget"
        )
    total = sum(s.numel for s in stats.values())
    if not total:
        return {}
    budget = avg_bits * total
    alloc = {path: lo for path in stats}
    spent = lo * total
    # max-heap on marginal gain per bit-element
    heap = []
    for path, s in stats.items():
        if lo < hi:
            gain = (_err(s, lo) - _err(s, lo + 1)) / s.numel
            heapq.heappush(heap, (-gain, path))
    while heap:
        neg_gain, path = heapq.heappop(heap)
        s = stats[path]
        if spent + s.numel > budget:
            continue  # this layer no longer fits; others may be smaller
        alloc[path] += 1
        spent += s.numel
        b = alloc[path]
        if b < hi:
            gain = (_err(s, b) - _err(s, b + 1)) / s.numel
            heapq.heappush(heap, (-gain, path))
    return alloc


def apply_bit_allocation(
    alloc: Mapping[str, int],
    stats: Mapping[str, LayerStat],
    *,
    bucket_size: Optional[int] = None,
) -> None:
    """Write an allocation into the name-pattern registry (exact-path
    patterns), so the next traced allreduce picks it up — the registry
    version bump forces make_train_step's cached trace to rebuild. Each
    layer keeps the config it was MEASURED with (bucket size, stochastic,
    skip mode) and only the bits change; pre-existing pattern settings
    therefore survive instead of being reset to env defaults."""
    for path, bits in alloc.items():
        base = stats[path].cc or cfg_mod.default_compression_config()
        cfg_mod.set_layer_pattern_config(
            "^" + re.escape(path) + "$",
            dataclasses.replace(
                base,
                bits=int(bits),
                bucket_size=int(bucket_size or base.bucket_size),
            ),
        )


def adapt_bits(
    grads,
    avg_bits: float,
    *,
    bits_range: Tuple[int, int] = (2, 8),
    bucket_size: Optional[int] = None,
    compress_small: bool = False,
) -> Dict[str, int]:
    """Measure -> solve -> apply in one call; returns the allocation.

    Call OUTSIDE jit every K steps; the registry-version bump makes
    make_train_step's cached trace rebuild, so the new bits take effect on
    the very next step (one retrace).

    ``make_train_step``'s step function does not expose per-step gradients,
    so obtain the measurement tree explicitly — a one-off
    ``jax.grad(loss_fn)(params, batch)`` on the current batch (one extra
    backward every K steps), or any recent gradient snapshot; the bucket
    RANGE statistics drift slowly, so staleness is benign:

        if step % 500 == 0:
            g = jax.device_get(jax.grad(loss_fn)(params_host, batch_host))
            cgx.adapt_bits(g, avg_bits=4)
    """
    stats = measure_layer_stats(
        grads, bucket_size=bucket_size, compress_small=compress_small
    )
    alloc = solve_bit_allocation(stats, avg_bits, bits_range=bits_range)
    apply_bit_allocation(alloc, stats, bucket_size=bucket_size)
    return alloc
