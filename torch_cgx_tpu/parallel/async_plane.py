"""Asynchronous cross-slice plane: hierarchical local-SGD over DCN.

The topology router (PR 7) split intra-slice ICI from cross-slice DCN,
but both levels still ran synchronously — one slow DCN edge stalled
every chip in every slice, every step (ROADMAP item 4's named soft
spot). This module decouples the slow tier ("The Big Send-off", arxiv
2504.18658: hierarchical collectives win exactly when the slow tier
leaves the critical path):

* **inner loop** — each slice keeps its existing staged synchronous
  allreduce (planned, pipelined, producer-fused: nothing in the staged
  program changes; under ``CGX_ASYNC=on`` the bridge's two-level path
  simply skips its cross stage);
* **outer loop** — every ``CGX_ASYNC_H`` inner steps a slice computes
  its parameter delta against the outer **anchor**, compresses it
  through the wire-plane codec path (edge kind ``xslice_delta`` in
  ``wire/edges.py``, error feedback riding the per-slice residual), and
  hands the wire bytes to a dedicated sender thread
  (``torch_backend/async_bridge.py``) — the train step NEVER blocks on
  DCN. Arrived peer deltas fold into the anchor at round boundaries
  through a configurable outer optimizer (SGD averaging, or Nesterov
  momentum — the DiLoCo outer step), in deterministic (peer, round)
  order so every slice that saw the same rounds holds bit-identical
  anchors;
* **bounded staleness** — a peer slice more than ``CGX_ASYNC_MAX_LAG``
  outer rounds behind raises ``async_lag`` HealthEvents (the PR 6
  plane; they feed the PR 5 eviction vote as suspect hints) and then an
  :class:`~..robustness.errors.AsyncStalenessError` — a
  ``BridgeTimeoutError`` subclass, so the recovery supervisor's ladder
  runs exactly as for an expired bridge wait;
* **deterministic recovery** — the outer state (anchor, EF residual,
  momentum, round, per-peer bookkeeping) is a plain numpy pytree that
  rides the PR 5 in-memory snapshots; an outer round is tagged with the
  group generation, and replay restores inner params and outer
  EF/momentum state bit-identically (the chaos soak in
  tests/test_async_plane.py pins a faulted run's post-rollback replay
  against a fault-free survivor-only run);
* **planner-aware** — under ``CGX_ASYNC=auto`` the PR 12 planner's
  sync-vs-async cost curves (``planner.async_route``, calibrated from
  live ``cgx.async.*`` telemetry) decide engagement and pick H per
  topology instead of a static knob (GC3, arxiv 2201.11840: the
  schedule compiler owns the decoupling decision).

With ``CGX_ASYNC`` unset the module is inert: no state allocates, no
byte ships, and staged programs / store keys / wire bytes are
bit-identical to the pre-async code (pinned in
tests/test_async_plane.py).
"""

from __future__ import annotations

import copy
import dataclasses
import threading
import weakref
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import config as cfg_mod
from ..config import CompressionConfig
from ..observability import flightrec
from ..observability import health as health_mod
from ..ops import codec_host
from ..robustness.errors import AsyncStalenessError
from ..utils.logging import get_logger, metrics
from ..wire import edges as wire_edges

log = get_logger()

# Live planes, reset by supervisor.invalidate_trace_caches: per-peer round
# bookkeeping and pending deltas describe the dead generation's
# membership (the controller-cadence reset class).
# cgx-analysis: allow(orphan-memo) — weak liveness set: dead planes self-evict; reset_planes() resets every member's state
_PLANES: "weakref.WeakSet" = weakref.WeakSet()
_PLANES_LOCK = threading.Lock()


def reset_planes(reason: str = "reconfigure") -> None:
    """Mark every live plane's membership stale (post-recovery hook): the
    next outer boundary re-derives slice leaders from the survivor host
    map at the bumped generation instead of folding rounds from (or
    naming as suspects) evicted peers."""
    with _PLANES_LOCK:
        planes = list(_PLANES)
    for p in planes:
        p.mark_membership_stale()
    if planes:
        metrics.add("cgx.async.membership_resets")
        log.info("async plane membership marked stale (%s)", reason)


def note_membership(generation: int) -> None:
    """Elastic membership hook (``robustness/elastic.py``): mark every
    live plane's membership stale at the bumped ``generation`` — a grow
    changes slice leadership exactly like an eviction does, and a joiner
    whose received anchor rides the snapshot pages must fold its first
    outer round against the NEW membership, never the donor's old one."""
    reset_planes(f"membership g{generation}")


@dataclasses.dataclass(frozen=True)
class Membership:
    """One slice's view of the cross-slice group: which slice it is, how
    many slices exist, and the GROUP-LOCAL + GLOBAL ranks of every
    slice's leader (by slice index — the eviction-vote attribution and
    the regression-pinned re-derivation surface)."""

    slice_idx: int
    n_slices: int
    leaders: Tuple[int, ...]  # group-local leader rank per slice
    global_ranks: Tuple[int, ...]  # global leader rank per slice
    generation: int = 0

    @classmethod
    def from_hosts(
        cls,
        hosts: Sequence[str],
        my_rank: int,
        global_ranks: Optional[Sequence[int]] = None,
        generation: int = 0,
    ) -> "Membership":
        """Derive from the CURRENT per-rank host map (after an eviction:
        the survivor-filtered map at the bumped generation) — the
        :func:`topology.slice_leaders` walk, so an evicted rank can never
        be named leader."""
        from . import topology as topo

        leaders = topo.slice_leaders(hosts)
        globals_ = (
            list(global_ranks) if global_ranks is not None
            else list(range(len(hosts)))
        )
        # slice index = position of my host's leader (leaders are in
        # first-seen host order, the slice-id order by construction —
        # the same derivation backend.async_slice_info uses)
        my_slice = [hosts[r] for r in leaders].index(hosts[my_rank])
        return cls(
            slice_idx=my_slice,
            n_slices=len(leaders),
            leaders=tuple(leaders),
            global_ranks=tuple(globals_[r] for r in leaders),
            generation=int(generation),
        )


# ---------------------------------------------------------------------------
# Outer optimizer (SGD / Nesterov momentum — the DiLoCo pair).
# ---------------------------------------------------------------------------


def outer_update(
    agg: np.ndarray,
    momentum: np.ndarray,
    *,
    kind: str,
    lr: float,
    mu: float,
) -> Tuple[np.ndarray, np.ndarray]:
    """(anchor update, new momentum) for one aggregated outer delta.

    "sgd": ``lr * agg`` (lr 1.0 = exact local-SGD delta averaging).
    "nesterov": ``m' = mu*m + agg``; update ``lr * (agg + mu*m')`` — the
    Nesterov look-ahead form DiLoCo uses for its outer optimizer.
    Pure f32 numpy on both paths, so replay is bit-exact."""
    agg = agg.astype(np.float32, copy=False)
    if kind == "sgd":
        return (np.float32(lr) * agg), momentum
    m_new = np.float32(mu) * momentum + agg
    return np.float32(lr) * (agg + np.float32(mu) * m_new), m_new


def init_outer_state(
    flat_params: np.ndarray, membership: Membership
) -> Dict[str, Any]:
    """Fresh outer state for one slice: the anchor starts at the current
    params (delta 0), EF and momentum at zero, round at 0. A plain
    dict-of-numpy pytree so ``checkpoint.snapshot_in_memory`` host-copies
    it unchanged (rung-4 substrate)."""
    flat = np.asarray(flat_params, np.float32).reshape(-1)
    return {
        "anchor": flat.copy(),
        "ef": np.zeros_like(flat),
        "momentum": np.zeros_like(flat),
        "round": 0,
        "generation": int(membership.generation),
        # highest peer round folded so far, per peer slice (-1 = none)
        "applied": {
            int(p): -1
            for p in range(membership.n_slices)
            if p != membership.slice_idx
        },
        # staleness-clock floor: lag is measured against
        # max(applied, lag_floor - 1), so a post-recovery stream starts
        # its clock at the re-derivation round
        "lag_floor": 0,
        # arrived-but-unapplied decoded deltas: peer -> [(round, vec)]
        "pending": {},
    }


# ---------------------------------------------------------------------------
# The plane.
# ---------------------------------------------------------------------------


class AsyncPlane:
    """One slice's end of the asynchronous cross-slice exchange.

    ``transport`` — post/poll endpoint (``AsyncBridgeSender`` on the
    bridge, ``LocalAsyncTransport.bind(...)`` in tests).
    ``membership_fn`` — returns the CURRENT :class:`Membership`;
    re-invoked after a recovery reconfiguration (``reset_planes``) so
    slice leaders re-derive from the survivor list at the bumped
    generation (the PR 13 regression fix).
    ``name`` — the edge name this plane's deltas resolve under in the
    edge registry (``resolve_edge("xslice_delta", name)``).
    ``h`` — inner steps per outer round; None resolves ``CGX_ASYNC_H``,
    then (under ``auto`` with the planner engaged) the planner's cost
    curves, then ``DEFAULT_ASYNC_H``.

    Thread model: every method runs on the training-loop thread; only
    the transport's sender thread touches the store. Nothing here blocks
    — ``maybe_outer_step`` is an enqueue plus a poll of already-arrived
    bytes.
    """

    def __init__(
        self,
        transport=None,
        membership_fn: Callable[[], Membership] = None,  # type: ignore[assignment]
        *,
        name: str = "outer",
        h: Optional[int] = None,
        max_lag: Optional[int] = None,
        is_leader: bool = True,
        intra=None,
        transport_fn: Optional[Callable[[], Any]] = None,
        intra_fn: Optional[Callable[[], Any]] = None,
    ):
        if membership_fn is None:
            raise TypeError("AsyncPlane requires a membership_fn")
        if transport is None and transport_fn is None and is_leader:
            raise TypeError(
                "AsyncPlane: a leader needs a transport (or transport_fn)"
            )
        if not is_leader and intra is None and intra_fn is None:
            raise TypeError(
                "AsyncPlane: a non-leader needs an intra channel (intra "
                "or intra_fn) — it applies the leader's fold bytes "
                "instead of polling the DCN streams"
            )
        # transport_fn/intra_fn re-resolve per membership refresh — a
        # recovery reconfiguration rebuilds the sender at the bumped
        # generation (ProcessGroupCGX.async_sender), and a plane holding
        # the STOPPED pre-recovery sender by value would resurrect its
        # thread under the dead generation's key namespace.
        self._transport_fn = transport_fn
        self._transport = (
            transport if transport is not None
            else (transport_fn() if transport_fn is not None else None)
        )
        self._intra_fn = intra_fn
        self._intra = intra if intra is not None else (
            intra_fn() if intra_fn is not None else None
        )
        self._membership_fn = membership_fn
        self.name = name
        self._h_arg = h
        self._max_lag_arg = max_lag
        # Only a slice's leader POSTS its delta (one writer per stream)
        # and FOLDS peer rounds (arrival instants differ across slice
        # members, so independent folding would diverge them); with an
        # ``intra`` channel wired, non-leaders apply the leader's exact
        # fold bytes instead (the two-level leader scheme applied to the
        # outer loop). Without one (single-rank slices — the JAX plane,
        # tests) every rank is its own leader.
        self.is_leader = bool(is_leader)
        self.membership = membership_fn()
        self.state: Optional[Dict[str, Any]] = None
        self._membership_stale = False
        self._auto_decision: Optional[Tuple[str, int]] = None
        # Reproducibility probe: crc32 of the FIRST posted wire frame —
        # deterministic under a fixed seed (round 0's delta precedes any
        # fold), so repeated runs must agree byte-for-byte (the
        # bench.py --async-dcn acceptance check reads it).
        self.first_delta_crc: Optional[int] = None
        with _PLANES_LOCK:
            _PLANES.add(self)

    # -- knobs -------------------------------------------------------------

    def delta_config(self) -> wire_edges.EdgeConfig:
        """The xslice_delta edge's wire treatment: a registered
        ``(xslice_delta, pattern)`` entry wins, then ``CGX_WIRE_BITS``,
        then the plane's own aggressive default
        (``DEFAULT_ASYNC_DELTA_BITS`` with error feedback on — deltas
        cross the slowest fabric, and EF carries the coarse-width
        residual forward)."""
        ec = wire_edges.resolve_edge(wire_edges.EDGE_XSLICE_DELTA, self.name)
        if ec is None:
            ec = wire_edges.EdgeConfig(
                cc=CompressionConfig(
                    bits=cfg_mod.DEFAULT_ASYNC_DELTA_BITS, bucket_size=0
                ),
                error_feedback=True,
            ).resolved()
        return ec

    def max_lag(self) -> int:
        return (
            self._max_lag_arg if self._max_lag_arg is not None
            else cfg_mod.async_max_lag()
        )

    def h(self, numel: Optional[int] = None) -> int:
        """Inner steps per outer round: explicit > ``CGX_ASYNC_H`` >
        planner cost curves (auto) > ``DEFAULT_ASYNC_H``."""
        if self._h_arg:
            return max(1, int(self._h_arg))
        env_h = cfg_mod.async_h()
        if env_h:
            return env_h
        decision = self._planner_decision(numel)
        if decision is not None:
            return max(1, decision[1])
        return cfg_mod.DEFAULT_ASYNC_H

    def engaged(self, numel: Optional[int] = None) -> bool:
        """"on" engages; "auto" defers to the planner's sync-vs-async
        cost curves (inert when the planner itself is off — the
        CGX_SCHEDULE gate discipline); "off" never."""
        mode = cfg_mod.async_mode()
        if mode == "off":
            return False
        if self.membership.n_slices <= 1:
            return False  # nothing crosses DCN
        if mode == "on":
            return True
        decision = self._planner_decision(numel)
        return decision is not None and decision[0] == "async"

    def _planner_decision(
        self, numel: Optional[int]
    ) -> Optional[Tuple[str, int]]:
        """(route, H) from the planner's cost curves, memoized per plane
        (the planner's own cache keys carry the model fingerprint; this
        memo only avoids re-solving every inner step). None when the
        planner is not engaged or the payload is still unknown."""
        if self._auto_decision is not None:
            return self._auto_decision
        if numel is None:
            return None
        from . import planner as planner_mod

        if not planner_mod.engaged():
            # Memoized too: "auto without the planner" is inert, and an
            # unmemoized None would make wants_params hand the full
            # device→host param flatten to maybe_outer_step EVERY step
            # just to re-learn it. A mid-run CGX_PLANNER flip re-solves
            # through reset_planes (membership refresh clears the memo).
            self._auto_decision = ("sync", cfg_mod.DEFAULT_ASYNC_H)
            return self._auto_decision
        cc = self.delta_config().cc
        route, h_best = planner_mod.async_route(
            int(numel), self.membership.n_slices, cc.bits, cc.bucket_size
        )
        self._auto_decision = (route, h_best)
        return self._auto_decision

    # -- membership lifecycle ---------------------------------------------

    def mark_membership_stale(self) -> None:
        self._membership_stale = True

    def _refresh_membership(self) -> None:
        """Re-derive slice membership from the CURRENT survivor list at
        the bumped generation (the regression fix: the cached membership
        could name an evicted rank as cross-slice leader). Peer round
        bookkeeping restarts — post-recovery rounds are a new stream,
        the same contract as the qerr-cadence reset — while anchor, EF
        and momentum survive (they are training state, not derived
        bookkeeping)."""
        new = self._membership_fn()
        old = self.membership
        self.membership = new
        self._membership_stale = False
        self._auto_decision = None  # topology changed: re-solve the route
        # Re-resolve the transports: the group rebuilt its sender (and
        # intra channel) at the bumped generation; the pre-recovery
        # objects are stopped and namespace-dead.
        if self._transport_fn is not None:
            self._transport = self._transport_fn()
        if self._intra_fn is not None:
            self._intra = self._intra_fn()
        if self.state is not None:
            self.state["generation"] = int(new.generation)
            # Fresh streams accept EVERY round (applied = -1): without a
            # rendezvous-agreed replay point (CGX_SNAPSHOT_EVERY=0) a
            # slower survivor legitimately resumes at an earlier round,
            # and a caught-up baseline would silently drop its deltas as
            # stale forever. The staleness CLOCK is floored at the
            # re-derivation round instead (lag_floor): it measures only
            # post-recovery lag, never the rounds the dead generation's
            # stream carried — so neither a spurious trip nor a dropped
            # contribution.
            self.state["applied"] = {
                int(p): -1
                for p in range(new.n_slices)
                if p != new.slice_idx
            }
            self.state["lag_floor"] = int(self.state["round"])
            self.state["pending"] = {}
        metrics.add("cgx.async.membership_rederived")
        flightrec.record(
            "async_membership",
            generation=new.generation,
            n_slices=new.n_slices,
            slice_idx=new.slice_idx,
            leaders=list(new.leaders),
            was=list(old.leaders),
        )

    # -- snapshot / replay (rung-4 substrate) ------------------------------

    def export_state(self) -> Optional[Dict[str, Any]]:
        """Deep host copy of the outer state (include it in the tree the
        supervisor snapshots — replay then restores inner params AND
        outer EF/momentum bit-identically)."""
        return copy.deepcopy(self.state)

    def restore_state(self, state: Optional[Dict[str, Any]]) -> None:
        self.state = copy.deepcopy(state)

    # -- the outer loop ----------------------------------------------------

    def wants_params(self, step_idx: int) -> bool:
        """Cheap pre-gate for the train-step hook: whether
        :meth:`maybe_outer_step` would do anything with the params this
        step. False lets the caller skip the device→host flatten
        entirely (a no-op boundary check must not cost a full param
        copy per step). Drains the transport on engaged non-boundary
        steps as a side effect (drain needs no params)."""
        if cfg_mod.async_mode() == "off":
            return False
        if self.membership.n_slices <= 1:
            return False
        if cfg_mod.async_mode() == "auto" and self._auto_decision is None:
            return True  # the route solve needs the payload size once
        if not self.engaged(None):
            return False
        if (int(step_idx) + 1) % self.h(None) != 0:
            if self.state is not None:
                self._drain()
            return False
        return True

    def maybe_outer_step(self, step_idx: int, flat_params: np.ndarray):
        """Drive the outer loop from the training loop, host-side: on a
        non-boundary step this drains the transport into the pending
        buffer and returns the params UNCHANGED (and with the plane
        disengaged it is a pure identity — the knob-unset inertness
        pin); on a boundary it runs :meth:`outer_round`. Never blocks:
        the post is an enqueue, the poll reads only published bytes."""
        flat = np.asarray(flat_params, np.float32).reshape(-1)
        if not self.engaged(flat.size):
            return flat_params
        if self._membership_stale:
            self._refresh_membership()
        if self.state is None:
            self.state = init_outer_state(flat, self.membership)
        if (int(step_idx) + 1) % self.h(flat.size) != 0:
            self._drain()
            return flat_params
        return self.outer_round(flat)

    def _drain(self) -> None:
        """Fold transport arrivals into the pending buffer (decode
        deferred to the boundary — the arrival order across peers is
        nondeterministic, the boundary fold order is not). Leaders only:
        with an intra channel wired, non-leaders never touch the DCN
        streams (they apply the leader's fold bytes instead, and the
        streams' reader refcounts are sized for one consumer per peer
        slice)."""
        st = self.state
        assert st is not None
        if self._intra is not None and not self.is_leader:
            return
        for peer, round_idx, payload in self._transport.poll():
            if peer == self.membership.slice_idx:
                continue
            if peer not in st["applied"]:
                # a post-eviction stream re-derivation dropped this peer
                metrics.add("cgx.async.stale_drops")
                continue
            st["pending"].setdefault(int(peer), []).append(
                (int(round_idx), np.frombuffer(bytes(payload), np.uint8))
            )

    def _decode(self, buf: np.ndarray, n: int, cc: CompressionConfig):
        q = codec_host.from_bytes(
            buf, n, cc.bits, max(1, cc.bucket_size), np.float32,
            skip_incomplete=cc.skip_incomplete_buckets,
        )
        return codec_host.dequantize(q, out_dtype=np.float32)

    def outer_round(self, flat: np.ndarray) -> np.ndarray:
        """One outer boundary: post this slice's compressed delta
        (non-blocking), fold every arrived round through the outer
        optimizer, enforce the staleness bound, and return the merged
        anchor as the new inner params."""
        st = self.state
        assert st is not None
        mem = self.membership
        if not self.is_leader:
            if self._intra is None:
                raise RuntimeError(
                    "AsyncPlane: non-leader has no intra channel "
                    "(intra_fn returned None?) — a follower applies the "
                    "leader's fold bytes, it cannot run the fold itself"
                )
            return self._outer_round_follower(st)
        cc = self.delta_config().cc
        use_ef = self.delta_config().error_feedback
        delta = flat - st["anchor"]
        d_eff = delta + st["ef"] if use_ef else delta
        q = codec_host.quantize(
            d_eff, cc.bits, max(1, cc.bucket_size),
            skip_incomplete_buckets=cc.skip_incomplete_buckets,
        )
        wire = q.to_bytes()
        decoded = codec_host.dequantize(q, out_dtype=np.float32)
        if use_ef:
            st["ef"] = d_eff - decoded
        wire_b_bytes = wire.tobytes()
        if self.first_delta_crc is None:
            import zlib

            self.first_delta_crc = zlib.crc32(wire_b_bytes)
        # the decoded (not raw) delta is what every peer folds — folding
        # it locally too keeps all slices' anchors bit-identical; only
        # the slice leader posts (one writer per stream)
        if self.is_leader:
            self._transport.post(st["round"], wire_b_bytes)
        raw_b, wire_b = 4.0 * d_eff.size, float(wire.nbytes)
        metrics.add(
            f"cgx.wire.bytes_raw.{wire_edges.EDGE_XSLICE_DELTA}", raw_b
        )
        metrics.add(
            f"cgx.wire.bytes_wire.{wire_edges.EDGE_XSLICE_DELTA}", wire_b
        )
        self._drain()
        # -- fold: own decoded + every arrived peer round <= ours, in
        # deterministic (peer, round) order, each scaled 1/n_slices
        scale = np.float32(1.0 / mem.n_slices)
        agg = decoded * scale
        applied_rounds = 0
        for peer in sorted(st["pending"]):
            rounds = sorted(st["pending"][peer], key=lambda rv: rv[0])
            keep: List[Tuple[int, np.ndarray]] = []
            for r, buf in rounds:
                if r > st["round"]:
                    keep.append((r, buf))  # from a future boundary
                    continue
                if r <= st["applied"].get(peer, -1):
                    metrics.add("cgx.async.stale_drops")
                    continue
                agg += self._decode(buf, flat.size, cc) * scale
                st["applied"][peer] = r
                applied_rounds += 1
            if keep:
                st["pending"][peer] = keep
            else:
                st["pending"].pop(peer, None)
        update, st["momentum"] = outer_update(
            agg, st["momentum"],
            kind=cfg_mod.async_outer(),
            lr=cfg_mod.async_outer_lr(),
            mu=cfg_mod.async_outer_momentum(),
        )
        st["anchor"] = st["anchor"] + update
        this_round = st["round"]
        st["round"] = this_round + 1
        if self._intra is not None:
            # Two-level leader scheme, outer edition: the slice's
            # non-leaders apply these exact bytes — independent folding
            # would diverge slice members, since peer rounds reach each
            # rank's poll at different instants. Published BEFORE the
            # staleness check so a tripping boundary still leaves the
            # slice internally consistent.
            self._intra.publish(
                this_round, update.astype(np.float32, copy=False).tobytes()
            )
        # -- staleness bookkeeping + the bounded-staleness gate
        max_lag = self.max_lag()
        lag_floor = int(st.get("lag_floor", 0))
        worst_lag, worst_peer = 0, None
        for peer, last in sorted(st["applied"].items()):
            lag = this_round - max(last, lag_floor - 1)
            if lag > worst_lag:
                worst_lag, worst_peer = lag, peer
            health_mod.note_async_lag(
                mem.global_ranks[peer] if peer < len(mem.global_ranks)
                else None,
                lag, float(max_lag),
            )
        metrics.set("cgx.async.lag_rounds", float(worst_lag))
        metrics.add("cgx.async.rounds")
        if worst_lag <= 1:
            metrics.add("cgx.async.rounds_on_time")
        metrics.add("cgx.async.rounds_folded", float(applied_rounds))
        flightrec.record(
            "async_round",
            round=this_round,
            generation=st["generation"],
            folded=applied_rounds,
            lag=worst_lag,
            wire_bytes=int(wire_b),
            bits=cc.bits,
        )
        if worst_lag > max_lag and worst_peer is not None:
            suspect_local = (
                mem.leaders[worst_peer]
                if worst_peer < len(mem.leaders) else worst_peer
            )
            raise AsyncStalenessError(
                f"async cross-slice plane: slice {worst_peer} (leader "
                f"group-local rank {suspect_local}) is {worst_lag} outer "
                f"rounds behind round {this_round} "
                f"(CGX_ASYNC_MAX_LAG={max_lag}, generation "
                f"{st['generation']}) — its deltas stopped arriving",
                suspects=[suspect_local],
                lag=worst_lag,
                round=this_round,
            )
        return st["anchor"].copy()

    def _outer_round_follower(self, st: Dict[str, Any]) -> np.ndarray:
        """Non-leader boundary with an intra channel: apply the leader's
        round fold byte-for-byte. The wait is intra-slice (the fast
        tier — the same fabric the sync intra stage blocks on every
        step), bounded, and raises ``BridgeTimeoutError`` into the
        recovery ladder if the leader died or raised mid-boundary."""
        this_round = st["round"]
        buf = self._intra.fetch(this_round)
        update = np.frombuffer(buf, np.float32)
        if update.size != st["anchor"].size:
            raise RuntimeError(
                f"async intra broadcast: round {this_round} update has "
                f"{update.size} elements, anchor has {st['anchor'].size} "
                "— slice members disagree on the flattened param layout"
            )
        st["anchor"] = st["anchor"] + update
        st["round"] = this_round + 1
        # deliberately NOT cgx.async.rounds: that counter (and its
        # rounds_on_time companion) is leader-only, so the summed
        # on-time rate in cgx_report/cgx_top is not deflated by the
        # slice fan-out; intra_fetched already ledgers follower rounds
        return st["anchor"].copy()


# ---------------------------------------------------------------------------
# Pytree front door (the make_train_step outer hook's flatten/unflatten).
# ---------------------------------------------------------------------------


def flatten_tree(tree) -> Tuple[np.ndarray, Callable[[np.ndarray], Any]]:
    """(flat f32 host vector, unflatten) for a params pytree — the
    plane's fused-buffer view. Unflatten restores leaf shapes/dtypes and
    the original tree structure (values come back as numpy; the caller's
    jit re-places them on device)."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    arrs = [np.asarray(l, np.float32).reshape(-1) for l in leaves]
    flat = (
        np.concatenate(arrs) if arrs else np.zeros((0,), np.float32)
    )
    shapes = [np.shape(l) for l in leaves]
    dtypes = [np.asarray(l).dtype for l in leaves]
    sizes = [a.size for a in arrs]

    def unflatten(v: np.ndarray):
        out, off = [], 0
        for shape, dtype, size in zip(shapes, dtypes, sizes):
            out.append(
                np.asarray(v[off:off + size], np.float32)
                .reshape(shape).astype(dtype)
            )
            off += size
        return jax.tree_util.tree_unflatten(treedef, out)

    return flat, unflatten
