"""Mixture-of-Experts with expert parallelism (EP).

The reference has no MoE/EP (SURVEY.md §2.3: "TP / PP / EP: absent") — this
subsystem is designed fresh for TPU rather than ported. GShard-style dense
dispatch, shaped for the MXU and for GSPMD expert parallelism:

* :class:`MoEMlp` — drop-in replacement for the dense ``Mlp`` block: top-k
  softmax router, capacity-bounded one-hot dispatch (no dynamic shapes —
  token->slot assignment is a cumsum over one-hots, overflowing tokens are
  dropped and ride the residual connection), per-expert FFN as batched
  einsums over a leading expert dimension.
* **EP sharding**: every tensor with a leading expert axis gets a
  ``with_sharding_constraint`` on the ``ep`` mesh axis (when configured);
  expert weights shard via :func:`moe_param_spec`. XLA/GSPMD then inserts
  the dispatch/combine ``all_to_all`` pair over ICI — the explicit-MPI
  equivalent the reference would have needed is exactly what SURVEY.md §7
  says should collapse into the compiler.
* **Load-balance auxiliary loss** (Switch-Transformer form) is sown under
  ``intermediates/moe_aux_loss``; collect with :func:`aux_loss`.

Composes with the quantized gradient allreduce: expert weights are regular
pytree leaves, so per-layer compression configs apply (pattern
``.*experts.*`` etc.).
"""

from __future__ import annotations

from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..utils import compat
from ..wire import dispatch as wire_dispatch
from ..wire.edges import EDGE_MOE_A2A


_warned_constraint = False


def ep_dispatch(exp_in: jax.Array, axis_name: str, *, name: str = "moe.dispatch"):
    """Explicit expert-parallel dispatch ``all_to_all`` through the wire
    dispatcher (the ``moe_a2a`` edge) — for MoE layers running INSIDE
    ``shard_map`` over ``axis_name`` (the GSPMD path in :class:`MoEMlp`
    instead lets the compiler insert the collective, which the edge
    registry cannot see).

    ``exp_in`` is this device's dense dispatch buffer ``(E, C, D)``
    (every expert's slots, local tokens). Returns ``(E/ws, ws*C, D)``:
    this device's experts' slots, gathered from every rank. Raw unless a
    ``moe_a2a`` edge config resolves; with one, the payload rides the
    quantized wire (packed bit-planes + per-slice meta, STE backward).
    Requires ``E % ws == 0``."""
    ws = compat.axis_size(axis_name)
    if exp_in.shape[0] % ws:
        raise ValueError(
            f"ep_dispatch: expert dim {exp_in.shape[0]} not divisible by "
            f"axis size {ws}"
        )
    return wire_dispatch.wire_all_to_all(
        exp_in, axis_name, split_axis=0, concat_axis=1,
        kind=EDGE_MOE_A2A, name=name,
    )


def ep_combine(exp_out: jax.Array, axis_name: str, *, name: str = "moe.combine"):
    """Inverse of :func:`ep_dispatch`: ``(E/ws, ws*C, D)`` expert outputs
    back to the token-owning ranks as ``(E, C, D)`` — the combine
    ``all_to_all``, same ``moe_a2a`` edge surface."""
    return wire_dispatch.wire_all_to_all(
        exp_out, axis_name, split_axis=1, concat_axis=0,
        kind=EDGE_MOE_A2A, name=name,
    )


def _capacity(tokens: int, n_experts: int, top_k: int, factor: float) -> int:
    return max(1, int(np.ceil(tokens * top_k * factor / n_experts)))


class MoEMlp(nn.Module):
    """Top-k routed expert FFN.

    Shapes: x (B, S, D) -> (B, S, D); experts hold (E, D, F) / (E, F, D)
    kernels with F = ratio * d_model.
    """

    d_model: int
    n_experts: int
    top_k: int = 2
    capacity_factor: float = 1.25
    ratio: int = 4
    dtype: Any = jnp.bfloat16
    ep_axis: Optional[str] = None  # mesh axis to shard the expert dim over

    def _constrain(self, t, spec):
        if self.ep_axis is None:
            return t
        try:
            return jax.lax.with_sharding_constraint(t, spec)
        except (ValueError, RuntimeError) as e:
            # No mesh context (eager / plain jit without set_mesh) or a bad
            # axis name: EP degrades to replicated experts. Never silent —
            # on a real pod that is an OOM/perf cliff.
            global _warned_constraint
            if not _warned_constraint:
                _warned_constraint = True
                from ..utils.logging import get_logger

                get_logger().warning(
                    "MoE EP sharding constraint %s not applied (%s); experts "
                    "will be REPLICATED. Run under `with jax.set_mesh(mesh):`"
                    " with an %r mesh axis to shard them.",
                    spec, e, self.ep_axis,
                )
            return t

    @nn.compact
    def __call__(self, x, train: bool = True):
        b, s, d = x.shape
        e, k = self.n_experts, self.top_k
        if not 1 <= k <= e:
            raise ValueError(
                f"top_k={k} must be in [1, n_experts={e}]"
            )
        f = self.ratio * self.d_model
        t = b * s
        cap = _capacity(t, e, k, self.capacity_factor)
        ep = self.ep_axis

        xt = x.reshape(t, d)
        # Router in f32 (tiny matmul; numerics matter more than speed).
        router = self.param(
            "router", nn.initializers.lecun_normal(), (d, e), jnp.float32
        )
        logits = xt.astype(jnp.float32) @ router  # (T, E)
        probs = jax.nn.softmax(logits, axis=-1)

        # Top-k gates: iteratively take the argmax, mask, renormalize the
        # selected gates to sum to 1 per token (GShard convention).
        masked = probs
        sel_onehots, sel_gates = [], []
        for _ in range(k):
            idx = jnp.argmax(masked, axis=-1)  # (T,)
            oh = jax.nn.one_hot(idx, e, dtype=jnp.float32)  # (T, E)
            sel_onehots.append(oh)
            sel_gates.append(jnp.sum(probs * oh, axis=-1))  # (T,)
            masked = masked * (1.0 - oh)
        denom = sum(sel_gates) + 1e-9

        # Load-balance aux loss (Switch form): E * sum_e fraction_e * prob_e,
        # computed on the top-1 assignment.
        frac = jnp.mean(sel_onehots[0], axis=0)
        mean_prob = jnp.mean(probs, axis=0)
        self.sow(
            "intermediates", "moe_aux_loss",
            jnp.asarray(e, jnp.float32) * jnp.sum(frac * mean_prob),
        )

        # Capacity-bounded slot assignment: position of each token within
        # its expert's queue = exclusive cumsum of the choice one-hots (the
        # k-th choice queues behind all first choices, etc.).
        dispatch = jnp.zeros((t, e, cap), jnp.float32)
        combine = jnp.zeros((t, e, cap), jnp.float32)
        slots_used = jnp.zeros((e,), jnp.float32)
        for i in range(k):
            oh = sel_onehots[i]
            pos = (jnp.cumsum(oh, axis=0) - oh) + slots_used[None, :]  # (T, E)
            slot = jnp.sum(pos * oh, axis=-1)  # (T,) queue position
            keep = (slot < cap).astype(jnp.float32)
            slot_oh = jax.nn.one_hot(
                jnp.minimum(slot, cap - 1).astype(jnp.int32), cap,
                dtype=jnp.float32,
            )  # (T, C)
            d_i = oh[:, :, None] * slot_oh[:, None, :] * keep[:, None, None]
            dispatch = dispatch + d_i
            gate = (sel_gates[i] / denom)[:, None, None]
            combine = combine + gate * d_i
            slots_used = slots_used + jnp.sum(oh, axis=0)

        # Dispatch tokens to expert slots: (E, C, D) — the all_to_all
        # boundary under EP sharding.
        exp_in = jnp.einsum(
            "tec,td->ecd", dispatch.astype(self.dtype), xt.astype(self.dtype)
        )
        exp_in = self._constrain(exp_in, P(ep, None, None))

        w_in = self.param(
            "experts_in",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, d, f), jnp.float32,
        ).astype(self.dtype)
        b_in = self.param(
            "experts_in_bias", nn.initializers.zeros, (e, f), jnp.float32
        ).astype(self.dtype)
        w_out = self.param(
            "experts_out",
            nn.initializers.lecun_normal(batch_axis=(0,)),
            (e, f, d), jnp.float32,
        ).astype(self.dtype)
        b_out = self.param(
            "experts_out_bias", nn.initializers.zeros, (e, d), jnp.float32
        ).astype(self.dtype)

        h = jnp.einsum("ecd,edf->ecf", exp_in, w_in) + b_in[:, None, :]
        h = self._constrain(h, P(ep, None, None))
        h = nn.gelu(h)
        exp_out = jnp.einsum("ecf,efd->ecd", h, w_out) + b_out[:, None, :]
        exp_out = self._constrain(exp_out, P(ep, None, None))

        y = jnp.einsum(
            "tec,ecd->td", combine.astype(self.dtype), exp_out
        )
        return y.reshape(b, s, d)


def moe_param_spec(path: str, leaf, axis: str = "ep") -> Optional[P]:
    """EP PartitionSpec for MoE params: shard the leading expert dim of
    ``experts_*`` kernels/biases over ``axis``; router replicated. Returns
    None for non-MoE params (caller falls through to its other rules)."""
    if "experts" in path:
        return P(*((axis,) + (None,) * (leaf.ndim - 1)))
    if path.endswith("router"):
        return P()
    return None


def aux_loss(intermediates) -> jax.Array:
    """Sum all sown ``moe_aux_loss`` values (0 when no MoE layers ran)."""
    total = jnp.asarray(0.0, jnp.float32)
    for path, leaves in jax.tree_util.tree_flatten_with_path(intermediates)[0]:
        if "moe_aux_loss" in jax.tree_util.keystr(path):
            total = total + jnp.sum(leaves)
    return total
